"""Benchmark driver. Default: ResNet-50 / CIFAR-10 training throughput
(BASELINE.json config 1). ``BENCH_MODEL=llama`` benches the flagship
Llama train step (tokens/sec).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} (plus
``backend``, and ``error``/``note`` when degraded). ``vs_baseline`` is
null — the reference mount is empty and BASELINE.json records no
published numbers (SURVEY.md §6); this run IS the baseline.

Robustness contract (round-2 hardening, see VERDICT.md item 1): round 1
recorded rc=1 because the ambient TPU plugin failed/hung jax backend
init *before any benchmark code ran*. This file is now an orchestrator:
it probes backend availability in a throwaway subprocess under a
timeout, runs the actual benchmark in a child process (``BENCH_CHILD=1``
re-entry), retries once on TPU, falls back to a sanitized CPU
environment, and ALWAYS emits its JSON line — a wedged TPU yields a CPU
number with a note, never an empty record.

``BENCH_AMP=1`` (default on TPU) uses the reference's AMP-O2 recipe
mapped to TPU: fp32 master params, bf16 compute — the MXU's native dtype.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time


def _amp_enabled():
    import jax
    plat = jax.devices()[0].platform.lower()
    default = "1" if plat in ("tpu", "axon") else "0"
    return os.environ.get("BENCH_AMP", default) == "1"


def _loader_batches(batch, image_shape=(3, 32, 32), min_workers=0):
    """Config-1's input path as specified: CIFAR-10 (local cache) or the
    deterministic FakeData stand-in (zero-egress), through
    ``paddle.io.DataLoader`` with worker processes + C++ shm queue +
    prefetch (reference ``buffered_reader.cc`` double buffering).
    Returns ``(workers, generator)``; the generator yields forever and
    callers bound consumption themselves. ``workers`` goes into the
    emitted JSON so 0-worker and 4-worker records are distinguishable."""
    from paddle_tpu.io import DataLoader
    from paddle_tpu.vision.datasets import Cifar10, FakeData
    # Default worker count depends on where COMPUTE runs. On an
    # accelerator the host idles during device steps, so workers overlap
    # with compute even on a 1-core host — keep the reference's 4-worker
    # shape. With CPU compute, workers STEAL the training process's
    # cores (the round-4 loader-fed collapse: 11.77 vs 24.3 img/s was
    # contention, not pipeline cost — the loader itself runs at ~21k
    # img/s on this host); spawn only what spare cores allow. Cores =
    # the scheduling affinity mask (cgroup/cpuset aware), not the
    # machine's nominal count. ``min_workers`` lets the goodput bench
    # keep the worker+shm transport it exists to measure.
    import jax as _jax
    try:
        n_cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        n_cores = os.cpu_count() or 1
    if _jax.default_backend() == "cpu":
        default_workers = min(4, max(min_workers, n_cores - 1))
    else:
        default_workers = 4
    workers = int(os.environ.get("BENCH_WORKERS", str(default_workers)))
    ds = None
    if tuple(image_shape) == (3, 32, 32):   # CIFAR only at its own shape
        try:
            ds = Cifar10(mode="train")
        except Exception:
            ds = None
    if ds is None:
        ds = FakeData(size=max(2048, batch * 4), image_shape=image_shape)
    loader = DataLoader(ds, batch_size=batch, shuffle=True, drop_last=True,
                        num_workers=workers, use_shared_memory=True,
                        prefetch_factor=2)

    def gen():
        while True:
            for xb, yb in loader:
                yield xb, yb

    return workers, gen()


def bench_resnet():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.vision.models import resnet50
    from paddle_tpu.framework.functional import FunctionalModule

    batch = int(os.environ.get("BENCH_BATCH", "256"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    amp = _amp_enabled()
    # BENCH_DATA=loader feeds real batches through the DataLoader stack
    # (worker procs + shm queue + prefetch) instead of a constant array —
    # config 1 as specified in BASELINE.json
    use_loader = os.environ.get("BENCH_DATA", "synthetic") == "loader"

    paddle.seed(0)
    model = resnet50(num_classes=10)
    model.train()
    fm = FunctionalModule(model, training=True)
    p_arrs = fm.param_arrays()
    b_arrs = fm.buffer_arrays()
    key = fm.next_key()

    x = jnp.ones((batch, 3, 32, 32),
                 jnp.bfloat16 if amp else jnp.float32)
    y = jnp.zeros((batch,), jnp.int32)

    def _loss_fn(ps, b_arrs, key, x, y):
        cps = [a.astype(jnp.bfloat16) if amp and a.dtype == jnp.float32
               else a for a in ps]
        logits, new_b = fm(cps, b_arrs, key, x)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        loss = -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()
        return loss, new_b

    def train_step(p_arrs, b_arrs, key, x, y):
        def loss_fn(ps):
            return _loss_fn(ps, b_arrs, key, x, y)

        (loss, new_b), grads = jax.value_and_grad(loss_fn, has_aux=True)(p_arrs)
        new_p = [p - 0.05 * g.astype(p.dtype) for p, g in zip(p_arrs, grads)]
        return loss, new_p, new_b

    step = jax.jit(train_step, donate_argnums=(0, 1))
    loss, p_arrs, b_arrs = step(p_arrs, b_arrs, key, x, y)   # compile
    loss.block_until_ready()

    comp_dtype = x.dtype
    n_workers = None
    if use_loader:
        import numpy as np
        n_workers, batches = _loader_batches(batch)

        def feed():
            xb, yb = next(batches)
            return (jnp.asarray(np.asarray(xb.numpy()), comp_dtype),
                    jnp.asarray(np.asarray(yb.numpy()).reshape(-1),
                                jnp.int32))
        x, y = feed()                                        # warm loader
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, p_arrs, b_arrs = step(p_arrs, b_arrs, key, x, y)
            x, y = feed()          # overlaps with the async device step
        loss.block_until_ready()
        dt = time.perf_counter() - t0
    else:
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, p_arrs, b_arrs = step(p_arrs, b_arrs, key, x, y)
        loss.block_until_ready()
        dt = time.perf_counter() - t0
    out = {
        "metric": ("resnet50_cifar10_train_throughput_loader" if use_loader
                   else "resnet50_cifar10_train_throughput"),
        "value": round(batch * steps / dt, 2),
        "unit": "images/sec",
        "vs_baseline": None,
    }
    if n_workers is not None:
        out["workers"] = n_workers
    if not use_loader:
        # step donates (p, b): thread them through the probe's closure
        st = [p_arrs, b_arrs]

        def _probe_step():
            loss, st[0], st[1] = step(st[0], st[1], key, x, y)
            return loss

        out["telemetry_overhead_pct"] = _telemetry_overhead_pct(
            _probe_step, lambda r: r.block_until_ready(),
            steps=min(steps, 10))
        p_arrs, b_arrs = st
    # -- training observatory (ISSUE 12): memory peak, phase split,
    # numerics-sentinel cost — the first training-side memory/phase
    # entries in the bench trajectory
    out["train_peak_bytes"] = _train_peak_bytes()
    if os.environ.get("BENCH_PHASES", "1") == "1":
        def _fwd(ps):
            return _loss_fn(ps, b_arrs, key, x, y)[0]

        def _grads(ps):
            return jax.value_and_grad(
                lambda q: _loss_fn(q, b_arrs, key, x, y)[0])(ps)[1]

        def _opt(ps, gs):
            return [p - 0.05 * g.astype(p.dtype) for p, g in zip(ps, gs)]

        out["train_phase_breakdown"] = _phase_breakdown_probe(
            p_arrs, _fwd, _grads, _opt)
    out["numerics_overhead_pct"] = _numerics_overhead_pct()
    out["ledger_overhead_pct"] = _ledger_overhead_pct()
    out["compile_observatory_overhead_pct"] = \
        _compile_observatory_overhead_pct()
    _emit_observatory_aux(out)
    return out


def _telemetry_overhead_pct(run_step, sync, steps=10, instrumented_step=None,
                            setup=None, teardown=None):
    """Cost of the observability layer itself, measured in-situ: the same
    jitted step with the full per-step telemetry surface in the loop
    (span begin/end + step-time histogram + counter + gauge) vs bare.
    Emitted with every resnet bench so a regression in the telemetry hot
    path shows up as a perf delta, not as silent slow training.

    ``instrumented_step`` overrides the default full-telemetry step —
    callers (the flight-recorder overhead guard) time their own
    instrumentation surface against the same bare loop; ``setup`` /
    ``teardown`` bracket the instrumented timing window."""
    if instrumented_step is None:
        from paddle_tpu.profiler.telemetry import get_registry, get_tracer

        reg = get_registry()
        hist = reg.histogram("bench_step_seconds", "bench overhead probe")
        ctr = reg.counter("bench_steps_total", "bench overhead probe")
        gauge = reg.gauge("bench_last_step_seconds", "bench overhead probe")
        tracer = get_tracer()

        def instrumented_step():
            sp = tracer.begin("bench_step")
            t1 = time.perf_counter()
            r = run_step()
            d = time.perf_counter() - t1
            tracer.end(sp)
            hist.observe(d)
            ctr.inc()
            gauge.set(d)
            return r

        setup = tracer.enable

        def teardown():
            tracer.disable()
            tracer.drain()             # don't leak probe spans to exports

    def timed(fn):
        t0 = time.perf_counter()
        r = None
        for _ in range(steps):
            r = fn()
        sync(r)
        return time.perf_counter() - t0

    timed(run_step)                    # warm both paths
    t_plain = timed(run_step)
    if setup is not None:
        setup()
    try:
        t_instr = timed(instrumented_step)
    finally:
        if teardown is not None:
            teardown()
    return round((t_instr - t_plain) / max(t_plain, 1e-9) * 100, 3)


def _train_peak_bytes():
    """Peak device bytes of the training run so far (PJRT allocator
    lifetime peak; 0 on backends without allocator stats)."""
    try:
        from paddle_tpu.device.memory import max_memory_allocated
        return int(max_memory_allocated())
    except Exception:
        return 0


def _phase_breakdown_probe(p_arrs, fwd_fn, grads_fn, opt_fn, steps=None):
    """Split-timed step-phase decomposition of a jitted train step:
    forward = t(loss-only program), backward = t(loss+grads) - forward,
    optimizer = t(update-only program); comm_wait is 0 on one chip. The
    measured durations are ALSO recorded through
    ``profiler.step_phase`` so the ``paddle_step_phase_seconds``
    histogram and ``cost_table()['phases']`` carry the same numbers the
    record reports. Returns {phase: fraction} plus the per-phase
    seconds under ``*_s`` keys."""
    import jax

    from paddle_tpu.profiler import step_phase

    steps = steps or int(os.environ.get("BENCH_PHASE_STEPS", "2"))

    def timed(fn, *args):
        r = fn(*args)                       # compile/warm
        jax.block_until_ready(r)
        t0 = time.perf_counter()
        for _ in range(steps):
            r = fn(*args)
        jax.block_until_ready(r)
        return (time.perf_counter() - t0) / steps, r

    was = step_phase.is_enabled()
    step_phase.enable()
    try:
        t_fwd, _ = timed(jax.jit(fwd_fn), p_arrs)
        t_fwdbwd, grads = timed(jax.jit(grads_fn), p_arrs)
        t_opt, _ = timed(jax.jit(opt_fn), p_arrs, grads)
        t_bwd = max(t_fwdbwd - t_fwd, 0.0)
        for ph, dt in (("forward", t_fwd), ("backward", t_bwd),
                       ("optimizer", t_opt)):
            step_phase.record_phase(ph, dt)
        total = max(t_fwd + t_bwd + t_opt, 1e-12)
        return {
            "forward": round(t_fwd / total, 4),
            "backward": round(t_bwd / total, 4),
            "comm_wait": 0.0,
            "optimizer": round(t_opt / total, 4),
            "forward_s": round(t_fwd, 5),
            "backward_s": round(t_bwd, 5),
            "optimizer_s": round(t_opt, 5),
        }
    finally:
        if not was:
            step_phase.disable()


def _numerics_overhead_pct():
    """Per-step cost of the numerics sentinel (grad L2/abs-max/
    nonfinite stats for every parameter, interval 1) vs sentinel-off,
    measured on an eager 2-layer MLP train step — the sentinel
    instruments the eager tape's grad-ready hooks, which a jitted
    whole-step program never fires, so the eager loop IS the worst
    case."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.profiler import tensor_stats

    # sized so compute dominates the way a real model's does — the
    # sentinel's per-param cost is fixed, so a toy step would report
    # a uselessly inflated percentage
    net = nn.Sequential(nn.Linear(256, 256), nn.Tanh(),
                        nn.Linear(256, 64))
    opt = paddle.optimizer.SGD(learning_rate=0.01,
                               parameters=net.parameters())
    x = paddle.to_tensor(np.random.default_rng(0)
                         .normal(size=(64, 256)).astype(np.float32))

    def step():
        loss = (net(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    def setup():
        tensor_stats.enable(interval=1, mode="warn")

    def teardown():
        tensor_stats.disable()
        tensor_stats.reset()

    return _telemetry_overhead_pct(step, lambda r: None, steps=10,
                                   instrumented_step=step,
                                   setup=setup, teardown=teardown)


def _ledger_overhead_pct():
    """Per-step cost of the determinism ledger (sha1 param/grad digests
    at every optimizer step, interval 1, warn mode) vs ledger-off, on
    the same eager MLP step the numerics-sentinel probe uses — the
    digest path pulls every parameter and gradient to host, so the
    eager loop is the honest worst case for the sensing layer."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.profiler import ledger

    net = nn.Sequential(nn.Linear(256, 256), nn.Tanh(),
                        nn.Linear(256, 64))
    opt = paddle.optimizer.SGD(learning_rate=0.01,
                               parameters=net.parameters())
    x = paddle.to_tensor(np.random.default_rng(0)
                         .normal(size=(64, 256)).astype(np.float32))

    def step():
        loss = (net(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    def setup():
        ledger.enable(mode="warn", interval=1)

    def teardown():
        ledger.disable()
        ledger.reset()

    return _telemetry_overhead_pct(step, lambda r: None, steps=10,
                                   instrumented_step=step,
                                   setup=setup, teardown=teardown)


def _compile_observatory_overhead_pct():
    """Per-call cost of the compile observatory (signature build +
    trace-cache accounting at every ``to_static`` entry) vs
    observatory-off, on a jitted MLP forward — the to_static entry
    builds a full per-leaf signature on every call when the observatory
    is on, so the cached-program hot loop is the honest worst case for
    the sensing layer. Disabled must cost one bool check."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.profiler import compile_observatory as co

    net = nn.Sequential(nn.Linear(256, 256), nn.Tanh(),
                        nn.Linear(256, 64))
    static_net = paddle.jit.to_static(net)
    x = paddle.to_tensor(np.random.default_rng(0)
                         .normal(size=(64, 256)).astype(np.float32))

    def step():
        return static_net(x)

    co.disable()                       # bare path: observatory off
    try:
        return _telemetry_overhead_pct(step, lambda r: None, steps=10,
                                       instrumented_step=step,
                                       setup=co.enable,
                                       teardown=co.disable)
    finally:
        co.reset()                     # back to the env-gated default


def _emit_observatory_aux(out):
    """stderr aux lines for the training-observatory record fields."""
    for name in ("train_peak_bytes", "numerics_overhead_pct",
                 "ledger_overhead_pct",
                 "compile_observatory_overhead_pct"):
        if name in out:
            print(json.dumps({"aux_metric": name, "value": out[name]}),
                  file=sys.stderr)
    if "train_phase_breakdown" in out:
        print(json.dumps({"aux_metric": "train_phase_breakdown",
                          **{k: v for k, v in
                             out["train_phase_breakdown"].items()
                             if not k.endswith("_s")}}), file=sys.stderr)


def bench_data():
    """Config-3 goodput: DataLoader (worker procs + C++ shm queue +
    prefetch) → HBM transfer rate on detection-sized images (reference:
    ``buffered_reader.cc`` double-buffered H2D prefetch)."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    batch = int(os.environ.get("BENCH_BATCH", "32"))
    steps = int(os.environ.get("BENCH_STEPS", "30"))
    shape = (3, int(os.environ.get("BENCH_IMG", "320")),
             int(os.environ.get("BENCH_IMG", "320")))
    # the goodput metric EXISTS to measure the worker+shm transport —
    # never let the spare-core default degrade it to single-process
    n_workers, batches = _loader_batches(batch, image_shape=shape,
                                         min_workers=2)
    dev = jax.devices()[0]

    next(batches)                                            # warm workers
    n_bytes = 0
    t0 = time.perf_counter()
    for _ in range(steps):
        xb, yb = next(batches)
        xa = jax.device_put(np.asarray(xb.numpy()), dev)
        n_bytes += xa.size * xa.dtype.itemsize
    xa.block_until_ready()
    dt = time.perf_counter() - t0
    print(json.dumps({"aux_metric": "loader_hbm_goodput",
                      "value": round(n_bytes / dt / 2**20, 2),
                      "unit": "MiB/s"}), file=sys.stderr)
    return {
        "metric": "dataloader_hbm_samples_per_sec",
        "value": round(batch * steps / dt, 2),
        "unit": "samples/sec",
        "vs_baseline": None,
        "workers": n_workers,
    }


def bench_llama():
    """Flagship single-chip Llama train-step bench (tokens/sec); exercises
    the Pallas flash-attention path + AMP master weights."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.framework.functional import FunctionalModule

    batch = int(os.environ.get("BENCH_BATCH", "4"))
    seq = int(os.environ.get("BENCH_SEQ", "1024"))
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    amp = _amp_enabled()
    # MFU sweep knobs (BENCH_REMAT=1/full -> full activation recompute per
    # layer — trades FLOPs for HBM so bigger BENCH_BATCH/BENCH_SEQ fit;
    # BENCH_REMAT=dots -> dots-saveable policy: matmul outputs kept,
    # elementwise recomputed — much cheaper recompute, the usual TPU
    # MFU-vs-memory sweet spot)
    remat_mode = os.environ.get("BENCH_REMAT", "0")
    remat = remat_mode not in ("0", "")
    if remat_mode not in ("0", "", "1", "full"):
        import paddle_tpu as _p
        # any other value is a recompute policy name (dots/dots_batch/
        # everything); fleet.utils.recompute raises on unknown names
        _p.set_flags({"FLAGS_recompute_policy": remat_mode})
    # BENCH_PRESET=1b: a genuinely 1B-class config (TinyLlama-1.1B
    # shape) — the sub-1B default can't saturate the MXU (round-2 MFU
    # was measured at h1024/L8; VERDICT item 2 asks for 1B+)
    preset = os.environ.get("BENCH_PRESET", "")
    if preset == "1b":
        dims = dict(hidden_size=2048, intermediate_size=5632,
                    num_hidden_layers=22, num_attention_heads=32,
                    num_key_value_heads=4)
    else:
        dims = dict(hidden_size=int(os.environ.get("BENCH_HIDDEN", "1024")),
                    intermediate_size=int(os.environ.get("BENCH_INTER",
                                                         "2816")),
                    num_hidden_layers=int(os.environ.get("BENCH_LAYERS",
                                                         "8")),
                    num_attention_heads=16, num_key_value_heads=8)

    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=32000,
                      max_position_embeddings=max(2048, seq),
                      use_recompute=remat, **dims)
    model = LlamaForCausalLM(cfg)
    model.train()
    fm = FunctionalModule(model, training=True)
    p_arrs = fm.param_arrays()
    # BENCH_PARAM_DTYPE=bf16: pure-bf16 state — params AND grads live in
    # bf16 (no fp32 master, no per-step cast). On a 16 GB v5e at the 1b
    # preset this frees ~6.6 GB (fp32 params 4.4 + fp32 grads 4.4 +
    # bf16 copies 2.2 → bf16 params 2.2 + bf16 grads 2.2), buying
    # no-remat arithmetic at batches that otherwise need recompute —
    # a throughput-measurement mode (production training keeps the
    # AMP-O2 master-weight path for convergence)
    pure_bf16 = os.environ.get("BENCH_PARAM_DTYPE", "") == "bf16"
    if pure_bf16:
        p_arrs = [a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a
                  for a in p_arrs]
        # rebind the module's Tensors to the bf16 arrays: they would
        # otherwise keep the fp32 originals alive for the whole run
        # (unlike the baseline path, which donates them to the jitted
        # step), stranding 4.4 GB at the 1b preset and defeating the
        # mode's point
        for t, a in zip(fm.params, p_arrs):
            t._data = a
        amp = False            # params are already compute-dtype
    key = fm.next_key()
    import numpy as np
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                         jnp.int32)

    # BENCH_ACCUM=n: gradient accumulation over n microbatches — an
    # activation-memory lever for the 1b preset on a 16 GB chip (the
    # microbatch fwd+bwd serialize on the grad-sum dependency, so peak
    # activation memory is that of batch/n, at full arithmetic)
    accum = max(int(os.environ.get("BENCH_ACCUM", "1")), 1)
    assert batch % accum == 0, "BENCH_ACCUM must divide BENCH_BATCH"

    def _loss_fn(ps, mb_ids, mb_labels):
        cps = [a.astype(jnp.bfloat16) if amp and a.dtype == jnp.float32
               else a for a in ps]
        (loss, _), _ = fm(cps, [], key, mb_ids, labels=mb_labels)
        return loss

    def train_step(p_arrs, key, ids, labels):
        loss_fn = _loss_fn

        if accum == 1:
            loss, grads = jax.value_and_grad(loss_fn)(p_arrs, ids, labels)
        else:
            # lax.scan carrying the accumulator: the carry dependency
            # forces microbatches to run strictly one after another, so
            # the peak-memory property holds by construction (an
            # unrolled Python loop would let XLA overlap forwards)
            mb = batch // accum
            ids_mb = ids.reshape(accum, mb, ids.shape[1])
            labels_mb = labels.reshape(accum, mb, labels.shape[1])

            def acc_step(carry, xs):
                loss_acc, grads_acc = carry
                l_i, g_i = jax.value_and_grad(loss_fn)(p_arrs, *xs)
                return (loss_acc + l_i,
                        [a + b for a, b in zip(grads_acc, g_i)]), None

            zeros = (jnp.zeros((), jnp.float32),
                     [jnp.zeros_like(p) for p in p_arrs])
            (loss, grads), _ = jax.lax.scan(acc_step, zeros,
                                            (ids_mb, labels_mb))
            loss = loss / accum
            grads = [g / accum for g in grads]
        new_p = [p - 1e-4 * g.astype(p.dtype) for p, g in zip(p_arrs, grads)]
        return loss, new_p

    step = jax.jit(train_step, donate_argnums=(0,))
    if os.environ.get("BENCH_ANALYZE", "1") == "1":
        # compiled-program introspection: XLA's own flop/byte counts +
        # peak memory — tells compute- vs HBM-bound without trace tooling.
        # The AOT executable then REPLACES the jit wrapper for the run
        # (the jit call cache doesn't reuse an AOT compile; calling step()
        # afterwards would compile the whole model twice)
        try:
            comp = step.lower(p_arrs, key, ids, labels).compile()
            ca = comp.cost_analysis() or {}
            ma = comp.memory_analysis()
            print(json.dumps({
                "aux_metric": "compiled_analysis",
                "xla_gflops": round(ca.get("flops", 0) / 1e9, 1),
                "xla_gbytes": round(ca.get("bytes accessed", 0) / 1e9, 2),
                "temp_mb": round(
                    getattr(ma, "temp_size_in_bytes", 0) / 1e6, 1),
                "argument_mb": round(
                    getattr(ma, "argument_size_in_bytes", 0) / 1e6, 1),
            }), file=sys.stderr)
            step = comp
        except Exception as e:
            print(f"bench: compiled analysis skipped: {e}", file=sys.stderr)
    loss, p_arrs = step(p_arrs, key, ids, labels)
    loss.block_until_ready()

    t0 = time.perf_counter()
    for _ in range(steps):
        loss, p_arrs = step(p_arrs, key, ids, labels)
    loss.block_until_ready()
    dt = time.perf_counter() - t0

    from paddle_tpu.profiler.mfu import llama_train_flops, PEAK_FLOPS, chip_kind
    flops = llama_train_flops(cfg, batch, seq)
    chip = os.environ.get("BENCH_CHIP") or chip_kind(jax.devices()[0])
    mfu = flops * steps / dt / PEAK_FLOPS.get(chip, PEAK_FLOPS["v5p"])
    print(json.dumps({"aux_metric": "mfu_" + chip,
                      "value": round(mfu * 100, 2), "unit": "%"}),
          file=sys.stderr)
    out = {
        "metric": "llama_1b_train_tokens_per_sec",
        "value": round(batch * seq * steps / dt, 2),
        "unit": "tokens/sec",
        "vs_baseline": None,
        "mfu_pct": round(mfu * 100, 2),
        "chip": chip,
        "config": {"batch": batch, "seq": seq, "remat": remat_mode,
                   "accum": accum,
                   "param_dtype": ("bf16" if pure_bf16
                                   else "fp32+amp" if amp else "fp32"),
                   **{k: v for k, v in dims.items()}},
    }
    # -- training observatory (ISSUE 12): memory peak, phase split,
    # numerics-sentinel cost
    out["train_peak_bytes"] = _train_peak_bytes()
    if os.environ.get("BENCH_PHASES", "1") == "1":
        def _fwd(ps):
            return _loss_fn(ps, ids, labels)

        def _grads(ps):
            return jax.value_and_grad(_loss_fn)(ps, ids, labels)[1]

        def _opt(ps, gs):
            return [p - 1e-4 * g.astype(p.dtype) for p, g in zip(ps, gs)]

        out["train_phase_breakdown"] = _phase_breakdown_probe(
            p_arrs, _fwd, _grads, _opt)
    out["numerics_overhead_pct"] = _numerics_overhead_pct()
    out["ledger_overhead_pct"] = _ledger_overhead_pct()
    out["compile_observatory_overhead_pct"] = \
        _compile_observatory_overhead_pct()
    _emit_observatory_aux(out)
    return out


def bench_bert():
    """Config-2 (BASELINE.json configs[1]): BERT/ERNIE-base fine-tune
    step time through the @to_static → HLO path on one device."""
    import time

    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models.bert import (BertConfig,
                                        BertForSequenceClassification)

    batch = int(os.environ.get("BENCH_BATCH", "32"))
    seq = int(os.environ.get("BENCH_SEQ", "128"))
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    paddle.seed(0)
    cfg = BertConfig()                    # base size: L12 H768 A12
    model = BertForSequenceClassification(cfg)
    model.eval()                          # deterministic step timing
    static = paddle.jit.to_static(model)
    opt = paddle.optimizer.AdamW(learning_rate=5e-5,
                                 parameters=model.parameters())
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (batch, seq)))
    labels = paddle.to_tensor(rng.integers(0, cfg.num_labels, (batch,)))
    mask = paddle.to_tensor(
        (rng.random((batch, seq)) < 0.9).astype(np.int64))

    def step():
        loss, _ = static(ids, attention_mask=mask, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    loss = step()                          # compile
    jax.block_until_ready(loss._data)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step()
    jax.block_until_ready(loss._data)
    dt = (time.perf_counter() - t0) / steps
    return {
        "metric": "bert_base_finetune_step_ms",
        "value": round(dt * 1e3, 2),
        "unit": "ms/step",
        "vs_baseline": None,
        "config": {"batch": batch, "seq": seq},
        "samples_per_sec": round(batch / dt, 2),
    }


def bench_comm():
    """Gradient-communication bench (BENCH_MODEL=comm): a simulated dp-N
    bucketed+quantized gradient all-reduce over a synthetic parameter set,
    vs the per-tensor fp32 baseline. Emits ``dp_allreduce_wire_bytes``
    (the quantized wire volume) with the fp32 baseline, compression
    ratio, call counts and max quantization error riding along — the
    CommStats counters the distributed.comm layer maintains."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.comm import (GradientBucketer, get_comm_stats,
                                             reset_comm_stats)

    nprocs = int(os.environ.get("BENCH_DP", "4"))
    steps = int(os.environ.get("BENCH_STEPS", "5"))
    # synthetic grad set shaped like a small model: 16 weight matrices +
    # 16 vectors, ~4.3 MB fp32 per rank
    shapes = [(256, 256)] * 16 + [(1024,)] * 16

    def run(quant, fuse_mb):
        reset_comm_stats()

        def worker():
            r = dist.get_rank()
            rng = np.random.default_rng(r)
            params = [paddle.to_tensor(np.zeros(s, np.float32))
                      for s in shapes]
            for p in params:
                p.grad = paddle.to_tensor(
                    rng.normal(size=p.shape).astype(np.float32))
            b = GradientBucketer(params, fuse_grad_size_in_MB=fuse_mb,
                                 quantization=quant, error_feedback=True)
            t0 = time.perf_counter()
            for _ in range(steps):
                b.sync_grads()
            return time.perf_counter() - t0

        times = dist.spawn(worker, nprocs=nprocs).results
        return get_comm_stats().as_dict(), max(times)

    base, t_base = run(None, 0)        # per-tensor fp32 (the legacy path)
    quant, t_quant = run("int8", 32)   # bucketed blockwise-int8
    overlap = _bench_comm_overlap(nprocs)
    fused = _bench_fused_step()
    for name, val in (("comm_overlap_step_ratio",
                       overlap["comm_overlap_step_ratio"]),
                      ("fused_step_dispatch_ratio",
                       fused["fused_step_dispatch_ratio"])):
        print(json.dumps({"aux_metric": name, "value": val}),
              file=sys.stderr)
    return {
        "metric": "dp_allreduce_wire_bytes",
        "value": quant["wire_bytes"],
        "unit": "bytes",
        "vs_baseline": None,
        "fp32_wire_bytes": base["wire_bytes"],
        "compression_ratio": round(base["wire_bytes"]
                                   / max(quant["wire_bytes"], 1), 3),
        "calls_fp32": base["calls"],
        "calls_int8": quant["calls"],
        "max_quant_error": quant["quant_max_error"],
        "sync_seconds_fp32": round(t_base, 3),
        "sync_seconds_int8": round(t_quant, 3),
        "dp": nprocs,
        "steps": steps,
        **overlap,
        **fused,
    }


def _bench_comm_overlap(nprocs):
    """Overlapped (ready-bucket, in-backward dispatch) vs barrier-at-step
    dp step time on a simulated dp-N MLP train loop. Same bucketer, same
    quantized wire — the delta is purely WHEN the collectives run.

    Runs under the simulator's wire-cost model
    (``PADDLE_SIM_WIRE_LAT_US``/``GBPS``, applied to BOTH variants): the
    in-memory rendezvous is otherwise instantaneous, leaving no wire time
    for overlap to hide — exactly the cost that dominates a real
    multi-chip interconnect."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu import nn
    from paddle_tpu.distributed import collective as _collective
    from paddle_tpu.distributed import fleet

    steps = int(os.environ.get("BENCH_OVERLAP_STEPS", "8"))
    repeats = int(os.environ.get("BENCH_OVERLAP_REPEATS", "2"))
    # pure-latency wire by default: latency is propagation (it pipelines
    # across in-flight buckets, the thing overlap exploits); bandwidth
    # would add per-byte occupancy on top — opt in via BENCH_SIM_WIRE_GBPS
    wire_env = {"PADDLE_SIM_WIRE_LAT_US":
                os.environ.get("BENCH_SIM_WIRE_LAT_US", "10000"),
                "PADDLE_SIM_WIRE_GBPS":
                os.environ.get("BENCH_SIM_WIRE_GBPS", "0")}

    def run(overlap):
        strat = fleet.DistributedStrategy()
        strat.comm_overlap = overlap
        strat.fuse_grad_size_in_MB = 0.0625    # one bucket per layer weight
        strat.comm_quantization = "int8"
        strat.comm_configs = {"error_feedback": True}

        def worker():
            r = dist.get_rank()
            net = nn.Sequential(*[layer
                                  for _ in range(8)
                                  for layer in (nn.Linear(128, 128),
                                                nn.ReLU())])
            for k, p in enumerate(net.parameters()):
                rng = np.random.default_rng(100 + k)
                p.set_value(rng.normal(size=p.shape).astype(np.float32)
                            * 0.05)
            dp = dist.parallel.DataParallel(net, strategy=strat)
            opt = paddle.optimizer.SGD(learning_rate=0.01,
                                       parameters=net.parameters())
            rng = np.random.default_rng(r)
            xs = [paddle.to_tensor(rng.normal(size=(8, 128))
                                   .astype(np.float32))
                  for _ in range(steps + 2)]
            ts = []
            for i, x in enumerate(xs):           # first 2 = warmup/compile
                t0 = time.perf_counter()
                loss = (dp(x) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
                if i >= 2:
                    ts.append(time.perf_counter() - t0)
            return ts

        def once():
            # per-step slowest rank, then the median step: robust to the
            # single-core scheduler noise that min/total-time is not
            res = dist.spawn(worker, nprocs=nprocs).results
            return float(np.median([max(col) for col in zip(*res)]))

        return min(once() for _ in range(repeats))

    saved = {k: os.environ.get(k) for k in wire_env}
    os.environ.update(wire_env)
    _collective._SIM_WIRE[0] = None      # re-read the knobs
    try:
        t_barrier = run(False)
        t_overlap = run(True)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        _collective._SIM_WIRE[0] = None
    return {
        "comm_overlap_step_ratio": round(t_overlap / t_barrier, 3),
        "overlap_step_seconds": round(t_overlap, 4),
        "barrier_step_seconds": round(t_barrier, 4),
        "overlap_dp": nprocs,
    }


def _bench_fused_step():
    """Host-dispatch collapse of the fused donated optimizer step on the
    llama config's parameter set: eager = one update dispatch per
    parameter per step, fused = O(1) compiled calls per step."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.optimizer.fused import opt_telemetry

    cfg = LlamaConfig(vocab_size=1000, hidden_size=128,
                      intermediate_size=256, num_hidden_layers=4,
                      num_attention_heads=4, num_key_value_heads=4,
                      max_position_embeddings=128)
    model = LlamaForCausalLM(cfg)
    params = [p for p in model.parameters() if p is not None]
    rng = np.random.default_rng(0)
    grads = [rng.normal(size=tuple(p.shape)).astype(np.float32) * 0.01
             for p in params]

    def dispatches(fused, steps=3):
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=params)
        opt.fuse_step = fused
        counter = opt_telemetry()["dispatches"]
        mode = "fused" if fused else "eager"
        before = counter.value(mode=mode)
        for _ in range(steps):
            for p, g in zip(params, grads):
                p.grad = paddle.to_tensor(g)
            opt.step()
        return (counter.value(mode=mode) - before) / steps

    eager = dispatches(False)
    fused = dispatches(True)
    return {
        "fused_step_dispatches_eager": round(eager, 1),
        "fused_step_dispatches_fused": round(fused, 1),
        "fused_step_dispatch_ratio": round(eager / max(fused, 1e-9), 1),
        "fused_step_params": len(params),
    }


def bench_dispatch():
    """Eager (dygraph) per-op dispatch overhead vs raw jax — SURVEY §7.3
    item 1's top risk, measured. Reports µs/op for a no-grad elementwise
    add (the pure dispatch path) plus the grad-enabled ratio and the
    comparable raw-jax eager-vjp cost as aux lines."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as paddle

    def clock(fn, n=2000, warmup=200):
        for _ in range(warmup):
            r = fn()
        jax.block_until_ready([getattr(r, "_data", r)])
        t0 = time.perf_counter()
        for _ in range(n):
            r = fn()
        jax.block_until_ready([getattr(r, "_data", r)])
        return (time.perf_counter() - t0) / n * 1e6

    xj = jnp.ones((256, 256))
    yj = jnp.ones((256, 256))
    xp = paddle.to_tensor(np.ones((256, 256), np.float32))
    yp = paddle.to_tensor(np.ones((256, 256), np.float32))
    xg = paddle.to_tensor(np.ones((256, 256), np.float32),
                          stop_gradient=False)

    raw = clock(lambda: jnp.add(xj, yj))
    nograd = clock(lambda: xp + yp)
    grad_on = clock(lambda: xg + yp)
    raw_vjp = clock(lambda: jax.vjp(jnp.add, xj, yj)[0], n=500, warmup=50)

    for name, val in (("raw_jnp_add_us", raw),
                      ("eager_grad_add_us", grad_on),
                      ("raw_jax_eager_vjp_us", raw_vjp),
                      ("grad_vs_rawvjp_ratio", grad_on / raw_vjp)):
        print(json.dumps({"aux_metric": name, "value": round(val, 2)}),
              file=sys.stderr)
    return {
        "metric": "eager_dispatch_overhead_vs_jax",
        "value": round(nograd / raw, 3),
        "unit": "x (add, 256x256; paddle eager / raw jnp)",
        "vs_baseline": None,
    }


def bench_llama_decode():
    """Serving-tier decode bench: batched autoregressive decode through the
    paged KV cache + Pallas paged_attention kernel (tokens/sec).

    ``BENCH_SHARED_PREFIX=1`` switches to the engine-level variant: the
    batch shares a common system prompt served through
    ``ContinuousServingEngine``'s prefix cache (one warm-up request fills
    the index; the timed requests prefill only their unique tails), and
    the record carries the measured prefix hit rate."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    batch = int(os.environ.get("BENCH_BATCH", "8"))
    prompt = int(os.environ.get("BENCH_PROMPT", "128"))
    new = int(os.environ.get("BENCH_NEW_TOKENS", "128"))
    shared_prefix = os.environ.get("BENCH_SHARED_PREFIX", "0") == "1"

    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                      intermediate_size=2816, num_hidden_layers=8,
                      num_attention_heads=16, num_key_value_heads=8,
                      max_position_embeddings=max(2048, prompt + new))
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)

    if shared_prefix:
        import threading
        from paddle_tpu.inference import ContinuousServingEngine
        tail = int(os.environ.get("BENCH_TAIL", "16"))
        sys_prompt = rng.integers(0, cfg.vocab_size, prompt - tail)
        prompts = [np.concatenate([sys_prompt,
                                   rng.integers(0, cfg.vocab_size, tail)])
                   .astype(np.int64)[None] for _ in range(batch)]
        eng = ContinuousServingEngine(
            model, max_batch_size=batch, max_len=prompt + new,
            enable_prefix_cache=True)
        with eng:
            # first request prefills + registers the shared blocks
            eng.generate(prompts[0], max_new_tokens=new, timeout=1800)
            t0 = time.perf_counter()
            threads = [threading.Thread(
                target=lambda p=p: eng.generate(p, max_new_tokens=new,
                                                timeout=1800))
                for p in prompts[1:]]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            cache = eng._cache
            lookups = max(cache.prefix_hits + cache.prefix_misses, 1)
            hit_rate = round(cache.prefix_hits / lookups, 3)
            cached = cache.cached_tokens_total
        return {
            "metric": "llama_paged_decode_tokens_per_sec",
            "value": round((batch - 1) * new / dt, 2),
            "unit": "tokens/sec",
            "vs_baseline": None,
            "shared_prefix": True,
            "prefix_hit_rate": hit_rate,
            "prefix_cached_tokens": int(cached),
        }

    ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size,
                                        (batch, prompt)).astype(np.int64))
    model.generate(ids, max_new_tokens=4, use_paged_cache=True)  # warmup
    t0 = time.perf_counter()
    out = model.generate(ids, max_new_tokens=new, use_paged_cache=True)
    assert out.shape[1] == prompt + new
    dt = time.perf_counter() - t0
    return {
        "metric": "llama_paged_decode_tokens_per_sec",
        "value": round(batch * new / dt, 2),
        "unit": "tokens/sec",
        "vs_baseline": None,
    }


def bench_serving():
    """Engine-level serving fast-path bench (``BENCH_MODEL=serving``):
    TTFT and decode throughput through ``ContinuousServingEngine`` with a
    shared system prompt, prefix cache ON vs OFF in the same run — the
    paper's production story (millions of users share system prompts /
    few-shot templates; arxiv 2605.25645 shows prefix reuse is the
    dominant TTFT lever on TPU)."""
    import threading

    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.inference import ContinuousServingEngine
    from paddle_tpu.profiler import request_trace as rt

    # fresh sliding window: the SLO percentiles below cover THIS run
    rt.reset_slo_monitor()
    n_req = int(os.environ.get("BENCH_REQUESTS", "8"))
    sys_len = int(os.environ.get("BENCH_SYS_PROMPT", "128"))
    tail = int(os.environ.get("BENCH_TAIL", "8"))
    new = int(os.environ.get("BENCH_NEW_TOKENS", "8"))
    chunk = int(os.environ.get("BENCH_CHUNK_TOKENS", "64"))

    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=256,
                      intermediate_size=704, num_hidden_layers=4,
                      num_attention_heads=8, num_key_value_heads=4,
                      max_position_embeddings=max(2048, sys_len + tail + new))
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    sys_prompt = rng.integers(0, cfg.vocab_size, sys_len)
    prompts = [np.concatenate([sys_prompt,
                               rng.integers(0, cfg.vocab_size, tail)])
               .astype(np.int64)[None] for _ in range(n_req)]

    def run(prefix_cache):
        eng = ContinuousServingEngine(
            model, max_batch_size=4, max_len=sys_len + tail + new + 16,
            enable_prefix_cache=prefix_cache, prefill_chunk_tokens=chunk)
        stats = {}
        with eng:
            # request 0 warms compiled programs AND (when enabled) fills
            # the prefix index with the shared system-prompt blocks
            eng.generate(prompts[0], max_new_tokens=new, timeout=1800)
            ttfts = []
            for p in prompts[1:]:
                t0 = time.perf_counter()
                eng.generate(p, max_new_tokens=1, timeout=1800)
                ttfts.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            outs = [None] * (n_req - 1)

            def _gen(i, p):
                outs[i] = np.asarray(
                    eng.generate(p, max_new_tokens=new,
                                 timeout=1800).numpy())

            threads = [threading.Thread(target=_gen, args=(i, p))
                       for i, p in enumerate(prompts[1:])]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            # content digest of every delivered stream, in prompt order
            # (greedy decode is deterministic, so this is stable across
            # runs — bench_compare flags any drift as output-content
            # regression, not just perf regression)
            import hashlib
            h = hashlib.sha1()
            for o in outs:
                h.update(np.ascontiguousarray(o).tobytes())
            cache = eng._cache
            stats = {
                "ttft_ms": round(float(np.mean(ttfts)) * 1e3, 2),
                "tokens_per_sec": round((n_req - 1) * new / dt, 2),
                "prefix_hits": int(cache.prefix_hits),
                "prefix_misses": int(cache.prefix_misses),
                "cached_tokens": int(cache.cached_tokens_total),
                "token_digest": h.hexdigest(),
            }
        return stats

    def run_mixed(ragged):
        """Ragged-vs-legacy variant: MIXED concurrent load (varied prompt
        lengths, staggered arrivals) so prefill and decode contend for
        every tick — the regime the one-kernel token-budget scheduler
        (Ragged Paged Attention, arxiv 2604.15464) exists for."""
        mix_rng = np.random.default_rng(1)
        lens = [sys_len // 2 + int(mix_rng.integers(1, sys_len // 2 + 8))
                for _ in range(n_req)]
        mix = [mix_rng.integers(0, cfg.vocab_size, n).astype(np.int64)[None]
               for n in lens]
        eng = ContinuousServingEngine(
            model, max_batch_size=4, max_len=max(lens) + new + 16,
            enable_prefix_cache=False, prefill_chunk_tokens=chunk,
            token_budget=chunk, enable_ragged=ragged)
        with eng:
            eng.generate(mix[0], max_new_tokens=new, timeout=1800)  # warmup
            t0 = time.perf_counter()
            threads = [threading.Thread(
                target=lambda p=p, i=i: (time.sleep(0.002 * i),
                                         eng.generate(p, max_new_tokens=new,
                                                      timeout=1800)))
                for i, p in enumerate(mix[1:])]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
        waste = 1.0 - (eng.useful_tokens_total
                       / max(eng.padded_tokens_total, 1))
        return {"tokens_per_sec": (n_req - 1) * new / dt,
                "waste_ratio": round(waste, 3),
                "buckets": sorted(eng.ragged_buckets_used)}

    def run_spec(spec_on):
        """Speculative-decode on-vs-off variant: short prompts + long
        decodes so TPOT dominates, tier-2 self-draft drafter (acceptance
        ~1.0) as the upper bound. Interpret-tier wall clock understates
        the win (a verify span costs k+1 attention grid steps there, and
        the draft forwards are full model runs), so the target-forwards-
        per-token ratio is emitted alongside as the device-tier proxy —
        the same convention as the ragged tokens/s ratio."""
        sp_rng = np.random.default_rng(2)
        sp_new = max(new, 8)
        sp = [sp_rng.integers(0, cfg.vocab_size, 24).astype(np.int64)[None]
              for _ in range(4)]
        eng = ContinuousServingEngine(
            model, max_batch_size=4, max_len=24 + sp_new + 16,
            enable_prefix_cache=False, token_budget=64,
            spec_decode=spec_on, spec_k=4,
            draft_model=model if spec_on else None)
        with eng:
            eng.generate(sp[0], max_new_tokens=2, timeout=1800)  # warmup
            t0 = time.perf_counter()
            threads = [threading.Thread(
                target=lambda p=p: eng.generate(p, max_new_tokens=sp_new,
                                                timeout=1800))
                for p in sp]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
        tokens = len(sp) * sp_new
        return {
            "tokens_per_sec": tokens / dt,
            "decode_ticks": eng.decode_steps,
            "tokens": tokens,
            "forwards_per_token": eng.decode_steps / max(tokens, 1),
            "acceptance": (eng.spec_accepted_tokens
                           / max(eng.spec_drafted_tokens, 1)),
            "drafted": eng.spec_drafted_tokens,
            # batched drafting win: draft-model forwards per drafting
            # tick (the per-slot path pays ~slots*k forwards per tick,
            # the batched path pays ~k)
            "draft_forwards_per_tick": round(
                eng.spec_draft_forwards / max(eng.spec_draft_ticks, 1),
                3),
        }

    def compile_probe():
        """Compile-observatory steady-state probe: warm every declared
        program bucket via ``warmup_programs()``, then replay the mixed
        prefill+decode workload — post-warmup trace-cache misses must
        be ZERO (``serving_recompiles_per_1k_ticks == 0`` is the
        recompile-storm acceptance gate), and the warmup wall seconds
        are the cold-start compile budget a fleet pays per process."""
        from paddle_tpu.profiler import compile_observatory as co
        co.reset()
        co.enable()
        mix_rng = np.random.default_rng(3)
        lens = [sys_len // 2 + int(mix_rng.integers(1, sys_len // 2 + 8))
                for _ in range(n_req)]
        mix = [mix_rng.integers(0, cfg.vocab_size, n)
               .astype(np.int64)[None] for n in lens]
        eng = ContinuousServingEngine(
            model, max_batch_size=4, max_len=max(lens) + new + 16,
            enable_prefix_cache=False, prefill_chunk_tokens=chunk,
            token_budget=chunk, enable_ragged=True)
        warmup_s = sum(eng.warmup_programs().values())
        base = co.snapshot()["totals"]["misses"]
        with eng:
            ticks0 = eng.ragged_steps
            threads = [threading.Thread(
                target=lambda p=p, i=i: (time.sleep(0.002 * i),
                                         eng.generate(p,
                                                      max_new_tokens=new,
                                                      timeout=1800)))
                for i, p in enumerate(mix)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            misses = co.snapshot()["totals"]["misses"] - base
            ticks = max(eng.ragged_steps - ticks0, 1)
        return {"warmup_compile_s": round(warmup_s, 3),
                "post_warmup_misses": int(misses),
                "recompiles_per_1k_ticks": round(misses / ticks * 1e3,
                                                 3)}

    def qblock_step_probe():
        """Q-block vs per-token ragged grid at a representative mixed
        prefill+decode tick: the per-token kernel runs one grid step per
        (token, kv_head, page); the q-block kernel runs one per
        (q_block, kv_head, job). The step ratio is the device-tier
        speed lever (fewer, fatter MXU launches for the same math) and
        is exact from the schedules — no timing noise."""
        from paddle_tpu.ops.pallas.ragged_paged_attention import (
            qblock_schedule, _qblock_rows)
        page, pps = 16, 8
        # 3 decode slots mid-stream + a chunked-prefill tail + a fresh
        # prefill: 64 packed tokens, the run_mixed regime
        seq_slots = np.asarray([0, 1, 2, 3, 4], np.int32)
        q_starts = np.asarray([0, 1, 2, 3, 32], np.int32)
        q_lens = np.asarray([1, 1, 1, 29, 32], np.int32)
        ctx = np.asarray([97, 54, 21, 29, 32], np.int32)
        tbl = np.zeros((8, pps), np.int32)
        tokens = 64
        kv_heads = cfg.num_key_value_heads
        _, _, job_page, _, _ = qblock_schedule(
            tokens, seq_slots, q_starts, q_lens, ctx, tbl,
            _qblock_rows(), page)
        q_steps = job_page.shape[0] * kv_heads * job_page.shape[1]
        t_steps = tokens * kv_heads * pps
        return {"qblock_grid_steps": int(q_steps),
                "token_grid_steps": int(t_steps),
                "step_ratio": round(q_steps / t_steps, 4)}

    def run_int8_weights():
        """Fully-quantized serving config: int8 weights end-to-end
        (``quantize_linears`` routes every Linear through the Pallas
        int8 GEMM) + int8 KV pages, on a fresh same-seed model so the
        shared float model above stays untouched. Emits the tokens/s
        ratio vs the float engine and the weight-footprint win."""
        import hashlib

        from paddle_tpu.nn.layers.common import Linear

        paddle.seed(0)
        qmodel = LlamaForCausalLM(cfg)
        eng = ContinuousServingEngine(
            qmodel, max_batch_size=4, max_len=sys_len + tail + new + 16,
            enable_prefix_cache=False, prefill_chunk_tokens=chunk,
            weight_dtype="int8", kv_dtype="int8")
        with eng:
            eng.generate(prompts[0], max_new_tokens=new, timeout=1800)
            t0 = time.perf_counter()
            outs = [None] * (n_req - 1)

            def _gen(i, p):
                outs[i] = np.asarray(
                    eng.generate(p, max_new_tokens=new,
                                 timeout=1800).numpy())

            threads = [threading.Thread(target=_gen, args=(i, p))
                       for i, p in enumerate(prompts[1:])]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
        int8_bytes = float_bytes = 0

        def visit(layer):
            nonlocal int8_bytes, float_bytes
            if isinstance(layer, Linear) and layer._w_int8 is not None:
                int8_bytes += (layer._w_int8.nbytes
                               + layer._w_scale.nbytes)
                float_bytes += layer._w_int8.size * 4
            for sub in layer._sub_layers.values():
                if sub is not None:
                    visit(sub)

        visit(qmodel)
        h = hashlib.sha1()
        for o in outs:
            h.update(np.ascontiguousarray(o).tobytes())
        return {
            "tokens_per_sec": (n_req - 1) * new / dt,
            "quantized_linears": int(eng.quantized_linears),
            "weight_bytes_ratio": round(int8_bytes
                                        / max(float_bytes, 1), 4),
            "token_digest": h.hexdigest(),
        }

    def kv_capacity_probe():
        """``BENCH_KV_DTYPE=int8`` capacity probe: max concurrent
        full-length sessions a fixed pool byte budget holds, int8 vs
        native pages (analytic from the page codec's byte layout,
        cross-checked against a live int8 engine's measured
        ``page_nbytes``)."""
        from paddle_tpu.models.generation import kv_page_nbytes
        pool_mb = float(os.environ.get("BENCH_KV_POOL_MB", "64"))
        budget = int(pool_mb * 2 ** 20)
        page = 16
        seq_len = sys_len + tail + new + 16
        pages_per_seq = -(-seq_len // page)
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        kv_heads = cfg.num_key_value_heads
        native_pb = kv_page_nbytes(kv_heads, head_dim, page, "native",
                                   "float32", cfg.num_hidden_layers)
        int8_pb = kv_page_nbytes(kv_heads, head_dim, page, "int8",
                                 "float32", cfg.num_hidden_layers)
        native_sessions = budget // (pages_per_seq * native_pb)
        int8_sessions = budget // (pages_per_seq * int8_pb)
        # prove the int8 pool serves real traffic + measured page bytes
        eng = ContinuousServingEngine(model, max_batch_size=2,
                                      max_len=seq_len, kv_dtype="int8")
        with eng:
            eng.generate(prompts[0], max_new_tokens=new, timeout=1800)
            measured_pb = eng._cache.page_nbytes
        return {
            "pool_mb": pool_mb,
            "native_sessions": int(native_sessions),
            "int8_sessions": int(int8_sessions),
            "capacity_ratio": round(int8_sessions
                                    / max(native_sessions, 1), 2),
            "int8_page_nbytes": int(int8_pb),
            "int8_page_nbytes_measured": int(measured_pb),
        }

    def run_kv_tier():
        """Tiered-KV probe (ISSUE 19): a distinct-prefix working set
        several times the device page pool, served twice — the second
        pass re-admits every prefix AFTER its pages were evicted from
        device. With ``host_pool_mb=0`` that is a full re-prefill; with
        the host tier on, eviction demoted the pages to host RAM and
        re-admission promotes them back (a memcpy, not a forward pass).
        TTFT ratio off/on is ``serving_kv_tier_hit_speedup``; the
        delivered streams must be bit-identical either way. Small
        prefill chunks keep the comparison honest off-TPU: a full
        re-prefill pays ceil(prompt/chunk) chunk ticks where a
        host-tier hit pays one, so the ratio survives the
        interpret-mode per-forward floor that would otherwise mask
        the prefill-token savings."""
        import hashlib
        kvt_pref, kvt_tail_n = 96, 8
        kvt_n = int(os.environ.get("BENCH_KV_TIER_REQS", "10"))
        kvt_len = kvt_pref + kvt_tail_n + new + 16
        kvt_rng = np.random.default_rng(7)
        kvt_prompts = [
            np.concatenate([kvt_rng.integers(0, cfg.vocab_size, kvt_pref),
                            kvt_rng.integers(0, cfg.vocab_size, kvt_tail_n)])
            .astype(np.int64)[None] for _ in range(kvt_n)]

        def one(pool_mb):
            eng = ContinuousServingEngine(
                model, max_batch_size=2, max_len=kvt_len,
                enable_prefix_cache=True, num_pages=10,
                host_pool_mb=pool_mb, prefill_chunk_tokens=32)
            with eng:
                # pass 1: populate the prefix index; the working set
                # (60 prefix pages at the default 10 requests) dwarfs
                # the 9-page device pool, so every prefix is evicted
                # (and, with the tier on, demoted) before its
                # re-admission below
                for p in kvt_prompts:
                    eng.generate(p, max_new_tokens=1, timeout=1800)
                ttfts = []
                for p in kvt_prompts:
                    t0 = time.perf_counter()
                    eng.generate(p, max_new_tokens=1, timeout=1800)
                    ttfts.append(time.perf_counter() - t0)
                h = hashlib.sha1()
                for p in kvt_prompts:
                    o = np.asarray(eng.generate(
                        p, max_new_tokens=new, timeout=1800).numpy())
                    h.update(np.ascontiguousarray(o).tobytes())
                pool = eng._host_pool
                return {"ttft_ms": round(float(np.mean(ttfts)) * 1e3, 2),
                        "promotions": int(pool.promotions),
                        "demotions": int(pool.demotions),
                        "token_digest": h.hexdigest()}

        t_off = one(0)
        t_on = one(64)
        assert t_on["token_digest"] == t_off["token_digest"], \
            "host-tier promotion changed delivered tokens"
        return {
            "speedup": round(t_off["ttft_ms"]
                             / max(t_on["ttft_ms"], 1e-6), 2),
            "ttft_host_ms": t_on["ttft_ms"],
            "ttft_reprefill_ms": t_off["ttft_ms"],
            "promotions": t_on["promotions"],
            "demotions": t_on["demotions"],
            "token_digest": t_on["token_digest"],
        }

    def run_long_context():
        """Long-context probe (ISSUE 19): a prompt larger than the
        device page pool, chunk-prefilled through the sep ring-attention
        schedule (host-striped KV, pow2 decode tail). Emits prompt
        tokens per prefill-wall-second and cross-checks the delivered
        stream against a single-device oracle engine whose pool DOES
        hold the whole prompt."""
        import hashlib
        lc_len = 512
        lc_rng = np.random.default_rng(9)
        lc_prompt = lc_rng.integers(0, cfg.vocab_size,
                                    lc_len).astype(np.int64)[None]
        lc_max = lc_len + new + 16
        eng = ContinuousServingEngine(
            model, max_batch_size=2, max_len=lc_max,
            enable_prefix_cache=False, num_pages=16,  # 240-token pool
            sep_prefill=True, sep_stripe_tokens=64,
            sep_threshold_tokens=256)
        with eng:
            eng.generate(lc_prompt, max_new_tokens=1, timeout=1800)
            t0 = time.perf_counter()
            eng.generate(lc_prompt, max_new_tokens=1, timeout=1800)
            dt = time.perf_counter() - t0
            out = np.asarray(eng.generate(
                lc_prompt, max_new_tokens=new, timeout=1800).numpy())
            sep_reqs = int(eng.sep_requests)
            chunks = int(eng._cache.sep_chunks)
        oracle = ContinuousServingEngine(
            model, max_batch_size=2, max_len=lc_max,
            enable_prefix_cache=False)
        with oracle:
            want = np.asarray(oracle.generate(
                lc_prompt, max_new_tokens=new, timeout=1800).numpy())
        assert np.array_equal(out, want), \
            "sep long-context decode diverged from single-device oracle"
        h = hashlib.sha1(np.ascontiguousarray(out).tobytes())
        return {
            "tokens_per_s": round((lc_len + 1) / dt, 2),
            "prompt_tokens": lc_len,
            "sep_requests": sep_reqs,
            "sep_prefill_chunks": chunks,
            "oracle_match": True,
            "token_digest": h.hexdigest(),
        }

    off = run(False)
    on = run(True)
    mixed_ragged = run_mixed(True)
    mixed_legacy = run_mixed(False)
    spec_on = run_spec(True)
    spec_off = run_spec(False)
    qblock = qblock_step_probe()
    compile_obs = compile_probe()
    int8w = run_int8_weights()
    int8w_ratio = round(int8w["tokens_per_sec"]
                        / max(off["tokens_per_sec"], 1e-9), 2)
    spec_speedup = round(spec_on["tokens_per_sec"]
                         / max(spec_off["tokens_per_sec"], 1e-9), 2)
    kv_probe = (kv_capacity_probe()
                if os.environ.get("BENCH_KV_DTYPE", "").lower() == "int8"
                else None)
    kv_tier = run_kv_tier()
    long_ctx = run_long_context()
    ragged_ratio = round(mixed_ragged["tokens_per_sec"]
                         / max(mixed_legacy["tokens_per_sec"], 1e-9), 2)
    # latency percentiles + goodput from the request-trace SLO monitor
    # (every engine generate above fed it) — the bench trajectory's
    # first latency-percentile entries
    slo = rt.slo_report()
    aux = [
        ("serving_ragged_tokens_per_s_ratio", ragged_ratio),
        ("serving_ragged_waste_ratio", mixed_ragged["waste_ratio"]),
        ("serving_legacy_waste_ratio", mixed_legacy["waste_ratio"]),
        ("serving_p95_ttft_ms", round(slo["ttft"]["p95_s"] * 1e3, 2)),
        ("serving_p95_tpot_ms", round(slo["tpot"]["p95_s"] * 1e3, 2)),
        ("serving_goodput_ratio", round(slo["goodput_ratio"], 3)),
        ("serving_spec_tpot_speedup", spec_speedup),
        ("serving_spec_acceptance_rate",
         round(spec_on["acceptance"], 3)),
        ("serving_spec_forwards_per_token",
         round(spec_on["forwards_per_token"], 3)),
        ("serving_qblock_step_ratio", qblock["step_ratio"]),
        ("serving_int8_weight_tokens_per_s_ratio", int8w_ratio),
        ("serving_int8_weight_bytes_ratio",
         int8w["weight_bytes_ratio"]),
        ("spec_draft_forwards_per_tick",
         spec_on["draft_forwards_per_tick"]),
        ("serving_recompiles_per_1k_ticks",
         compile_obs["recompiles_per_1k_ticks"]),
        ("serving_warmup_compile_s", compile_obs["warmup_compile_s"]),
        ("serving_kv_tier_hit_speedup", kv_tier["speedup"]),
        ("serving_long_context_tokens_per_s", long_ctx["tokens_per_s"]),
    ]
    if kv_probe is not None:
        aux.append(("serving_kv_capacity_ratio",
                    kv_probe["capacity_ratio"]))
    # delivered-token-stream content digest (determinism ledger's
    # cross-run story at bench granularity): bench_compare treats
    # *_digest fields as exact-match metrics, so output-content drift
    # between two bench runs fails the comparison like a perf
    # regression would
    aux.append(("serving_token_digest", on["token_digest"]))
    for name, val in aux:
        print(json.dumps({"aux_metric": name, "value": val}),
              file=sys.stderr)
    return {
        "p95_ttft_ms": round(slo["ttft"]["p95_s"] * 1e3, 2),
        "p95_tpot_ms": round(slo["tpot"]["p95_s"] * 1e3, 2),
        "p95_queue_wait_ms": round(slo["queue_wait"]["p95_s"] * 1e3, 2),
        "goodput_ratio": round(slo["goodput_ratio"], 3),
        "metric": "serving_prefix_ttft_speedup",
        "value": round(off["ttft_ms"] / max(on["ttft_ms"], 1e-6), 2),
        "unit": "x (mean TTFT, prefix cache off / on, shared sys prompt)",
        "vs_baseline": None,
        "ttft_cached_ms": on["ttft_ms"],
        "ttft_nocache_ms": off["ttft_ms"],
        "tokens_per_sec_cached": on["tokens_per_sec"],
        "tokens_per_sec_nocache": off["tokens_per_sec"],
        "prefix_hits": on["prefix_hits"],
        "prefix_cached_tokens": on["cached_tokens"],
        "serving_token_digest": on["token_digest"],
        # ragged-vs-legacy under mixed concurrent prefill+decode load
        "serving_ragged_tokens_per_s_ratio": ragged_ratio,
        "ragged_tokens_per_sec": round(mixed_ragged["tokens_per_sec"], 2),
        "legacy_tokens_per_sec": round(mixed_legacy["tokens_per_sec"], 2),
        "ragged_waste_ratio": mixed_ragged["waste_ratio"],
        "legacy_waste_ratio": mixed_legacy["waste_ratio"],
        "ragged_buckets": mixed_ragged["buckets"],
        # speculative decode on-vs-off (self-draft upper bound)
        "serving_spec_tpot_speedup": spec_speedup,
        "spec_acceptance_rate": round(spec_on["acceptance"], 3),
        "spec_drafted_tokens": spec_on["drafted"],
        "spec_forwards_per_token": round(spec_on["forwards_per_token"], 3),
        "nospec_forwards_per_token": round(spec_off["forwards_per_token"],
                                           3),
        "spec_draft_forwards_per_tick": spec_on["draft_forwards_per_tick"],
        # compile observatory: cold-start warmup cost + steady-state
        # recompile rate (must be 0 — misses after warmup mean shapes
        # are churning past the declared buckets)
        "serving_recompiles_per_1k_ticks":
            compile_obs["recompiles_per_1k_ticks"],
        "serving_warmup_compile_s": compile_obs["warmup_compile_s"],
        "compile_post_warmup_misses": compile_obs["post_warmup_misses"],
        # q-block vs per-token ragged grid (exact step counts)
        "serving_qblock_step_ratio": qblock["step_ratio"],
        "qblock_grid_steps": qblock["qblock_grid_steps"],
        "token_grid_steps": qblock["token_grid_steps"],
        # fully-quantized config: int8 weights + int8 KV pages
        "serving_int8_weight_tokens_per_s_ratio": int8w_ratio,
        "serving_int8_weight_bytes_ratio": int8w["weight_bytes_ratio"],
        "int8_weight_token_digest": int8w["token_digest"],
        "quantized_linears": int8w["quantized_linears"],
        "kv_capacity_probe": kv_probe,
        # tiered KV: host-RAM prefix spill (TTFT on re-admission, host
        # tier vs full re-prefill, identical token streams enforced)
        "serving_kv_tier_hit_speedup": kv_tier["speedup"],
        "kv_tier_ttft_host_ms": kv_tier["ttft_host_ms"],
        "kv_tier_ttft_reprefill_ms": kv_tier["ttft_reprefill_ms"],
        "kv_tier_promotions": kv_tier["promotions"],
        "kv_tier_token_digest": kv_tier["token_digest"],
        # long-context sep-parallel prefill (prompt > device page pool,
        # bit-identical to the single-device oracle)
        "serving_long_context_tokens_per_s": long_ctx["tokens_per_s"],
        "long_context_prompt_tokens": long_ctx["prompt_tokens"],
        "long_context_sep_chunks": long_ctx["sep_prefill_chunks"],
        "long_context_token_digest": long_ctx["token_digest"],
        "config": {"requests": n_req, "sys_prompt": sys_len, "tail": tail,
                   "new_tokens": new, "chunk_tokens": chunk},
    }


def bench_fleet():
    """Fleet-router bench (``BENCH_MODEL=fleet``): shared-system-prompt
    mixed-tenant workload over 2 engine replicas, prefix-affinity
    routing vs round-robin — the PR-4 ``serving_prefix_ttft_speedup``
    methodology applied at the orchestration layer (the Gemma-on-TPU
    serving study, arxiv 2605.25645: replica routing + cache locality
    decide TPU serving economics)."""
    import threading

    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.distributed.fleet.elastic.tcp_kv import MemKVStore
    from paddle_tpu.inference import ServingRouter
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.profiler import request_trace as rt

    # fresh sliding window: the SLO percentiles below cover THIS run
    rt.reset_slo_monitor()
    n_req = int(os.environ.get("BENCH_REQUESTS", "8"))
    sys_len = int(os.environ.get("BENCH_SYS_PROMPT", "128"))
    tail = int(os.environ.get("BENCH_TAIL", "8"))
    new = int(os.environ.get("BENCH_NEW_TOKENS", "8"))

    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=256,
                      intermediate_size=704, num_hidden_layers=4,
                      num_attention_heads=8, num_key_value_heads=4,
                      max_position_embeddings=max(2048, sys_len + tail + new))
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    sys_prompt = rng.integers(0, cfg.vocab_size, sys_len)
    prompts = [np.concatenate([sys_prompt,
                               rng.integers(0, cfg.vocab_size, tail)])
               .astype(np.int64)[None] for _ in range(n_req)]

    def run(policy):
        router = ServingRouter(
            model, num_replicas=2, policy=policy, store=MemKVStore(),
            heartbeat_ttl=600.0,
            engine_kwargs=dict(max_batch_size=4,
                               max_len=sys_len + tail + new + 16))
        with router:
            # request 0 warms compiled programs on ONE replica and (under
            # affinity) pins the shared chain there; round-robin then
            # pays the prefill again on the other replica
            router.generate(prompts[0], max_new_tokens=new,
                            tenant="tenant0", timeout=1800)
            ttfts = []
            for i, p in enumerate(prompts[1:], start=1):
                t0 = time.perf_counter()
                router.generate(p, max_new_tokens=1,
                                tenant=f"tenant{i % 3}", timeout=1800)
                ttfts.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            threads = [threading.Thread(
                target=lambda p=p, i=i: router.generate(
                    p, max_new_tokens=new, tenant=f"tenant{i % 3}",
                    timeout=1800))
                for i, p in enumerate(prompts[1:], start=1)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            cached = sum(r.engine._cache.cached_tokens_total
                         for r in router.replicas)
            stats = router.stats()
        return {
            "ttft_ms": round(float(np.mean(ttfts)) * 1e3, 2),
            "tokens_per_sec": round((n_req - 1) * new / dt, 2),
            "cached_tokens": int(cached),
            "affinity_hits": stats["affinity_hits"],
            "affinity_matchable": stats["affinity_matchable"],
        }

    rr = run("round_robin")
    aff = run("affinity")
    speedup = round(rr["ttft_ms"] / max(aff["ttft_ms"], 1e-6), 2)
    # fleet-level SLO percentiles + goodput: every routed request above
    # fed the request-trace SLO monitor (TTFT measured at the ROUTER,
    # queue wait and per-token gaps from the engine spans)
    slo = rt.slo_report()
    replay_rep = _bench_fleet_replay(model, sys_len, tail, new)
    # chaos pair: the SAME seeded burst with a replica killed mid-run,
    # controller-off vs controller-on — the ISSUE-14 acceptance numbers
    # (recover_ratio > 1 means the controller recovered faster)
    kill_spec = os.environ.get("BENCH_FLEET_FAULT",
                               "kill:replica=r1,request=4")
    ctl_off = _bench_fleet_replay(model, sys_len, tail, new,
                                  fault_spec=kill_spec)
    ctl_on = _bench_fleet_replay(model, sys_len, tail, new,
                                 fault_spec=kill_spec, controller=True)
    export_pct, scrape_age = _bench_telemetry_plane(model, sys_len, new)
    ttr_on = ctl_on.get("time_to_recover_s")
    ttr_off = ctl_off.get("time_to_recover_s")
    if ttr_on is None:
        recover_ratio = None
    elif ttr_off is None:
        # controller-off never recovered inside its observation window:
        # credit the whole window (a floor, not a fabrication)
        window = max(ctl_off.get("observed_s") or 0.0, ttr_on)
        recover_ratio = round(window / max(ttr_on, 1e-6), 2)
    else:
        recover_ratio = round(ttr_off / max(ttr_on, 1e-6), 2)
    n_actions = ctl_on.get("controller_actions_total", 0)
    for name, val in (
            ("fleet_affinity_ttft_speedup", speedup),
            ("fleet_affinity_cached_tokens", aff["cached_tokens"]),
            ("fleet_rr_cached_tokens", rr["cached_tokens"]),
            ("fleet_p95_ttft_ms", round(slo["ttft"]["p95_s"] * 1e3, 2)),
            ("fleet_p95_tpot_ms", round(slo["tpot"]["p95_s"] * 1e3, 2)),
            ("fleet_goodput_ratio", round(slo["goodput_ratio"], 3)),
            ("fleet_goodput_under_burst",
             replay_rep.get("goodput_under_burst")),
            ("fleet_time_to_recover_s",
             replay_rep.get("time_to_recover_s")),
            ("fleet_controller_recover_ratio", recover_ratio),
            ("fleet_controller_actions", n_actions),
            ("telemetry_export_overhead_pct", export_pct),
            ("telemetry_scrape_age_s", scrape_age)):
        print(json.dumps({"aux_metric": name, "value": val}),
              file=sys.stderr)
    return {
        "p95_ttft_ms": round(slo["ttft"]["p95_s"] * 1e3, 2),
        "p95_tpot_ms": round(slo["tpot"]["p95_s"] * 1e3, 2),
        "p95_queue_wait_ms": round(slo["queue_wait"]["p95_s"] * 1e3, 2),
        "goodput_ratio": round(slo["goodput_ratio"], 3),
        "metric": "fleet_affinity_ttft_speedup",
        "value": speedup,
        "unit": "x (mean TTFT, round-robin / affinity, 2 replicas, "
                "shared sys prompt)",
        "vs_baseline": None,
        "ttft_affinity_ms": aff["ttft_ms"],
        "ttft_round_robin_ms": rr["ttft_ms"],
        "tokens_per_sec_affinity": aff["tokens_per_sec"],
        "tokens_per_sec_round_robin": rr["tokens_per_sec"],
        "cached_tokens_affinity": aff["cached_tokens"],
        "cached_tokens_round_robin": rr["cached_tokens"],
        "affinity_hit_rate": round(
            aff["affinity_hits"] / max(aff["affinity_matchable"], 1), 3),
        "replay": replay_rep,
        "fleet_controller_recover_ratio": recover_ratio,
        "fleet_controller_actions": n_actions,
        "telemetry_export_overhead_pct": export_pct,
        "telemetry_scrape_age_s": scrape_age,
        "controller_replay": {"on": ctl_on, "off": ctl_off,
                              "fault": kill_spec},
        "config": {"requests": n_req, "sys_prompt": sys_len, "tail": tail,
                   "new_tokens": new, "replicas": 2},
    }


def _bench_telemetry_plane(model, sys_len, new):
    """(telemetry_export_overhead_pct, telemetry_scrape_age_s): the
    serving-step cost of having a live HTTP exporter + an active
    scraper against it (ISSUE 15), measured with the standard
    ``_telemetry_overhead_pct`` machinery — the same engine step runs
    bare and then with the plane fully on (server thread + 20 Hz
    scrape), so a regression in the exporter hot path shows up as a
    perf delta. The scrape age is the freshness of the last successful
    scrape at teardown — a scraper that cannot keep up shows a growing
    age long before it shows wrong numbers."""
    import numpy as np
    from paddle_tpu.inference import ContinuousServingEngine
    from paddle_tpu.profiler.exporter import TelemetryServer
    from paddle_tpu.profiler.scrape import FleetScraper

    eng = ContinuousServingEngine(
        model, max_batch_size=2, max_len=max(sys_len // 4, 16) + new + 8)
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, 1000,
                          (1, max(sys_len // 8, 4))).astype(np.int64)
    state = {"server": None, "scraper": None, "age": None}
    with eng:
        eng.generate(prompt, max_new_tokens=2, timeout=1800)   # warm

        def step():
            return eng.generate(prompt, max_new_tokens=2, timeout=1800)

        def setup():
            srv = TelemetryServer(instance="bench", port=0).start()
            sc = FleetScraper(endpoints={"bench": srv.address},
                              interval_s=0.05, stale_s=60.0)
            sc.start()
            state["server"], state["scraper"] = srv, sc

        def teardown():
            sc, srv = state["scraper"], state["server"]
            if sc is not None:
                sc.scrape_once()
                state["age"] = sc.last_scrape_age()
                sc.stop()
            if srv is not None:
                srv.stop()

        pct = _telemetry_overhead_pct(step, lambda r: None, steps=5,
                                      instrumented_step=step,
                                      setup=setup, teardown=teardown)
    age = state["age"]
    return pct, None if age is None else round(age, 4)


def _bench_fleet_replay(model, sys_len, tail, new, fault_spec=None,
                        controller=False):
    """Seeded bursty replay against a fresh 2-replica fleet: the
    goodput-under-burst / time-to-recover measurement rig (ISSUE 11;
    ROADMAP 4's controller gets judged by exactly these numbers). SLO
    TTFT target is adaptive — 2x a measured warm-path request — so the
    burst (not host speed) decides the violation story. ``fault_spec``
    installs a fleet fault plan (e.g. ``kill:replica=r1,request=4``)
    for the run; ``controller=True`` runs a ``FleetController`` beside
    the replay — the ISSUE-14 chaos pair compares the same seed with
    the controller off vs on."""
    import numpy as np
    from paddle_tpu.distributed import fault as flt
    from paddle_tpu.distributed.fleet.elastic.tcp_kv import MemKVStore
    from paddle_tpu.inference import FleetController, ServingRouter
    from paddle_tpu.inference.fleet import replay as rp
    from paddle_tpu.profiler import alerts, request_trace as rt
    from paddle_tpu.profiler import timeseries

    seed = int(os.environ.get("BENCH_REPLAY_SEED", "11"))
    duration = float(os.environ.get("BENCH_REPLAY_DURATION_S", "6"))
    trace = rp.make_trace(
        preset="bursty", seed=seed, duration_s=duration, rate_rps=0.7,
        burst_factor=float(os.environ.get("BENCH_REPLAY_BURST", "10")),
        burst_start_frac=0.35, burst_dur_frac=0.2,
        prompt_len=(8, min(sys_len, 24)), new_tokens=(2, max(new // 2, 2)))
    router = ServingRouter(
        model, num_replicas=2, store=MemKVStore(), heartbeat_ttl=600.0,
        engine_kwargs=dict(max_batch_size=2,
                           max_len=sys_len + tail + new + 16))
    hist = timeseries.MetricsHistory(capacity=4096)
    engine = alerts.AlertEngine(history=hist)
    engine.add_rule(alerts.BurnRateRule(
        budget=0.2, fast_window_s=1.5, slow_window_s=4.5, factor=1.0))
    engine.attach(hist)
    old_ttft = os.environ.get("PADDLE_SLO_TTFT_MS")
    ctl = None
    try:
        with router:
            warm = np.arange(16, dtype=np.int64)[None]
            router.generate(warm, max_new_tokens=2, timeout=1800)
            t0 = time.perf_counter()
            router.generate(warm + 16, max_new_tokens=2, timeout=1800)
            warm_s = time.perf_counter() - t0
            os.environ["PADDLE_SLO_TTFT_MS"] = str(
                round(max(2.0 * warm_s, 0.2) * 1e3, 1))
            rt.reset_slo_monitor()
            if fault_spec:
                flt.install(fault_spec)
            if controller:
                ctl = FleetController(
                    router, history=hist, alert_engine=engine,
                    cooldown_s=1.0, restart_backoff_s=0.2,
                    interval_s=0.1, degraded_max_new=0)
                ctl.start()
            harness = rp.ReplayHarness(
                router, trace, vocab_size=256, history=hist,
                alert_engine=engine, tick_interval_s=0.25,
                recover_window_s=1.5, budget=0.2, factor=1.0)
            rep = harness.run().as_dict()
            if ctl is not None:
                ctl.stop()
                rep["controller_actions_total"] = len(ctl.actions)
                rep["controller_actions_by_kind"] = {}
                for a in ctl.actions:
                    k = rep["controller_actions_by_kind"]
                    k[a.action] = k.get(a.action, 0) + 1
            if rep.get("burst_t") and rep.get("t_end") is not None:
                rep["observed_s"] = rep["t_end"] - rep["burst_t"][1]
    finally:
        if ctl is not None:
            ctl.stop()
        if fault_spec:
            flt.clear()
        engine.detach()
        if old_ttft is None:
            os.environ.pop("PADDLE_SLO_TTFT_MS", None)
        else:
            os.environ["PADDLE_SLO_TTFT_MS"] = old_ttft
        rt.reset_slo_monitor()
    keep = ("preset", "seed", "schedule_digest", "requests", "ok",
            "statuses", "goodput_under_burst", "p99_ttft_under_burst_s",
            "p99_latency_s", "time_to_recover_s", "burst_requests",
            "burst_ok", "alerts", "observed_s", "controller_actions_total",
            "controller_actions_by_kind")
    return {k: rep.get(k) for k in keep if k in rep}


# --------------------------------------------------------------------------
# Orchestration: never hang, never exit without a JSON line.
# --------------------------------------------------------------------------

def _emit_telemetry_snapshot(out):
    """Every bench run ships its telemetry: a one-line per-family summary
    on stderr plus a full JSONL snapshot (BENCH_TELEMETRY_JSONL path, or
    bench_telemetry.jsonl next to this file). Regressions in the
    observability layer itself are caught by ``telemetry_overhead_pct``
    riding on the resnet record."""
    try:
        from paddle_tpu.profiler.telemetry import get_registry
        reg = get_registry()
        snap = reg.collect()
        summary = {}
        for name, fam in snap.items():
            if fam["type"] == "histogram":
                summary[name] = {
                    k or "_": {"count": s["count"],
                               "p50_ms": round(s["p50"] * 1e3, 3),
                               "p99_ms": round(s["p99"] * 1e3, 3)}
                    for k, s in fam["series"].items()}
            else:
                summary[name] = {k or "_": v
                                 for k, v in fam["series"].items()}
        aux = {"aux_metric": "telemetry_snapshot"}
        hits = summary.get("paddle_serving_prefix_hits", {}).get("_", 0)
        misses = summary.get("paddle_serving_prefix_misses", {}).get("_", 0)
        if hits or misses:
            # prefix-cache regressions must show up in EVERY bench run
            aux["prefix_hit_rate"] = round(hits / max(hits + misses, 1), 3)
        aux["families"] = summary
        print(json.dumps(aux), file=sys.stderr)
        path = os.environ.get(
            "BENCH_TELEMETRY_JSONL",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "bench_telemetry.jsonl"))
        reg.export_jsonl(path, extra={"metric": out.get("metric"),
                                      "value": out.get("value")})
    except Exception as e:   # telemetry must never kill a bench record
        print(f"bench: telemetry snapshot skipped: {e}", file=sys.stderr)


def _child_main():
    mode = os.environ.get("BENCH_MODEL", "resnet")
    out = (bench_llama() if mode == "llama"
           else bench_llama_decode() if mode == "llama_decode"
           else bench_serving() if mode == "serving"
           else bench_fleet() if mode == "fleet"
           else bench_data() if mode == "data"
           else bench_dispatch() if mode == "dispatch"
           else bench_bert() if mode == "bert"
           else bench_comm() if mode == "comm"
           else bench_resnet())
    import jax
    out["backend"] = jax.devices()[0].platform.lower()
    _emit_telemetry_snapshot(out)
    print(json.dumps(out))
    return 0


def _run_child(env, timeout):
    """Run this file with BENCH_CHILD=1; return (json_dict|None, tail)."""
    env = dict(env)
    env["BENCH_CHILD"] = "1"
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired as e:
        out = e.output or ""
        if isinstance(out, bytes):
            out = out.decode("utf-8", "replace")
        return None, out[-2000:] + f"\n[timeout {timeout}s]"
    except OSError as e:
        return None, f"[spawn failed: {e}]"
    # relay aux lines (e.g. mfu) from the child's stderr
    if proc.stderr:
        sys.stderr.write(proc.stderr[-4000:])
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if "metric" in obj:
                return obj, ""
    tail = (proc.stdout[-1000:] + "\n" + proc.stderr[-1000:]).strip()
    return None, tail[-2000:] + f"\n[rc={proc.returncode}]"


def main():
    if os.environ.get("BENCH_CHILD") == "1":
        return _child_main()

    from __graft_entry__ import _probe_backend, _sanitized_cpu_env

    errors = []
    plat = _probe_backend(timeout=float(os.environ.get("BENCH_PROBE_TIMEOUT",
                                                       "180")))
    if plat is None:
        errors.append("backend probe failed/hung; skipping accelerator "
                      "attempts")
    elif plat == "cpu":
        # no accelerator to try — go straight to the CPU-sized workload
        # instead of burning the accelerator-sized attempts on host cores
        print("bench: default backend is cpu; running cpu-sized workload",
              file=sys.stderr)
        plat = None
    else:
        print(f"bench: probed default backend = {plat}", file=sys.stderr)
        # Prove this workload's Pallas kernels in disposable subprocesses
        # BEFORE the long-lived bench child exists (guarded_compile —
        # VERDICT.md round-2 weak #1: a hung first Mosaic compile must
        # never happen in a process we can't afford to lose).
        # BENCH_PROVE=0 skips proving entirely: round-4 evidence showed a
        # hung Mosaic compile wedges the remote tunnel SERVER-side — the
        # disposable subprocess protects this process but not the pool —
        # so zero-Mosaic sessions must not even attempt the canary.
        if os.environ.get("BENCH_PROVE", "1") == "0":
            # the jax production paged kernel is ALSO a Mosaic compile —
            # a zero-Mosaic session must pin decode to the pure-XLA tier,
            # not merely skip the in-repo proof
            os.environ.setdefault("PADDLE_TPU_PAGED_IMPL", "xla")
            print("bench: BENCH_PROVE=0 — skipping kernel proofs; "
                  "unproven Pallas kernels use their XLA fallbacks "
                  f"(paged impl={os.environ['PADDLE_TPU_PAGED_IMPL']})",
                  file=sys.stderr)
        else:
            try:
                from paddle_tpu.utils.guarded_compile import (bench_kernels,
                                                              prove_all)
                need = bench_kernels(os.environ.get("BENCH_MODEL", "resnet"))
                if need:
                    print(f"bench: proving kernels {need} in subprocess",
                          file=sys.stderr)
                    print(f"bench: kernel proofs: {prove_all(need)}",
                          file=sys.stderr)
            except Exception as e:   # guard must never kill the bench
                print(f"bench: kernel proving skipped: {e}", file=sys.stderr)
        for attempt, tmo in ((1, 1500), (2, 900)):
            obj, tail = _run_child(os.environ, tmo)
            if obj is not None:
                print(json.dumps(obj))
                return 0
            errors.append(f"{plat} attempt {attempt}: {tail}")
            print(f"bench: {plat} attempt {attempt} failed:\n{tail}",
                  file=sys.stderr)
            time.sleep(15)

    # CPU fallback: sanitized env, smaller default workload so it
    # finishes quickly on host cores.
    cpu_env = _sanitized_cpu_env(1)
    mode_ = os.environ.get("BENCH_MODEL", "resnet")
    # per-model CPU sizing: BERT-base fwd+bwd at batch 64 never finishes
    # a 5-step run inside the child timeout on one host core (the
    # round-4 'bert: timeout 1200s' null) — a small batch still yields a
    # valid ms/step datum
    cpu_env.setdefault("BENCH_BATCH", {"llama": "2", "bert": "4"}
                       .get(mode_, "64"))
    cpu_env.setdefault("BENCH_STEPS", "3" if mode_ == "bert" else "5")
    cpu_env.setdefault("BENCH_SEQ", "128" if mode_ == "bert" else "512")
    cpu_env["BENCH_AMP"] = os.environ.get("BENCH_AMP", "0")
    # the serving bench runs many engine phases (prefix on/off, ragged
    # vs legacy, spec, int8, compile probe, kv tier, long context) —
    # on a 1-core host the sum clears 1200s even though each phase is
    # small; give it the same headroom ratio the tier-1 suite got
    obj, tail = _run_child(cpu_env, 2400 if mode_ == "serving" else 1200)
    if obj is not None:
        if errors:
            obj["note"] = "cpu fallback: " + " | ".join(e.splitlines()[0]
                                                        for e in errors)[:400]
            # a wedged tunnel at measurement time must not hide earlier
            # on-chip evidence — point the record at the newest session
            # pack, and only when that pack actually holds a successful
            # on-chip run of THIS metric
            import glob
            here = os.path.dirname(os.path.abspath(__file__))
            packs = sorted(glob.glob(os.path.join(here,
                                                  "BENCH_TPU_SESSION*.json")),
                           key=os.path.getmtime, reverse=True)
            for pack in packs:     # newest first; first pack with a hit wins
                try:
                    with open(pack) as f:
                        data = json.load(f)
                    rows = data.get("results",
                                    data if isinstance(data, list) else [])
                    # rows are either wrapped {"label", "result": {...}}
                    # (R4 pack) or flat {...} (round-2 session file)
                    flat = [r.get("result", r) for r in rows
                            if isinstance(r, dict)]
                    hit = any(r.get("metric") == obj.get("metric")
                              and r.get("backend") == "tpu"
                              and r.get("value") is not None for r in flat)
                except Exception:
                    hit = False
                if hit:
                    obj["on_chip_evidence"] = os.path.basename(pack)
                    break
        print(json.dumps(obj))
        return 0
    errors.append(f"cpu fallback: {tail}")

    mode = os.environ.get("BENCH_MODEL", "resnet")
    print(json.dumps({
        "metric": ("llama_1b_train_tokens_per_sec" if mode == "llama"
                   else "llama_paged_decode_tokens_per_sec"
                   if mode == "llama_decode"
                   else "serving_prefix_ttft_speedup" if mode == "serving"
                   else "fleet_affinity_ttft_speedup" if mode == "fleet"
                   else "dataloader_hbm_samples_per_sec" if mode == "data"
                   else "eager_dispatch_overhead_vs_jax"
                   if mode == "dispatch"
                   else "bert_base_finetune_step_ms" if mode == "bert"
                   else "dp_allreduce_wire_bytes" if mode == "comm"
                   else "resnet50_cifar10_train_throughput"),
        "value": None,
        "unit": ("tokens/sec" if mode in ("llama", "llama_decode")
                 else "samples/sec" if mode == "data"
                 else "x" if mode in ("dispatch", "serving", "fleet")
                 else "ms/step" if mode == "bert"
                 else "bytes" if mode == "comm"
                 else "images/sec"),
        "vs_baseline": None,
        "error": (" || ".join(e.replace("\n", " ")[:300]
                              for e in errors))[:1200],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
