"""Benchmark: ResNet-50 / CIFAR-10 training throughput (BASELINE.json config 1).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is null — the reference mount is empty and BASELINE.json
records no published numbers (SURVEY.md §6); this run IS the baseline.
"""
from __future__ import annotations

import json
import os
import sys
import time


def main():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.vision.models import resnet50
    from paddle_tpu.framework.functional import FunctionalModule

    batch = int(os.environ.get("BENCH_BATCH", "256"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))

    paddle.seed(0)
    model = resnet50(num_classes=10)
    model.train()
    fm = FunctionalModule(model, training=True)
    p_arrs = fm.param_arrays()
    b_arrs = fm.buffer_arrays()
    key = fm.next_key()

    x = jnp.ones((batch, 3, 32, 32), jnp.float32)
    y = jnp.zeros((batch,), jnp.int32)

    def train_step(p_arrs, b_arrs, key, x, y):
        def loss_fn(ps):
            logits, new_b = fm(ps, b_arrs, key, x)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            loss = -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()
            return loss, new_b

        (loss, new_b), grads = jax.value_and_grad(loss_fn, has_aux=True)(p_arrs)
        new_p = [p - 0.05 * g for p, g in zip(p_arrs, grads)]
        return loss, new_p, new_b

    step = jax.jit(train_step, donate_argnums=(0, 1))

    # warmup / compile
    loss, p_arrs, b_arrs = step(p_arrs, b_arrs, key, x, y)
    loss.block_until_ready()

    t0 = time.perf_counter()
    for _ in range(steps):
        loss, p_arrs, b_arrs = step(p_arrs, b_arrs, key, x, y)
    loss.block_until_ready()
    dt = time.perf_counter() - t0

    ips = batch * steps / dt
    print(json.dumps({
        "metric": "resnet50_cifar10_train_throughput",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": None,
    }))


if __name__ == "__main__":
    sys.exit(main())
