"""Benchmark driver. Default: ResNet-50 / CIFAR-10 training throughput
(BASELINE.json config 1). ``BENCH_MODEL=llama`` benches the flagship
Llama train step (tokens/sec).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is null — the reference mount is empty and BASELINE.json
records no published numbers (SURVEY.md §6); this run IS the baseline.

``BENCH_AMP=1`` (default on TPU) uses the reference's AMP-O2 recipe mapped
to TPU: fp32 master params, bf16 compute (cast at step entry) — the MXU's
native dtype.
"""
from __future__ import annotations

import json
import os
import sys
import time


def _amp_enabled():
    import jax
    default = "1" if jax.default_backend() == "tpu" else "0"
    return os.environ.get("BENCH_AMP", default) == "1"


def bench_resnet():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.vision.models import resnet50
    from paddle_tpu.framework.functional import FunctionalModule

    batch = int(os.environ.get("BENCH_BATCH", "256"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    amp = _amp_enabled()

    paddle.seed(0)
    model = resnet50(num_classes=10)
    model.train()
    fm = FunctionalModule(model, training=True)
    p_arrs = fm.param_arrays()
    b_arrs = fm.buffer_arrays()
    key = fm.next_key()

    x = jnp.ones((batch, 3, 32, 32),
                 jnp.bfloat16 if amp else jnp.float32)
    y = jnp.zeros((batch,), jnp.int32)

    def train_step(p_arrs, b_arrs, key, x, y):
        def loss_fn(ps):
            cps = [a.astype(jnp.bfloat16) if amp and a.dtype == jnp.float32
                   else a for a in ps]
            logits, new_b = fm(cps, b_arrs, key, x)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            loss = -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()
            return loss, new_b

        (loss, new_b), grads = jax.value_and_grad(loss_fn, has_aux=True)(p_arrs)
        new_p = [p - 0.05 * g.astype(p.dtype) for p, g in zip(p_arrs, grads)]
        return loss, new_p, new_b

    step = jax.jit(train_step, donate_argnums=(0, 1))
    loss, p_arrs, b_arrs = step(p_arrs, b_arrs, key, x, y)   # compile
    loss.block_until_ready()

    t0 = time.perf_counter()
    for _ in range(steps):
        loss, p_arrs, b_arrs = step(p_arrs, b_arrs, key, x, y)
    loss.block_until_ready()
    dt = time.perf_counter() - t0
    return {
        "metric": "resnet50_cifar10_train_throughput",
        "value": round(batch * steps / dt, 2),
        "unit": "images/sec",
        "vs_baseline": None,
    }


def bench_llama():
    """Flagship single-chip Llama train-step bench (tokens/sec); exercises
    the Pallas flash-attention path + AMP master weights."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.framework.functional import FunctionalModule

    batch = int(os.environ.get("BENCH_BATCH", "4"))
    seq = int(os.environ.get("BENCH_SEQ", "1024"))
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    amp = _amp_enabled()

    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                      intermediate_size=2816, num_hidden_layers=8,
                      num_attention_heads=16, num_key_value_heads=8,
                      max_position_embeddings=max(2048, seq))
    model = LlamaForCausalLM(cfg)
    model.train()
    fm = FunctionalModule(model, training=True)
    p_arrs = fm.param_arrays()
    key = fm.next_key()
    import numpy as np
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                         jnp.int32)

    def train_step(p_arrs, key, ids, labels):
        def loss_fn(ps):
            cps = [a.astype(jnp.bfloat16) if amp and a.dtype == jnp.float32
                   else a for a in ps]
            (loss, _), _ = fm(cps, [], key, ids, labels=labels)
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(p_arrs)
        new_p = [p - 1e-4 * g.astype(p.dtype) for p, g in zip(p_arrs, grads)]
        return loss, new_p

    step = jax.jit(train_step, donate_argnums=(0,))
    loss, p_arrs = step(p_arrs, key, ids, labels)
    loss.block_until_ready()

    t0 = time.perf_counter()
    for _ in range(steps):
        loss, p_arrs = step(p_arrs, key, ids, labels)
    loss.block_until_ready()
    dt = time.perf_counter() - t0

    from paddle_tpu.profiler.mfu import llama_train_flops, PEAK_FLOPS
    flops = llama_train_flops(cfg, batch, seq)
    chip = os.environ.get("BENCH_CHIP", "v5p")
    mfu = flops * steps / dt / PEAK_FLOPS.get(chip, PEAK_FLOPS["v5p"])
    print(json.dumps({"aux_metric": "mfu_" + chip,
                      "value": round(mfu * 100, 2), "unit": "%"}),
          file=sys.stderr)
    return {
        "metric": "llama_1b_train_tokens_per_sec",
        "value": round(batch * seq * steps / dt, 2),
        "unit": "tokens/sec",
        "vs_baseline": None,
    }


def main():
    mode = os.environ.get("BENCH_MODEL", "resnet")
    out = bench_llama() if mode == "llama" else bench_resnet()
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
