"""paddle.geometric — graph learning primitives (reference:
``python/paddle/geometric/`` — ``math.py`` segment ops backed by phi
``segment_pool`` kernels, ``message_passing/send_recv.py``
``send_u_recv``/``send_ue_recv``/``send_uv`` backed by
``graph_send_recv`` kernels).

TPU-native: every op is a jnp ``segment_*`` / gather composition — XLA
lowers the unsorted-segment reductions to efficient one-hot matmuls or
scatters on the MXU, which is exactly how GNN aggregation is done on TPU
(no CUDA atomic-scatter kernel needed). ``out_size``/``num_segments``
must be static under jit (pass it explicitly inside traced code).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .framework.core import Tensor
from .autograd.tape import apply

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "send_u_recv", "send_ue_recv", "send_uv"]


def _n_segments(ids, out_size):
    if out_size is not None:
        return int(out_size)
    arr = ids._data if isinstance(ids, Tensor) else jnp.asarray(ids)
    if isinstance(arr, jax.core.Tracer):
        raise ValueError(
            "segment op under jit: the output size is data-dependent — "
            "pass num_segments explicitly")
    return int(jax.device_get(arr.max())) + 1 if arr.size else 0


def _segment(x, ids, num, op):
    def fn(a, i):
        return _segment_raw(a, i, num, op)
    return apply(fn, x, ids, op_name=f"segment_{op}")


# num_segments is an extension kwarg over the reference signature: the
# output row count is data-dependent (max id + 1), which cannot be derived
# under a jit trace — pass it explicitly in traced code.

def segment_sum(data, segment_ids, num_segments=None, name=None):
    return _segment(data, segment_ids,
                    _n_segments(segment_ids, num_segments), "sum")


def segment_mean(data, segment_ids, num_segments=None, name=None):
    return _segment(data, segment_ids,
                    _n_segments(segment_ids, num_segments), "mean")


def segment_max(data, segment_ids, num_segments=None, name=None):
    return _segment(data, segment_ids,
                    _n_segments(segment_ids, num_segments), "max")


def segment_min(data, segment_ids, num_segments=None, name=None):
    return _segment(data, segment_ids,
                    _n_segments(segment_ids, num_segments), "min")


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather source-node features along edges, reduce at destinations.
    Default output row count is ``x.shape[0]`` (the reference's
    node-count semantics), so edge-less nodes keep their zero row."""
    num = int(x.shape[0]) if out_size is None else int(out_size)

    def fn(a, src, dst):
        msgs = jnp.take(a, src.astype(jnp.int32), axis=0)
        return _segment_raw(msgs, dst, num, reduce_op)
    return apply(fn, x, src_index, dst_index, op_name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine source-node features with edge features, reduce at
    destinations (message_op: add | sub | mul | div). Default output row
    count is ``x.shape[0]`` like the reference."""
    num = int(x.shape[0]) if out_size is None else int(out_size)

    def fn(a, e, src, dst):
        msgs = jnp.take(a, src.astype(jnp.int32), axis=0)
        if message_op == "add":
            msgs = msgs + e
        elif message_op == "sub":
            msgs = msgs - e
        elif message_op == "mul":
            msgs = msgs * e
        elif message_op == "div":
            msgs = msgs / e
        else:
            raise ValueError(message_op)
        return _segment_raw(msgs, dst, num, reduce_op)
    return apply(fn, x, y, src_index, dst_index, op_name="send_ue_recv")


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from source and destination node features."""

    def fn(a, b, src, dst):
        u = jnp.take(a, src.astype(jnp.int32), axis=0)
        v = jnp.take(b, dst.astype(jnp.int32), axis=0)
        if message_op == "add":
            return u + v
        if message_op == "sub":
            return u - v
        if message_op == "mul":
            return u * v
        if message_op == "div":
            return u / v
        raise ValueError(message_op)
    return apply(fn, x, y, src_index, dst_index, op_name="send_uv")


def _segment_raw(msgs, dst, num, reduce_op):
    dst = dst.astype(jnp.int32)
    if reduce_op == "sum":
        return jax.ops.segment_sum(msgs, dst, num)
    shape = (num,) + (1,) * (msgs.ndim - 1)
    cnt = jax.ops.segment_sum(jnp.ones((msgs.shape[0],), jnp.float32),
                              dst, num).reshape(shape)
    if reduce_op == "mean":
        s = jax.ops.segment_sum(msgs, dst, num)
        return s / jnp.maximum(cnt, 1.0).astype(msgs.dtype)
    if reduce_op in ("max", "min"):
        out = (jax.ops.segment_max if reduce_op == "max"
               else jax.ops.segment_min)(msgs, dst, num)
        # empty segments: reference returns 0 (count mask — dtype-safe for
        # ints, where isfinite would never fire)
        return jnp.where(cnt > 0, out, jnp.zeros((), msgs.dtype))
    raise ValueError(reduce_op)
