"""paddle.Model — high-level API (reference: ``python/paddle/hapi/model.py`` —
fit/evaluate/predict + callbacks; SURVEY.md §2.2)."""
from __future__ import annotations

import os
import time

import numpy as np

from .framework.core import Tensor
from .framework import io as fio
from .io import DataLoader, Dataset
from .metric import Metric
from .profiler import step_phase as _step_phase


def _pad_rows(x, target):
    """Pad the leading (batch) dim up to ``target`` by repeating the last
    sample. Inputs only — labels are never padded (outputs are sliced
    back before the loss sees them)."""
    if isinstance(x, (list, tuple)):
        return type(x)(_pad_rows(v, target) for v in x)
    if isinstance(x, Tensor) and x.ndim > 0 and x.shape[0] < target:
        arr = np.asarray(x.numpy())
        pad = np.repeat(arr[-1:], target - arr.shape[0], axis=0)
        return Tensor(np.concatenate([arr, pad]))
    return x


def _slice_rows(out, n):
    """Drop pad rows from network outputs (backward sends the pad rows a
    zero cotangent, so gradients match the unpadded batch)."""
    if isinstance(out, (list, tuple)):
        return type(out)(_slice_rows(v, n) for v in out)
    if isinstance(out, Tensor) and out.ndim > 0 and out.shape[0] > n:
        return out[:n]
    return out


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []

    # -- trailing-partial-batch shape bucketing ------------------------------
    def _pad_partial_enabled(self):
        """Pad the last (smaller) batch of each epoch up to the compiled
        spec instead of tracing a second program per epoch. Only engages
        where it matters (a @to_static network — eager nets don't compile
        per spec) and where it is numerically safe (no batch-coupled
        normalization whose statistics would see the pad rows)."""
        if getattr(self.network, "_static_forward", None) is None:
            return False
        net = self.network
        subs = (net.sublayers(include_self=True)
                if hasattr(net, "sublayers") else [net])
        return not any("BatchNorm" in type(l).__name__ for l in subs)

    def _maybe_pad_partial(self, x, st):
        if not st["enabled"]:
            return x, None
        lead = x[0] if isinstance(x, (list, tuple)) else x
        if not isinstance(lead, Tensor) or lead.ndim == 0:
            return x, None
        n = lead.shape[0]
        if st["spec"] is None:       # first batch defines the compiled spec
            st["spec"] = n
            return x, None
        if n >= st["spec"]:
            return x, None
        return _pad_rows(x, st["spec"]), n

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        metrics = metrics or []
        self._metrics = metrics if isinstance(metrics, (list, tuple)) else [metrics]

    def _unpack(self, batch):
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            *inputs, label = batch
            if len(inputs) == 1:
                return inputs[0], label
            return inputs, label
        return batch, None

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        with _step_phase.span("forward"):
            out = self.network(*inputs) \
                if isinstance(inputs, (list, tuple)) \
                else self.network(inputs)
            loss = self._loss(out, labels) if self._loss else out
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        return [loss.numpy()]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        out = self.network(*inputs) if isinstance(inputs, (list, tuple)) \
            else self.network(inputs)
        loss = self._loss(out, labels) if self._loss else out
        return [loss.numpy()]

    def predict_batch(self, inputs):
        self.network.eval()
        out = self.network(*inputs) if isinstance(inputs, (list, tuple)) \
            else self.network(inputs)
        return [out.numpy() if isinstance(out, Tensor) else out]

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        loader = train_data if isinstance(train_data, DataLoader) else DataLoader(
            train_data, batch_size=batch_size, shuffle=shuffle,
            drop_last=drop_last, num_workers=num_workers)
        from .callbacks import CallbackList, EarlyStopping
        cbs = CallbackList(callbacks, model=self,
                           params={"epochs": epochs, "batch_size": batch_size,
                                   "verbose": verbose})
        for c in cbs.callbacks:     # early-stop best-model dir
            if isinstance(c, EarlyStopping) and c.save_dir is None:
                c.save_dir = save_dir
        cbs.on_train_begin({})
        it = 0
        pad_state = {"enabled": self._pad_partial_enabled(), "spec": None}
        for epoch in range(epochs):
            self.network.train()
            for m in self._metrics:
                m.reset()
            cbs.on_epoch_begin(epoch, {})
            t0 = time.time()
            logs = {}
            have_cbs = bool(cbs.callbacks)
            from .callbacks import ProgBarLogger
            own_print = verbose and not any(
                isinstance(c, ProgBarLogger) for c in cbs.callbacks)
            for step, batch in enumerate(loader):
                if have_cbs:
                    cbs.on_train_batch_begin(step, {})
                x, y = self._unpack(batch)
                x, true_n = self._maybe_pad_partial(x, pad_state)
                with _step_phase.span("forward"):
                    out = self.network(x)
                    if true_n is not None:
                        out = _slice_rows(out, true_n)
                    loss = self._loss(out, y) if self._loss else out
                loss.backward()
                if (step + 1) % accumulate_grad_batches == 0:
                    self._optimizer.step()
                    self._optimizer.clear_grad()
                for m in self._metrics:
                    m.update(m.compute(out, y))
                it += 1
                # logs force a device sync (loss.numpy()) — only when someone
                # consumes them, to keep async dispatch pipelined on TPU
                if have_cbs:
                    logs = {"loss": float(loss.numpy())}
                    logs.update({m.name(): m.accumulate()
                                 for m in self._metrics})
                    cbs.on_train_batch_end(step, logs)
                if own_print and step % log_freq == 0:
                    metr = {m.name(): m.accumulate() for m in self._metrics}
                    print(f"Epoch {epoch + 1}/{epochs} step {step} "
                          f"loss: {float(loss.numpy()):.4f} {metr} "
                          f"({(time.time() - t0) / (step + 1):.3f}s/step)")
                if num_iters is not None and it >= num_iters:
                    cbs.on_epoch_end(epoch, logs)
                    cbs.on_train_end(logs)
                    return
            cbs.on_epoch_end(epoch, logs)
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                cbs.on_eval_begin({})
                ev = self.evaluate(eval_data, batch_size=batch_size,
                                   verbose=verbose)
                cbs.on_eval_end(ev)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, f"epoch_{epoch}"))
            if cbs.stop_training:
                break
        cbs.on_train_end({})

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else DataLoader(
            eval_data, batch_size=batch_size, num_workers=num_workers)
        self.network.eval()
        for m in self._metrics:
            m.reset()
        losses = []
        from .autograd import no_grad
        with no_grad():
            for step, batch in enumerate(loader):
                x, y = self._unpack(batch)
                out = self.network(x)
                if self._loss:
                    losses.append(float(self._loss(out, y).numpy()))
                for m in self._metrics:
                    m.update(m.compute(out, y))
                if num_iters is not None and step + 1 >= num_iters:
                    break
        result = {m.name(): m.accumulate() for m in self._metrics}
        if losses:
            result["loss"] = float(np.mean(losses))
        if verbose:
            print("Eval:", result)
        return result

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else DataLoader(
            test_data, batch_size=batch_size, num_workers=num_workers)
        self.network.eval()
        outputs = []
        from .autograd import no_grad
        with no_grad():
            for batch in loader:
                x, _ = self._unpack(batch)
                outputs.append(self.predict_batch([x])[0])
        if stack_outputs:
            return [np.concatenate(outputs)]
        return [outputs]

    def save(self, path, training=True):
        fio.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fio.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = fio.load(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None \
                and os.path.exists(opt_path):
            self._optimizer.set_state_dict(fio.load(opt_path))

    def parameters(self):
        return self.network.parameters()


def summary(net, input_size=None, dtypes=None, input=None):
    """paddle.summary — parameter counting table."""
    rows = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = p.size
        total += n
        if p.trainable:
            trainable += n
        rows.append((name, tuple(p.shape), n))
    width = max((len(r[0]) for r in rows), default=20) + 2
    lines = [f"{'Layer (param)':<{width}}{'Shape':<24}{'Param #':>12}"]
    lines += [f"{r[0]:<{width}}{str(r[1]):<24}{r[2]:>12,}" for r in rows]
    lines.append(f"Total params: {total:,}")
    lines.append(f"Trainable params: {trainable:,}")
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """paddle.flops (reference ``python/paddle/hapi/dynamic_flops.py``):
    per-layer FLOP counting via forward hooks over one dummy forward.
    Returns total FLOPs; ``custom_ops`` maps Layer classes to
    ``fn(layer, input, output) -> flops``."""
    import numpy as np
    from .framework.core import Tensor

    custom_ops = custom_ops or {}
    counts = []     # (layer name path, class, flops, params)
    seen_params = set()          # layers whose params were already counted

    def _n(shape):
        return int(np.prod([s for s in shape if s]))

    def count(layer, inp, out):
        x = inp[0] if isinstance(inp, (tuple, list)) else inp
        y = out[0] if isinstance(out, (tuple, list)) else out
        cls = type(layer)
        if cls in custom_ops:
            return custom_ops[cls](layer, inp, out)
        name = cls.__name__
        # reference dynamic_flops convention: one MAC = 1 FLOP, bias
        # counted (count_convNd: out_numel * (Cin/g*K + bias))
        if name in ("Conv2D", "Conv1D", "Conv3D", "Conv2DTranspose",
                    "Conv1DTranspose", "Conv3DTranspose"):
            k = _n(layer._kernel_size)
            cin = layer._in_channels // getattr(layer, "_groups", 1)
            bias = 1 if getattr(layer, "bias", None) is not None else 0
            return _n(y.shape) * (cin * k + bias)
        if name == "Linear":
            in_f = layer.weight.shape[0]
            bias = 1 if getattr(layer, "bias", None) is not None else 0
            return _n(y.shape) * (in_f + bias)
        if name in ("BatchNorm2D", "BatchNorm1D", "BatchNorm3D",
                    "BatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm2D"):
            return 2 * _n(x.shape)
        if name in ("ReLU", "ReLU6", "GELU", "Sigmoid", "Tanh", "Hardswish",
                    "Hardsigmoid", "SiLU", "Silu", "Swish", "LeakyReLU",
                    "Softmax"):
            return _n(y.shape)
        if "Pool" in name:
            return _n(y.shape)
        return 0

    handles = []
    is_leaf = lambda l: not list(l.children())

    def attach(layer, prefix=""):
        for n, child in layer.named_children():
            path = f"{prefix}.{n}" if prefix else n
            if is_leaf(child):
                def hook(l, i, o, _p=path):
                    fl = count(l, i, o)
                    params = 0
                    if id(l) not in seen_params:   # shared layers: once
                        seen_params.add(id(l))
                        params = sum(p.size for p in l.parameters())
                    counts.append((_p, type(l).__name__, fl, params))
                handles.append(child.register_forward_post_hook(hook))
            else:
                attach(child, path)
    attach(net)
    if not handles and is_leaf(net):
        # the net itself is a single leaf layer (paddle.flops(conv, ...))
        def root_hook(l, i, o):
            fl = count(l, i, o)
            params = 0
            if id(l) not in seen_params:
                seen_params.add(id(l))
                params = sum(p.size for p in l.parameters())
            counts.append(("(root)", type(l).__name__, fl, params))
        handles.append(net.register_forward_post_hook(root_hook))

    # snapshot per-layer training flags: a blanket net.train() after
    # would flip deliberately-frozen sublayers (e.g. frozen BN) to train
    modes = [(l, l.training) for l in net.sublayers(include_self=True)] \
        if hasattr(net, "sublayers") else [(net, net.training)]
    net.eval()
    try:
        x = Tensor(np.zeros(input_size, np.float32))
        net(x)
    finally:
        for h in handles:
            h.remove()
        for l, was in modes:
            l.training = was

    total = sum(c[2] for c in counts)
    total_params = sum(c[3] for c in counts)
    if print_detail:
        width = max((len(c[0]) for c in counts), default=20) + 2
        print(f"{'Layer':<{width}}{'Type':<18}{'FLOPs':>16}{'Params':>12}")
        for path, tname, fl, pr in counts:
            print(f"{path:<{width}}{tname:<18}{fl:>16,}{pr:>12,}")
        print(f"Total GFLOPs: {total / 1e9:.4f}")
        print(f"Total params: {total_params:,}")
    return total
