"""Mixture-of-Experts with expert parallelism (reference:
``python/paddle/incubate/distributed/models/moe/`` — ``MoELayer`` with
``NaiveGate``/``SwitchGate``/``GShardGate``, dispatch via the
``global_scatter``/``global_gather`` all-to-all collective ops; SURVEY.md
§2.3 "EP").

TPU-native design: the reference's scatter/gather pair is an explicit NCCL
all-to-all moving each token to its expert's rank. Here dispatch is the
GShard einsum formulation — tokens → one-hot dispatch/combine tensors →
``[experts, capacity, d]`` batches — with the expert dim sharded over a mesh
axis (default 'dp': expert parallelism over the data-parallel group, the
reference's default ep group). XLA's SPMD partitioner lowers the resharding
of the expert dim to exactly that all-to-all over ICI. Experts are a single
stacked-weight FFN (``[E, d, d_hidden]`` einsum) so the per-expert matmuls
stay batched on the MXU instead of a Python loop over small matmuls.

Static shapes: capacity ``C = ceil(tokens * cap_factor * top_k / E)`` bounds
each expert's batch; overflow tokens are dropped (combine weight 0), matching
the reference's capacity semantics.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .....framework.core import Tensor
from .....autograd.tape import apply, no_grad
from .....nn.layer import Layer, LayerList
from .....nn.initializer import XavierUniform
from ..... import flags  # noqa: F401
from .....distributed import mesh as mesh_mod

__all__ = ["MoELayer", "NaiveGate", "SwitchGate", "GShardGate", "ExpertFFN",
           "plan_dispatch", "dispatch_combine", "ep_axis_for",
           "moe_capacity"]


def ep_axis_for(num_experts, ep_axis="dp"):
    """The mesh axis to shard the expert dim over, or None: requires an
    installed mesh whose ``ep_axis`` is >1 AND divides ``num_experts``
    (4 experts over a dp=8 axis must replicate, not crash at lowering).
    The single EP-eligibility policy for every MoE caller."""
    if not ep_axis or not mesh_mod.has_mesh():
        return None
    n = mesh_mod.axis_size(ep_axis)
    return ep_axis if n > 1 and num_experts % n == 0 else None


def moe_capacity(n_tokens, num_experts, top_k, capacity_factor):
    """Static per-expert capacity ``C = ceil(S·cf·k/E)`` (≥1)."""
    return max(1, math.ceil(n_tokens * capacity_factor * top_k
                            / num_experts))


def plan_dispatch(logits, capacity, top_k):
    """GShard dispatch plan (pure jnp, static shapes): router logits
    [S, E] → (softmax probs [S, E], dispatch one-hot [S, E, C], combine
    weights [S, E, C]). Shared by :class:`MoELayer` and the model-zoo
    sparse blocks (models/mixtral.py) so the routing math lives once."""
    s, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    _, top_idx = jax.lax.top_k(probs, top_k)             # [S, k]
    # one-hot per choice: [k, S, E]
    choice = jax.nn.one_hot(top_idx.T, e, dtype=jnp.float32)
    # position of each (choice, token) within its expert queue — cumsum
    # ordered by choice rank then token index (reference: gshard ordering)
    flat = choice.reshape(-1, e)                          # [k*S, E]
    pos = jnp.cumsum(flat, axis=0) - flat                 # rank in queue
    pos = jnp.sum(pos * flat, axis=-1)                    # [k*S]
    keep = (pos < capacity) & (jnp.sum(flat, -1) > 0)
    pos = pos.astype(jnp.int32)
    pos_oh = jax.nn.one_hot(jnp.where(keep, pos, capacity), capacity,
                            dtype=jnp.float32)            # [k*S, C]
    disp = flat[:, :, None] * pos_oh[:, None, :]          # [k*S, E, C]
    disp = disp.reshape(top_k, s, e, capacity).sum(0)
    gate_w = jnp.sum(choice.reshape(top_k, s, e) *
                     probs[None], axis=-1)                # [k, S]
    # per-token weight to each chosen expert (top-k indices are distinct,
    # so summing over k is exact), normalized over the token's top-k
    w = jnp.einsum("ks,kse->se", gate_w,
                   choice.reshape(top_k, s, e))           # [S, E]
    w = w / jnp.maximum(w.sum(-1, keepdims=True), 1e-9)
    combine = disp * w[:, :, None]
    return probs, disp, combine


def dispatch_combine(tok, logits, capacity, top_k, expert_fn, ep_axis=None,
                     tracer_ref=None):
    """Full MoE data path around :func:`plan_dispatch`: tokens [S, d] →
    expert batches [E, C, d] (EP-constrained over ``ep_axis`` when given
    and tracing) → ``expert_fn`` → combined output [S, d]. Returns
    ``(out, probs, dispatched_frac)`` so callers derive their own aux
    loss. Shared by :class:`MoELayer` and models/mixtral.py."""
    probs, disp, combine = plan_dispatch(logits, capacity, top_k)
    expert_in = jnp.einsum("sec,sd->ecd", disp, tok)
    if ep_axis and isinstance(tracer_ref, jax.core.Tracer):
        expert_in = jax.lax.with_sharding_constraint(
            expert_in, mesh_mod.sharding(ep_axis, None, None))
    expert_out = expert_fn(expert_in)
    out = jnp.einsum("sec,ecd->sd", combine, expert_out)
    frac = jnp.mean(disp.sum(-1), axis=0)               # [E]
    return out, probs, frac


# ---------------------------------------------------------------------------
# Gates
# ---------------------------------------------------------------------------

class BaseGate(Layer):
    def __init__(self, d_model, num_experts, top_k):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.top_k = top_k
        self.weight = self.create_parameter(
            [d_model, num_experts], default_initializer=XavierUniform())
        self.loss = None          # aux load-balance loss (Tensor) after fwd

    def gate_logits(self, x):
        from .....ops import math as pmath
        return pmath.matmul(x, self.weight)


class NaiveGate(BaseGate):
    """Plain top-k softmax gate, no aux loss (reference NaiveGate)."""

    def __init__(self, d_model, num_expert=None, world_size=None, top_k=2,
                 num_experts=None, **kw):
        e = num_experts if num_experts is not None else (
            (num_expert or 1) * (world_size or 1))
        super().__init__(d_model, e, top_k)

    def aux_loss(self, probs, dispatch_frac):
        return None


class GShardGate(NaiveGate):
    """Top-2 gate with GShard load-balance aux loss:
    ``E * mean(probs_e) · mean(frac_dispatched_e)`` summed over experts."""

    def __init__(self, d_model, num_expert=None, world_size=None, top_k=2,
                 balance_loss_weight=1.0, **kw):
        super().__init__(d_model, num_expert, world_size, top_k, **kw)
        self.balance_loss_weight = balance_loss_weight

    def aux_loss(self, probs, dispatch_frac):
        e = self.num_experts
        return self.balance_loss_weight * e * jnp.sum(
            jnp.mean(probs, axis=0) * dispatch_frac)


class SwitchGate(GShardGate):
    """Top-1 switch-transformer gate (same aux-loss form, k=1)."""

    def __init__(self, d_model, num_expert=None, world_size=None, top_k=1,
                 **kw):
        super().__init__(d_model, num_expert, world_size, top_k=1, **kw)


GATES = {"naive": NaiveGate, "gshard": GShardGate, "switch": SwitchGate}


# ---------------------------------------------------------------------------
# Experts
# ---------------------------------------------------------------------------

class ExpertFFN(Layer):
    """All experts' FFNs as stacked weights [E, d, dh]/[E, dh, d] — one
    batched einsum per projection (MXU-friendly), expert dim shardable."""

    def __init__(self, num_experts, d_model, d_hidden, activation="gelu"):
        super().__init__()
        self.num_experts = num_experts
        self.w1 = self.create_parameter([num_experts, d_model, d_hidden],
                                        default_initializer=XavierUniform())
        self.b1 = self.create_parameter([num_experts, 1, d_hidden],
                                        is_bias=True)
        self.w2 = self.create_parameter([num_experts, d_hidden, d_model],
                                        default_initializer=XavierUniform())
        self.b2 = self.create_parameter([num_experts, 1, d_model],
                                        is_bias=True)
        self.activation = activation

    def forward_arrays(self, x, w1, b1, w2, b2):
        """x: [E, C, d] (jax arrays; called inside the MoE apply region)."""
        h = jnp.einsum("ecd,edh->ech", x, w1) + b1
        h = jax.nn.gelu(h) if self.activation == "gelu" else jax.nn.relu(h)
        return jnp.einsum("ech,ehd->ecd", h, w2) + b2


# ---------------------------------------------------------------------------
# MoE layer
# ---------------------------------------------------------------------------

class MoELayer(Layer):
    """paddle.incubate.distributed.models.moe.MoELayer.

    Args (reference-compatible subset): ``d_model``, ``experts`` (a LayerList
    of per-expert Layers — looped; or None to use the fused ``ExpertFFN``),
    ``gate`` (name or Layer), ``top_k``, ``capacity_factor``; plus TPU-native
    ``num_experts``/``d_hidden`` for the fused path and ``ep_axis`` (mesh axis
    carrying the expert dim; default 'dp' = reference's default ep group).
    ``forward`` returns the combined output; the gate's aux loss is in
    ``self.aux_loss`` (add it to the training loss).
    """

    def __init__(self, d_model=None, experts=None, gate="gshard", top_k=2,
                 capacity_factor=1.25, num_experts=None, d_hidden=None,
                 ep_axis="dp", moe_group=None, mp_group=None, **kw):
        super().__init__()
        if isinstance(gate, dict):      # reference passes a config dict
            top_k = gate.get("top_k", top_k)
            gate = gate.get("type", "gshard")
        self.d_model = d_model
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.ep_axis = ep_axis
        if experts is not None:
            self.experts = experts if isinstance(experts, LayerList) \
                else LayerList(list(experts))
            self.num_experts = len(self.experts)
            self.fused = None
        else:
            assert num_experts and d_hidden, \
                "fused MoE needs num_experts + d_hidden"
            self.num_experts = num_experts
            self.fused = ExpertFFN(num_experts, d_model, d_hidden)
            self.experts = None
        if isinstance(gate, str):
            self.gate = GATES[gate](d_model, num_experts=self.num_experts,
                                    top_k=top_k)
        else:
            self.gate = gate
        self.aux_loss = None

    # -- dispatch plan (pure jnp; shapes static) ----------------------------
    def _plan(self, logits, capacity):
        """logits [S, E] → dispatch [S, E, C] one-hot, combine [S, E, C]."""
        return plan_dispatch(logits, capacity, self.top_k)

    def forward(self, x):
        orig_shape = x.shape
        d = orig_shape[-1]
        s = 1
        for n in orig_shape[:-1]:
            s *= n
        e = self.num_experts
        capacity = moe_capacity(s, e, self.top_k, self.capacity_factor)
        ep = ep_axis_for(e, self.ep_axis)

        gate_w = self.gate.weight
        if self.fused is not None:
            f = self.fused

            def fn(xa, gw, w1, b1, w2, b2):
                tok = xa.reshape(s, d)
                logits = tok.astype(jnp.float32) @ gw.astype(jnp.float32)
                out, probs, frac = dispatch_combine(
                    tok, logits, capacity, self.top_k,
                    lambda ein: f.forward_arrays(ein, w1, b1, w2, b2),
                    ep_axis=ep, tracer_ref=xa)
                aux = self.gate.aux_loss(probs, frac)
                return (out.reshape(orig_shape).astype(xa.dtype),
                        (aux if aux is not None else jnp.zeros((), jnp.float32)))

            out, aux = apply(fn, x, gate_w, f.w1, f.b1, f.w2, f.b2,
                             op_name="moe")
        else:
            # reference-style per-expert Layer list (python loop; CPU/debug)
            def fn(xa, gw):
                tok = xa.reshape(s, d)
                logits = tok.astype(jnp.float32) @ gw.astype(jnp.float32)
                probs, disp, combine = self._plan(logits, capacity)
                expert_in = jnp.einsum("sec,sd->ecd", disp, tok)
                frac = jnp.mean(disp.sum(-1), axis=0)
                aux = self.gate.aux_loss(probs, frac)
                return (expert_in, combine,
                        aux if aux is not None else jnp.zeros((), jnp.float32))

            expert_in, combine, aux = apply(fn, x, gate_w, op_name="moe_dispatch")
            outs = []
            for i, exp in enumerate(self.experts):
                outs.append(exp(expert_in[i]))
            from .....ops import manipulation as manip
            expert_out = manip.stack(outs, axis=0)

            def comb(c, eo, xa):
                o = jnp.einsum("sec,ecd->sd", c, eo)
                return o.reshape(orig_shape).astype(xa.dtype)

            out = apply(comb, combine, expert_out, x, op_name="moe_combine")

        self.aux_loss = aux
        self.gate.loss = aux
        return out
