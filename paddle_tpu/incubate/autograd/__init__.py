"""paddle.incubate.autograd (reference: ``python/paddle/incubate/autograd/``
— forward-mode jvp, vjp, Jacobian, Hessian via the prim/composite-op
machinery; SURVEY.md §2.1 "Prim/composite ops", §2.2 "Incubate").

TPU-native: the reference needed a whole primitive-op decomposition layer to
get higher-order AD; JAX has it natively — jvp/jacfwd/jacrev/hessian compose
with the eager Tensor layer by lifting the user's Tensor-function to a pure
array function.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor
from ...autograd.tape import no_grad

__all__ = ["jvp", "vjp", "Jacobian", "Hessian", "forward_grad", "grad"]


def _lift(func):
    """Tensor-function -> pure array function."""

    def pure(*arrs):
        with no_grad():
            out = func(*[Tensor(a) for a in arrs])
        return jax.tree.map(
            lambda t: t._data if isinstance(t, Tensor) else t, out,
            is_leaf=lambda x: isinstance(x, Tensor))

    return pure


def _arrs(xs):
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    return [x._data if isinstance(x, Tensor) else jnp.asarray(x) for x in xs]


def _wrap(out):
    return jax.tree.map(lambda a: Tensor(a), out)


def jvp(func, xs, v=None):
    """Forward-mode: returns (func(xs), J·v) (reference contract)."""
    primals = _arrs(xs)
    tangents = _arrs(v) if v is not None else [jnp.ones_like(a)
                                               for a in primals]
    out, tang = jax.jvp(_lift(func), primals, tangents)
    return _wrap(out), _wrap(tang)


def vjp(func, xs, v=None):
    """Reverse-mode: returns (func(xs), vᵀ·J)."""
    primals = _arrs(xs)
    out, f_vjp = jax.vjp(_lift(func), *primals)
    if v is None:
        cot = jax.tree.map(jnp.ones_like, out)
    else:
        cot = _arrs(v)
        cot = cot[0] if not isinstance(out, (tuple, list)) else tuple(cot)
    grads = f_vjp(cot)
    return _wrap(out), _wrap(list(grads))


def forward_grad(func, xs, v=None):
    return jvp(func, xs, v)[1]


def grad(func, xs):
    """First-order gradient of a scalar Tensor-function."""
    primals = _arrs(xs)
    g = jax.grad(lambda *a: _lift(func)(*a), argnums=tuple(
        range(len(primals))))(*primals)
    out = _wrap(list(g))
    return out if len(primals) > 1 else out[0]


class Jacobian:
    """Lazy Jacobian matrix (reference paddle.incubate.autograd.Jacobian):
    index like J[:] / J[i, j]; shape [out_numel, in_numel] for single x."""

    def __init__(self, func, xs, is_batched=False):
        primals = _arrs(xs)
        assert len(primals) == 1, "Jacobian supports a single xs tensor"
        self._x = primals[0]
        jac = jax.jacrev(_lift(func))(self._x)
        if is_batched:
            # [B, out..., B, in...] batched semantics not materialized;
            # reference batches over dim 0: take the diagonal over batch
            b = self._x.shape[0]
            out_shape = jac.shape[:jac.ndim - self._x.ndim]
            jacb = jac.reshape(b, -1, b, int(jnp.prod(
                jnp.asarray(self._x.shape[1:]))))
            idx = jnp.arange(b)
            self._m = jacb[idx, :, idx, :]
        else:
            out_n = 1
            for d in jac.shape[:jac.ndim - self._x.ndim]:
                out_n *= d
            self._m = jac.reshape(out_n, self._x.size)

    @property
    def shape(self):
        return list(self._m.shape)

    def __getitem__(self, idx):
        return Tensor(self._m[idx])

    def numpy(self):
        import numpy as np
        return np.asarray(self._m)


class Hessian:
    """Dense Hessian of a scalar func at xs: [numel, numel]."""

    def __init__(self, func, xs, is_batched=False):
        primals = _arrs(xs)
        assert len(primals) == 1, "Hessian supports a single xs tensor"
        x = primals[0]
        h = jax.hessian(lambda a: jnp.sum(_lift(func)(a)))(x)
        self._m = h.reshape(x.size, x.size)

    @property
    def shape(self):
        return list(self._m.shape)

    def __getitem__(self, idx):
        return Tensor(self._m[idx])

    def numpy(self):
        import numpy as np
        return np.asarray(self._m)
