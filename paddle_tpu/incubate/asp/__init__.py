"""paddle.incubate.asp — 2:4 structured sparsity (reference:
``python/paddle/incubate/asp/`` — mask generation + pruning for Ampere
sparse tensor cores; SURVEY.md §2.2 "Incubate").

TPU note: TPUs have no 2:4 sparse MXU mode, so ASP here provides the
*training-side* semantics — mask computation (n:m along the reduction dim),
pruning, and mask maintenance after optimizer steps — producing checkpoints
that are valid 2:4-sparse for deployment elsewhere; compute runs dense.
"""
from __future__ import annotations

import numpy as np

from ...framework.core import Tensor
from ...autograd.tape import no_grad

__all__ = ["calculate_density", "create_mask", "prune_model", "decorate",
           "reset_excluded_layers", "set_excluded_layers"]

_excluded = set()
_masks = {}          # id(param) -> (param, np mask)


def calculate_density(x):
    arr = x.numpy() if isinstance(x, Tensor) else np.asarray(x)
    return float((arr != 0).sum() / arr.size)


def create_mask(tensor, func_name="mask_2d_best", n=2, m=4):
    """n:m mask along the last dim (keep the n largest of every m)."""
    arr = np.asarray(tensor.numpy() if isinstance(tensor, Tensor)
                     else tensor)
    flat = np.abs(arr).reshape(-1, m) if arr.size % m == 0 else None
    if flat is None:
        return np.ones_like(arr, dtype=bool)
    keep = np.argsort(-flat, axis=1)[:, :n]
    mask = np.zeros_like(flat, dtype=bool)
    np.put_along_axis(mask, keep, True, axis=1)
    return mask.reshape(arr.shape)


def set_excluded_layers(param_names, main_program=None):
    _excluded.update(param_names)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def _prunable(model):
    for name, p in model.named_parameters():
        if p is None or name in _excluded or p.ndim < 2:
            continue
        if p.shape[-1] % 4 == 0:
            yield name, p


def prune_model(model, n=2, m=4, mask_algo="mask_2d_best", with_mask=True):
    """Apply n:m pruning to eligible weights; stores masks for maintenance."""
    out = {}
    with no_grad():
        for name, p in _prunable(model):
            mask = create_mask(p, mask_algo, n, m)
            _masks[id(p)] = (p, mask)
            out[name] = mask
            p.set_value(p.numpy() * mask)
    return out


def decorate(optimizer):
    """Wrap optimizer.step to re-apply masks after each update (reference
    ``asp.decorate`` keeps pruned weights at zero through training)."""
    orig_step = optimizer.step

    def step():
        orig_step()
        if _masks:
            with no_grad():
                for p, mask in _masks.values():
                    p.set_value(p.numpy() * mask)

    optimizer.step = step
    return optimizer
