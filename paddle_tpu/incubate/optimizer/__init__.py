"""paddle.incubate.optimizer — LookAhead / ModelAverage (reference:
``python/paddle/incubate/optimizer/``; SURVEY.md §2.2)."""
from __future__ import annotations

import numpy as np

from ...framework.core import Tensor
from ...autograd.tape import no_grad
from ... import optimizer as _opt


class LookAhead:
    """Lookahead wrapper: every k steps, slow weights move toward fast
    weights by alpha and fast weights are reset to the slow ones."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._step = 0
        self._slow = {}

    @property
    def _parameter_list(self):
        return self.inner_optimizer._parameter_list

    def step(self):
        self.inner_optimizer.step()
        self._step += 1
        if self._step % self.k:
            return
        with no_grad():
            for p in self._parameter_list:
                if p is None:
                    continue
                slow = self._slow.get(id(p))
                if slow is None:
                    slow = np.asarray(p.numpy())
                fast = np.asarray(p.numpy())
                slow = slow + self.alpha * (fast - slow)
                self._slow[id(p)] = slow
                p.set_value(slow)

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["lookahead_slow"] = {k: v for k, v in self._slow.items()}
        sd["lookahead_step"] = self._step
        return sd

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()


class ModelAverage:
    """Maintains a running average of parameters; ``apply()`` swaps averaged
    weights in (for eval), ``restore()`` swaps the training weights back."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000, **kw):
        self._params = list(parameters or [])
        self._sum = {id(p): np.zeros(p.shape, np.float64) for p in self._params}
        self._cnt = 0
        self._backup = None

    def step(self):
        for p in self._params:
            self._sum[id(p)] += np.asarray(p.numpy(), np.float64)
        self._cnt += 1

    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): np.asarray(p.numpy()) for p in self._params}
        with no_grad():
            for p in self._params:
                avg = self._sum[id(p)] / max(self._cnt, 1)
                p.set_value(avg.astype(np.asarray(p.numpy()).dtype))

        class _Ctx:
            def __enter__(s):
                return s

            def __exit__(s, *a):
                if need_restore:
                    self.restore()

        return _Ctx()

    def restore(self, executor=None):
        if self._backup:
            with no_grad():
                for p in self._params:
                    p.set_value(self._backup[id(p)])
            self._backup = None


class DistributedFusedLamb(_opt.Lamb):
    """reference ``paddle.incubate.optimizer.DistributedFusedLamb`` — a
    CUDA-fused, sharded LAMB. TPU-native: the per-op fusion is XLA's job
    and parameter sharding comes from the sharding mesh axis, so this is
    LAMB with the reference's extra knobs accepted for compat (the
    clip_after_allreduce/is_grad_scaled_by_nranks semantics are owned by
    the hybrid optimizer's global-norm clip over mesh axes)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 clip_after_allreduce=True, is_grad_scaled_by_nranks=True,
                 alignment=128, nproc_per_node=None, use_master_param_norm=True,
                 gradient_accumulation_steps=1, use_master_acc_grad=True,
                 name=None):
        if gradient_accumulation_steps != 1:
            raise NotImplementedError(
                "DistributedFusedLamb: gradient_accumulation_steps != 1 — "
                "accumulate with model.no_sync()/manual accumulation, then "
                "step() once")
        super().__init__(learning_rate=learning_rate,
                         lamb_weight_decay=lamb_weight_decay, beta1=beta1,
                         beta2=beta2, epsilon=epsilon, parameters=parameters,
                         grad_clip=grad_clip,
                         exclude_from_weight_decay_fn=exclude_from_weight_decay_fn,
                         multi_precision=use_master_param_norm)
