"""paddle.incubate (reference: ``python/paddle/incubate/`` — fused ops API,
MoE, extra optimizers; SURVEY.md §2.2 "Incubate")."""
from __future__ import annotations

from . import nn  # noqa: F401
from . import distributed  # noqa: F401
from . import optimizer  # noqa: F401
from . import autograd  # noqa: F401
from . import asp  # noqa: F401
from ..distributed.fleet.utils import recompute as _recompute  # noqa: F401


def identity_loss(x, reduction="none"):
    from ..ops import math as pmath
    if reduction in ("mean",):
        return pmath.mean(x)
    if reduction in ("sum",):
        return pmath.sum(x)
    return x

# reference exposes the segment pools under incubate too
# (python/paddle/incubate/tensor/math.py)
from ..geometric import (  # noqa: E402,F401
    segment_sum, segment_mean, segment_max, segment_min,
)
