"""paddle.incubate (reference: ``python/paddle/incubate/`` — fused ops API,
MoE, extra optimizers; SURVEY.md §2.2 "Incubate")."""
from __future__ import annotations

from . import nn  # noqa: F401
from . import distributed  # noqa: F401
from . import optimizer  # noqa: F401
from . import autograd  # noqa: F401
from . import asp  # noqa: F401
from ..distributed.fleet.utils import recompute as _recompute  # noqa: F401


def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None,
                    name=None):
    """Legacy incubate name for ``paddle.geometric.send_u_recv``
    (reference: ``incubate.graph_send_recv`` predates the geometric
    namespace)."""
    from ..geometric import send_u_recv
    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def softmax_mask_fuse(x, mask, name=None):
    """reference: ``incubate.softmax_mask_fuse`` (a fused CUDA kernel);
    on TPU the add+softmax chain is XLA's fusion job — one traced op."""
    import jax
    import jax.numpy as jnp
    from ..autograd.tape import apply

    def fn(a, m):
        return jax.nn.softmax((a + m).astype(jnp.float32),
                              axis=-1).astype(a.dtype)

    return apply(fn, x, mask, op_name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Causal-masked softmax (reference fused kernel): mask is the upper
    triangle above the diagonal."""
    import jax
    import jax.numpy as jnp
    from ..autograd.tape import apply

    def fn(a):
        s = a.shape[-1]
        keep = jnp.tril(jnp.ones((a.shape[-2], s), bool))
        z = jnp.where(keep, a.astype(jnp.float32), -jnp.inf)
        return jax.nn.softmax(z, axis=-1).astype(a.dtype)

    return apply(fn, x, op_name="softmax_mask_fuse_upper_triangle")


def identity_loss(x, reduction="none"):
    from ..ops import math as pmath
    if reduction in ("mean",):
        return pmath.mean(x)
    if reduction in ("sum",):
        return pmath.sum(x)
    return x

# reference exposes the segment pools under incubate too
# (python/paddle/incubate/tensor/math.py)
from ..geometric import (  # noqa: E402,F401
    segment_sum, segment_mean, segment_max, segment_min,
)
