"""paddle.incubate.nn.functional — fused-op API surface (reference:
``fused_rotary_position_embedding``, ``fused_rms_norm``, ``swiglu``,
``fused_multi_head_attention``; phi fusion kernels, SURVEY.md §2.1/§2.2)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....ops.fused import (  # noqa: F401
    fused_rotary_position_embedding, fused_swiglu, rope_freqs,
)
from ....autograd.tape import apply

swiglu = fused_swiglu


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-5,
                   begin_norm_axis=-1, **kw):
    """RMSNorm (fused on GPU in the reference; XLA fuses it here)."""
    def fn(a, w, *b):
        var = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=-1,
                       keepdims=True)
        out = a.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon)
        out = out.astype(a.dtype) * w
        if b:
            out = out + b[0]
        return out

    args = (x, norm_weight) + ((norm_bias,) if norm_bias is not None else ())
    return apply(fn, *args, op_name="fused_rms_norm")


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5, **kw):
    from ....nn import functional as F
    return F.layer_norm(x, x.shape[-1:], weight=norm_weight, bias=norm_bias,
                        epsilon=epsilon)


def fused_multi_head_attention(*a, **kw):
    raise NotImplementedError(
        "fused_multi_head_attention: use paddle.nn.functional."
        "scaled_dot_product_attention (Pallas flash kernel on TPU)")


def fused_feedforward(*a, **kw):
    raise NotImplementedError(
        "fused_feedforward: compose Linear+activation — XLA fuses the chain")
