"""paddle.incubate.nn.functional — fused-op API surface (reference:
``fused_rotary_position_embedding``, ``fused_rms_norm``, ``swiglu``,
``fused_multi_head_attention``; phi fusion kernels, SURVEY.md §2.1/§2.2)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....ops.fused import (  # noqa: F401
    fused_rotary_position_embedding, fused_swiglu, rope_freqs,
)
from ....autograd.tape import apply

swiglu = fused_swiglu


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-5,
                   begin_norm_axis=-1, **kw):
    """RMSNorm (fused on GPU in the reference; XLA fuses it here)."""
    def fn(a, w, *b):
        var = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=-1,
                       keepdims=True)
        out = a.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon)
        out = out.astype(a.dtype) * w
        if b:
            out = out + b[0]
        return out

    args = (x, norm_weight) + ((norm_bias,) if norm_bias is not None else ())
    return apply(fn, *args, op_name="fused_rms_norm")


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5, **kw):
    from ....nn import functional as F
    return F.layer_norm(x, x.shape[-1:], weight=norm_weight, bias=norm_bias,
                        epsilon=epsilon)


def fused_multi_head_attention(*a, **kw):
    raise NotImplementedError(
        "fused_multi_head_attention: use paddle.nn.functional."
        "scaled_dot_product_attention (Pallas flash kernel on TPU)")


def fused_feedforward(x, linear1_weight, linear1_bias, linear2_weight,
                      linear2_bias, dropout1_rate=0.5, dropout2_rate=0.5,
                      activation="relu", ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, pre_layer_norm=False,
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5, training=True,
                      name=None):
    """paddle.incubate.nn.functional.fused_feedforward — the transformer
    FFN block (LN? → linear1 → act → dropout → linear2 → dropout →
    +residual → LN?). One traced chain; XLA emits the fused kernels the
    reference hand-writes in CUDA."""
    import paddle_tpu.nn.functional as F
    from ....nn.functional.norm import layer_norm

    def maybe_ln(t, scale, bias, eps):
        if scale is None and bias is None:
            return t
        return layer_norm(t, t.shape[-1], weight=scale, bias=bias,
                          epsilon=eps)

    residual = x
    h = maybe_ln(x, ln1_scale, ln1_bias, ln1_epsilon) if pre_layer_norm else x
    h = F.linear(h, linear1_weight, linear1_bias)
    h = getattr(F, activation)(h)
    h = F.dropout(h, dropout1_rate, training=training)
    h = F.linear(h, linear2_weight, linear2_bias)
    h = F.dropout(h, dropout2_rate, training=training)
    out = residual + h
    if not pre_layer_norm:
        out = maybe_ln(out, ln2_scale, ln2_bias, ln2_epsilon)
    return out


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """paddle.incubate.nn.functional.fused_linear — on TPU XLA fuses the
    matmul+bias chain; this is the API-parity entry (reference routes to
    the cublasLt fused gemm epilogue)."""
    def fn(a, w, *b):
        wt = jnp.swapaxes(w, -1, -2) if transpose_weight else w
        out = a @ wt
        if b:
            out = out + b[0]
        return out
    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply(fn, *args, op_name="fused_linear")


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu", name=None):
    """fused matmul + bias + activation (gelu/relu) — one XLA fusion."""
    def fn(a, w, b):
        if trans_x:
            a = jnp.swapaxes(a, -1, -2)
        if trans_y:
            w = jnp.swapaxes(w, -1, -2)
        out = a @ w + b
        if activation == "gelu":
            return jax.nn.gelu(out)
        if activation == "relu":
            return jax.nn.relu(out)
        if activation in ("", "none", None):
            return out
        raise ValueError(f"unsupported activation {activation!r}")
    return apply(fn, x, y, bias, op_name="fused_linear_activation")


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """dropout(x) + y in one fused op (reference fused_dropout_add)."""
    from ....framework import random as prandom
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and p > 0.0:
            # reference eval semantics for this mode: scale by (1-p)
            return apply(lambda a, b: a * (1.0 - p) + b, x, y,
                         op_name="fused_dropout_add")
        return apply(lambda a, b: a + b, x, y, op_name="fused_dropout_add")
    key = prandom.next_key()

    def fn(a, b):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        if mode == "upscale_in_train":
            a = jnp.where(keep, a / (1.0 - p), 0.0)
        else:
            a = jnp.where(keep, a, 0.0)
        return a + b
    return apply(fn, x, y, op_name="fused_dropout_add")


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", name=None):
    """layer_norm(residual + dropout(x + bias)) — the reference's fused
    residual block epilogue; XLA fuses the chain on TPU."""
    from ....framework import random as prandom
    key = prandom.next_key() if (training and dropout_rate > 0.0) else None

    def fn(a, res, *rest):
        it = iter(rest)
        b = next(it) if bias is not None else None
        g = next(it) if ln_scale is not None else None
        beta = next(it) if ln_bias is not None else None
        if b is not None:
            a = a + b
        if key is not None:
            keep = jax.random.bernoulli(key, 1.0 - dropout_rate, a.shape)
            if mode == "upscale_in_train":
                a = jnp.where(keep, a / (1.0 - dropout_rate), 0.0)
            else:
                a = jnp.where(keep, a, 0.0)
        elif mode == "downscale_in_infer" and dropout_rate > 0.0:
            a = a * (1.0 - dropout_rate)   # reference eval scaling
        h = a + res
        mu = jnp.mean(h, -1, keepdims=True)
        var = jnp.var(h, -1, keepdims=True)
        out = (h - mu) * jax.lax.rsqrt(var + ln_epsilon)
        if g is not None:
            out = out * g
        if beta is not None:
            out = out + beta
        return out

    args = [x, residual]
    for t in (bias, ln_scale, ln_bias):
        if t is not None:
            args.append(t)
    return apply(fn, *args, op_name="fused_bias_dropout_residual_ln")
