"""FusedMultiTransformer — the serving decoder block (reference:
``python/paddle/incubate/nn/layer/fused_transformer.py`` backed by the
``fused_multi_transformer`` phi fusion kernel; SURVEY.md §2.2 "Incubate",
VERDICT.md round-1 "no fused_multi_transformer serving block").

TPU-native design: instead of a hand-fused CUDA megakernel, all L layers'
weights are **stacked along a leading layer axis** and the block runs as a
single ``lax.scan`` over the stack. That is the idiomatic TPU fusion for a
multi-layer decode step: one traced layer body (compiles once regardless
of L), weights stream layer-by-layer from HBM, and XLA fuses the
norm→qkv→attention→proj→ffn chain inside the scanned body. The KV cache is
carried as one stacked ``[L, ...]`` array pair, so a full-model decode
step is one jittable program — the same shape the serving engine jits.

Layer body (pre-LN, GPT/Llama style, matching the reference default
``normalize_before=True``):
  h  = x + out_proj(attn(ln1(x)))
  y  = h + ffn2(act(ffn1(ln2(h))))
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...nn.layer import Layer
from ...framework.core import Tensor
from ...autograd.tape import apply


class FusedMultiTransformer(Layer):
    """API-compatible with ``paddle.incubate.nn.FusedMultiTransformer``.

    forward(src, attn_mask=None, caches=None, time_step=None)
      src        [batch, seq, embed_dim]
      caches     optional (k, v) stacked ``[L, batch, max_len, kv_heads,
                 head_dim]`` carried across decode steps
      time_step  int — current decode position when ``caches`` is used
                 (None ⇒ prefill: positions 0..seq fill the cache)
    Returns ``out`` or ``(out, caches)`` when caches are given/created.
    """

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 num_layers=1, nranks=1, trans_qkvw=True, ring_id=-1,
                 num_key_value_heads=None, epsilon=1e-5, name=None):
        super().__init__()
        if not normalize_before:
            raise NotImplementedError(
                "FusedMultiTransformer: only pre-LN (normalize_before=True) "
                "— the reference serving block's default")
        if dropout_rate:
            raise ValueError("FusedMultiTransformer is a serving block: "
                             "dropout_rate must be 0")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.kv_heads = num_key_value_heads or num_heads
        self.head_dim = embed_dim // num_heads
        self.dim_feedforward = dim_feedforward
        self.num_layers = num_layers
        self.activation = activation
        self.epsilon = epsilon
        L, D, F = num_layers, embed_dim, dim_feedforward
        qkv_out = (num_heads + 2 * self.kv_heads) * self.head_dim
        mk = self.create_parameter
        from ...nn.initializer import Constant, Normal
        self.ln_scale = mk([L, D], default_initializer=Constant(1.0))
        self.ln_bias = mk([L, D], is_bias=True)
        self.qkv_weight = mk([L, D, qkv_out],
                             default_initializer=Normal(0.0, 0.02))
        self.qkv_bias = mk([L, qkv_out], is_bias=True)
        self.linear_weight = mk([L, num_heads * self.head_dim, D],
                                default_initializer=Normal(0.0, 0.02))
        self.linear_bias = mk([L, D], is_bias=True)
        self.ffn_ln_scale = mk([L, D], default_initializer=Constant(1.0))
        self.ffn_ln_bias = mk([L, D], is_bias=True)
        self.ffn1_weight = mk([L, D, F], default_initializer=Normal(0.0, 0.02))
        self.ffn1_bias = mk([L, F], is_bias=True)
        self.ffn2_weight = mk([L, F, D], default_initializer=Normal(0.0, 0.02))
        self.ffn2_bias = mk([L, D], is_bias=True)

    def _act(self, x):
        if self.activation == "gelu":
            return jax.nn.gelu(x)
        if self.activation == "relu":
            return jax.nn.relu(x)
        if self.activation in ("swish", "silu"):
            return jax.nn.silu(x)
        raise ValueError(f"unsupported activation {self.activation}")

    def forward(self, src, attn_mask=None, caches=None, time_step=None,
                name=None):
        h, kvh, hd, eps = (self.num_heads, self.kv_heads, self.head_dim,
                           self.epsilon)
        act = self._act
        use_cache = caches is not None
        step = None if time_step is None else int(time_step)

        def ln(x, scale, bias):
            mu = jnp.mean(x, -1, keepdims=True)
            var = jnp.var(x, -1, keepdims=True)
            return (x - mu) * jax.lax.rsqrt(var + eps) * scale + bias

        def run(x, *params):
            (ln_s, ln_b, qkv_w, qkv_b, lin_w, lin_b,
             fln_s, fln_b, f1_w, f1_b, f2_w, f2_b, *rest) = params
            mask = rest[0] if attn_mask is not None else None
            ck = rest[-2] if use_cache else None
            cv = rest[-1] if use_cache else None
            b, s, d = x.shape

            def body(carry, layer):
                x = carry["x"]
                (ls, lb, qw, qb, lw, lbs, fs, fb, f1w, f1b, f2w, f2b) = (
                    layer["ln_s"], layer["ln_b"], layer["qkv_w"],
                    layer["qkv_b"], layer["lin_w"], layer["lin_b"],
                    layer["fln_s"], layer["fln_b"], layer["f1_w"],
                    layer["f1_b"], layer["f2_w"], layer["f2_b"])
                y = ln(x, ls, lb)
                qkv = jnp.einsum("bsd,de->bse", y, qw) + qb
                q, k, v = jnp.split(
                    qkv, [h * hd, h * hd + kvh * hd], axis=-1)
                q = q.reshape(b, s, h, hd)
                k = k.reshape(b, s, kvh, hd)
                v = v.reshape(b, s, kvh, hd)
                if use_cache:
                    pos = 0 if step is None else step
                    nk = jax.lax.dynamic_update_slice(
                        layer["ck"], k, (0, pos, 0, 0))
                    nv = jax.lax.dynamic_update_slice(
                        layer["cv"], v, (0, pos, 0, 0))
                    klen = pos + s
                    kk, vv = nk, nv
                else:
                    nk = nv = None
                    klen = s
                    kk, vv = k, v
                # GQA attention, causal over the cached prefix
                qg = q.reshape(b, s, kvh, h // kvh, hd)
                logits = jnp.einsum("bqkgd,bskd->bkgqs", qg,
                                    kk.astype(q.dtype))
                logits = logits / math.sqrt(hd)
                q_pos = (0 if step is None else step) + \
                    jax.lax.broadcasted_iota(jnp.int32, logits.shape, 3)
                k_pos = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 4)
                causal = k_pos <= q_pos
                if use_cache:
                    causal = causal & (k_pos < klen)
                logits = jnp.where(causal, logits, -jnp.inf)
                if mask is not None:
                    # normalize to [b, kv, g, q, s] (reference mask shapes:
                    # [b, heads|1, q, s], [b, q, s], or [q, s])
                    m = mask
                    if m.ndim == 2:
                        m = m[None, None, None]
                    elif m.ndim == 3:
                        m = m[:, None, None]
                    elif m.ndim == 4:
                        if m.shape[1] == 1:
                            m = m[:, :, None]            # [b,1,1,q,s]
                        else:                            # per-head mask
                            m = m.reshape(m.shape[0], kvh, h // kvh,
                                          *m.shape[2:])
                    logits = logits + m
                w = jax.nn.softmax(logits.astype(jnp.float32), -1)
                w = w.astype(q.dtype)
                o = jnp.einsum("bkgqs,bskd->bqkgd", w, vv.astype(q.dtype))
                o = o.reshape(b, s, h * hd)
                x = x + jnp.einsum("bsd,de->bse", o, lw) + lbs
                y2 = ln(x, fs, fb)
                y2 = act(jnp.einsum("bsd,df->bsf", y2, f1w) + f1b)
                x = x + jnp.einsum("bsf,fd->bsd", y2, f2w) + f2b
                out_cache = ((nk, nv) if use_cache else (0.0, 0.0))
                return {"x": x}, out_cache

            layers = {"ln_s": ln_s, "ln_b": ln_b, "qkv_w": qkv_w,
                      "qkv_b": qkv_b, "lin_w": lin_w, "lin_b": lin_b,
                      "fln_s": fln_s, "fln_b": fln_b, "f1_w": f1_w,
                      "f1_b": f1_b, "f2_w": f2_w, "f2_b": f2_b}
            if use_cache:
                layers["ck"] = ck
                layers["cv"] = cv
            carry, caches_out = jax.lax.scan(body, {"x": x}, layers)
            if use_cache:
                return carry["x"], caches_out[0], caches_out[1]
            return carry["x"]

        args = [src, self.ln_scale, self.ln_bias, self.qkv_weight,
                self.qkv_bias, self.linear_weight, self.linear_bias,
                self.ffn_ln_scale, self.ffn_ln_bias, self.ffn1_weight,
                self.ffn1_bias, self.ffn2_weight, self.ffn2_bias]
        if attn_mask is not None:
            args.append(attn_mask)
        if use_cache:
            args += [caches[0], caches[1]]
        # run() consumes (x, *params) — apply() threads Tensors through the
        # tape so the block trains and jits like any composed layer
        def fn(x, *params):
            return run(x, *params)
        out = apply(fn, *args, op_name="fused_multi_transformer")
        if use_cache:
            return out[0], (out[1], out[2])
        return out

    def init_cache(self, batch, max_len, dtype="float32"):
        """Allocate the stacked decode cache: (k, v) each
        [L, batch, max_len, kv_heads, head_dim]."""
        shape = (self.num_layers, batch, max_len, self.kv_heads,
                 self.head_dim)
        return (Tensor(jnp.zeros(shape, jnp.dtype(dtype))),
                Tensor(jnp.zeros(shape, jnp.dtype(dtype))))
