"""paddle.incubate.nn — fused transformer blocks (reference:
``python/paddle/incubate/nn/`` → phi fusion kernels; SURVEY.md §2.2).
On TPU the "fused" layers are regular composed ops — XLA fuses the chains
(SURVEY.md §7.0) — so these classes exist for API parity and route to the
same code paths the plain layers use.
"""
from __future__ import annotations

from . import functional  # noqa: F401
from .fused_transformer import FusedMultiTransformer  # noqa: F401
from ...nn.layers.transformer import TransformerEncoderLayer as _TEL


class FusedTransformerEncoderLayer(_TEL):
    """API-compatible with paddle.incubate.nn.FusedTransformerEncoderLayer;
    on TPU the plain encoder layer already compiles to fused HLO."""


class FusedMultiHeadAttention(object):
    def __init__(self, *a, **kw):
        from ...nn.layers.transformer import MultiHeadAttention
        raise NotImplementedError(
            "Use paddle.nn.MultiHeadAttention — XLA emits the fused kernel; "
            "the separate fused layer exists only for CUDA in the reference")
