"""paddle.incubate.nn — fused transformer blocks (reference:
``python/paddle/incubate/nn/`` → phi fusion kernels; SURVEY.md §2.2).
On TPU the "fused" layers are regular composed ops — XLA fuses the chains
(SURVEY.md §7.0) — so these classes exist for API parity and route to the
same code paths the plain layers use.
"""
from __future__ import annotations

from . import functional  # noqa: F401
from .fused_transformer import FusedMultiTransformer  # noqa: F401
from ...nn.layer import Layer
from ...nn.layers.transformer import TransformerEncoderLayer as _TEL


class FusedTransformerEncoderLayer(_TEL):
    """API-compatible with paddle.incubate.nn.FusedTransformerEncoderLayer;
    on TPU the plain encoder layer already compiles to fused HLO."""


class FusedMultiHeadAttention(object):
    def __init__(self, *a, **kw):
        from ...nn.layers.transformer import MultiHeadAttention
        raise NotImplementedError(
            "Use paddle.nn.MultiHeadAttention — XLA emits the fused kernel; "
            "the separate fused layer exists only for CUDA in the reference")


class FusedLinear(Layer):
    """paddle.incubate.nn.FusedLinear — Linear with the fused-gemm API
    (transpose_weight); XLA's epilogue fusion is the TPU analogue."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self.transpose_weight = transpose_weight
        shape = ((out_features, in_features) if transpose_weight
                 else (in_features, out_features))
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = self.create_parameter((out_features,), attr=bias_attr,
                                          is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        from .functional import fused_linear
        return fused_linear(x, self.weight, self.bias,
                            transpose_weight=self.transpose_weight)


class FusedFeedForward(Layer):
    """paddle.incubate.nn.FusedFeedForward — transformer FFN block over
    the fused_feedforward functional."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                 else act_dropout_rate)
        self.activation = activation
        self.epsilon = epsilon
        def mk(shape, attr, **kw):
            # attr=False is the reference no-parameter marker
            return None if attr is False else self.create_parameter(
                shape, attr=attr, **kw)

        self.linear1_weight = mk((d_model, dim_feedforward),
                                 linear1_weight_attr)
        self.linear1_bias = mk((dim_feedforward,), linear1_bias_attr,
                               is_bias=True)
        self.linear2_weight = mk((dim_feedforward, d_model),
                                 linear2_weight_attr)
        self.linear2_bias = mk((d_model,), linear2_bias_attr, is_bias=True)
        one = __import__("paddle_tpu").nn.initializer.Constant(1.0)
        self.ln1_scale = mk((d_model,), ln1_scale_attr,
                            default_initializer=one)
        self.ln1_bias = mk((d_model,), ln1_bias_attr, is_bias=True)
        self.ln2_scale = mk((d_model,), ln2_scale_attr,
                            default_initializer=one)
        self.ln2_bias = mk((d_model,), ln2_bias_attr, is_bias=True)

    def forward(self, src):
        from .functional import fused_feedforward
        return fused_feedforward(
            src, self.linear1_weight, self.linear1_bias,
            self.linear2_weight, self.linear2_bias,
            dropout1_rate=self.act_dropout_rate,
            dropout2_rate=self.dropout_rate, activation=self.activation,
            ln1_scale=self.ln1_scale, ln1_bias=self.ln1_bias,
            ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
            pre_layer_norm=self.normalize_before,
            ln1_epsilon=self.epsilon, ln2_epsilon=self.epsilon,
            training=self.training)
