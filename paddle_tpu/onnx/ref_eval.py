"""Independent numpy evaluator for the exported ONNX op subset — the test
oracle standing in for onnxruntime (not in the image). Implements ONNX
operator SEMANTICS (opset 13) from the public spec, deliberately NOT by
calling back into the exporter's jax ops, so export bugs can't self-verify."""
from __future__ import annotations

import numpy as np

from . import proto


def _pool2d(x, kernel, strides, pads, mode):
    n, c, h, w = x.shape
    kh, kw = kernel
    ph0, pw0, ph1, pw1 = pads
    fill = -np.inf if mode == "max" else 0.0
    xp = np.full((n, c, h + ph0 + ph1, w + pw0 + pw1), fill, x.dtype)
    xp[:, :, ph0:ph0 + h, pw0:pw0 + w] = x
    oh = (xp.shape[2] - kh) // strides[0] + 1
    ow = (xp.shape[3] - kw) // strides[1] + 1
    out = np.empty((n, c, oh, ow), x.dtype)
    for i in range(oh):
        for j in range(ow):
            win = xp[:, :, i * strides[0]:i * strides[0] + kh,
                     j * strides[1]:j * strides[1] + kw]
            out[:, :, i, j] = win.max((2, 3)) if mode == "max" \
                else win.mean((2, 3))
    return out


def _conv2d(x, w, b, strides, pads, dilations, group):
    n, cin, h, wd = x.shape
    cout, cing, kh, kw = w.shape
    ph0, pw0, ph1, pw1 = pads
    xp = np.zeros((n, cin, h + ph0 + ph1, wd + pw0 + pw1), x.dtype)
    xp[:, :, ph0:ph0 + h, pw0:pw0 + wd] = x
    dkh, dkw = (kh - 1) * dilations[0] + 1, (kw - 1) * dilations[1] + 1
    oh = (xp.shape[2] - dkh) // strides[0] + 1
    ow = (xp.shape[3] - dkw) // strides[1] + 1
    out = np.zeros((n, cout, oh, ow), np.float32)
    cpg = cout // group
    for g in range(group):
        xs = xp[:, g * cing:(g + 1) * cing]
        ws = w[g * cpg:(g + 1) * cpg]
        for i in range(oh):
            for j in range(ow):
                win = xs[:, :, i * strides[0]:i * strides[0] + dkh:dilations[0],
                         j * strides[1]:j * strides[1] + dkw:dilations[1]]
                out[:, g * cpg:(g + 1) * cpg, i, j] = np.einsum(
                    "nchw,ochw->no", win, ws)
    if b is not None:
        out += b.reshape(1, -1, 1, 1)
    return out.astype(x.dtype)


def run(model_bytes: bytes, inputs: dict):
    m = proto.parse_model(model_bytes)
    g = m["graph"]
    env = dict(g["initializers"])
    for name, dtype, shape in g["inputs"]:
        env[name] = np.asarray(inputs[name], dtype)
    for nd in g["nodes"]:
        op, a = nd["op_type"], nd["attrs"]
        iv = [env[i] for i in nd["input"] if i]
        o = nd["output"][0]
        if op in ("Add", "Sub", "Mul", "Div", "Pow", "Max", "Min", "Mod",
                  "And", "Or", "Xor", "Equal", "Less", "LessOrEqual",
                  "Greater", "GreaterOrEqual"):
            f = {"Add": np.add, "Sub": np.subtract, "Mul": np.multiply,
                 "Div": np.divide, "Pow": np.power, "Max": np.maximum,
                 "Min": np.minimum,
                 "Mod": (np.fmod if a.get("fmod") else np.mod),
                 "And": np.logical_and,
                 "Or": np.logical_or, "Xor": np.logical_xor,
                 "Equal": np.equal, "Less": np.less,
                 "LessOrEqual": np.less_equal, "Greater": np.greater,
                 "GreaterOrEqual": np.greater_equal}[op]
            env[o] = f(iv[0], iv[1])
        elif op in ("Tanh", "Exp", "Log", "Neg", "Abs", "Sqrt", "Sigmoid",
                    "Floor", "Ceil", "Round", "Sign", "Sin", "Cos", "Erf",
                    "Not", "Sinh", "Cosh", "Atan", "Asin", "Acos"):
            import math
            f = {"Tanh": np.tanh, "Exp": np.exp, "Log": np.log,
                 "Neg": np.negative, "Abs": np.abs, "Sqrt": np.sqrt,
                 "Sigmoid": lambda v: 1 / (1 + np.exp(-v)),
                 "Floor": np.floor, "Ceil": np.ceil, "Round": np.round,
                 "Sign": np.sign, "Sin": np.sin, "Cos": np.cos,
                 "Erf": np.vectorize(math.erf), "Not": np.logical_not,
                 "Sinh": np.sinh, "Cosh": np.cosh, "Atan": np.arctan,
                 "Asin": np.arcsin, "Acos": np.arccos}[op]
            env[o] = np.asarray(f(iv[0]), iv[0].dtype if op != "Erf"
                                else np.float32)
        elif op == "MatMul":
            env[o] = np.matmul(iv[0], iv[1])
        elif op == "Reshape":
            env[o] = iv[0].reshape([int(d) for d in iv[1]])
        elif op == "Transpose":
            env[o] = np.transpose(iv[0], a["perm"])
        elif op == "Expand":
            env[o] = np.broadcast_to(iv[0], [int(d) for d in iv[1]]).copy()
        elif op == "Squeeze":
            env[o] = np.squeeze(iv[0], tuple(int(d) for d in iv[1]))
        elif op == "Unsqueeze":
            out = iv[0]
            for d in sorted(int(x) for x in iv[1]):
                out = np.expand_dims(out, d)
            env[o] = out
        elif op == "Concat":
            env[o] = np.concatenate(iv, axis=a["axis"])
        elif op == "Slice":
            starts, ends, axes, steps = (iv[1].astype(int), iv[2].astype(int),
                                         iv[3].astype(int), iv[4].astype(int))
            idx = [slice(None)] * iv[0].ndim
            for s, e, ax, st in zip(starts, ends, axes, steps):
                idx[ax] = slice(int(s), int(e), int(st))
            env[o] = iv[0][tuple(idx)]
        elif op == "Pad":
            pads = iv[1].astype(int)
            nd2 = iv[0].ndim
            width = [(pads[i], pads[i + nd2]) for i in range(nd2)]
            cval = float(iv[2]) if len(iv) > 2 else 0.0
            env[o] = np.pad(iv[0], width, constant_values=cval)
        elif op in ("ReduceSum", "ReduceMax", "ReduceMin", "ReduceProd"):
            f = {"ReduceSum": np.sum, "ReduceMax": np.max,
                 "ReduceMin": np.min, "ReduceProd": np.prod}[op]
            env[o] = f(iv[0], axis=tuple(int(d) for d in iv[1]),
                       keepdims=bool(a.get("keepdims", 1)))
        elif op == "ArgMax":
            env[o] = np.argmax(iv[0], axis=a["axis"]).astype(np.int64)
        elif op == "Where":
            env[o] = np.where(iv[0], iv[1], iv[2])
        elif op == "Cast":
            env[o] = iv[0].astype(proto.ONNX2NP[a["to"]])
        elif op == "Conv":
            b = iv[2] if len(iv) > 2 else None
            env[o] = _conv2d(iv[0], iv[1], b, a["strides"], a["pads"],
                             a["dilations"], a.get("group", 1))
        elif op == "MaxPool":
            env[o] = _pool2d(iv[0], a["kernel_shape"], a["strides"],
                             a["pads"], "max")
        elif op == "AveragePool":
            env[o] = _pool2d(iv[0], a["kernel_shape"], a["strides"],
                             a["pads"], "avg")
        elif op == "Identity":
            env[o] = iv[0]
        else:
            raise NotImplementedError(f"ref_eval: op {op}")
    return [env[name] for name, _, _ in g["outputs"]]
