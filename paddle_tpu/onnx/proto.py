"""Minimal ONNX protobuf wire-format writer/reader (no onnx package in the
image — reference: ``paddle2onnx``'s dependency on the onnx protobufs; the
field numbers below are the stable public ``onnx.proto3`` schema, IR v3+).

Only the subset the exporter emits is modeled: ModelProto / GraphProto /
NodeProto / AttributeProto / TensorProto / ValueInfoProto. The encoder
produces bytes any ONNX runtime parses; the decoder exists for round-trip
tests and the in-repo reference evaluator."""
from __future__ import annotations

import struct

import numpy as np

# TensorProto.DataType
FLOAT, UINT8, INT8, INT32, INT64, BOOL, FLOAT16, DOUBLE = 1, 2, 3, 6, 7, 9, 10, 11

NP2ONNX = {np.dtype(np.float32): FLOAT, np.dtype(np.int64): INT64,
           np.dtype(np.int32): INT32, np.dtype(np.bool_): BOOL,
           np.dtype(np.float16): FLOAT16, np.dtype(np.float64): DOUBLE,
           np.dtype(np.uint8): UINT8, np.dtype(np.int8): INT8}
ONNX2NP = {v: k for k, v in NP2ONNX.items()}


# ---------------------------------------------------------------------------
# wire-format primitives
# ---------------------------------------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_field(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _int_field(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(value)


def _str_field(field: int, value: str) -> bytes:
    return _len_field(field, value.encode())


# ---------------------------------------------------------------------------
# message builders
# ---------------------------------------------------------------------------

def tensor_proto(name: str, arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    out = b""
    for d in arr.shape:
        out += _int_field(1, int(d))
    out += _int_field(2, NP2ONNX[arr.dtype])
    out += _str_field(8, name)
    out += _len_field(9, arr.tobytes())          # raw_data (little-endian)
    return out


def attr(name: str, value) -> bytes:
    out = _str_field(1, name)
    if isinstance(value, float):
        out += _tag(2, 5) + struct.pack("<f", value) + _int_field(20, 1)
    elif isinstance(value, bool) or isinstance(value, (int, np.integer)):
        out += _int_field(3, int(value)) + _int_field(20, 2)
    elif isinstance(value, str):
        out += _len_field(4, value.encode()) + _int_field(20, 3)
    elif isinstance(value, np.ndarray):
        out += _len_field(5, tensor_proto("", value)) + _int_field(20, 4)
    elif isinstance(value, (list, tuple)) and all(
            isinstance(v, (int, np.integer)) for v in value):
        for v in value:
            out += _int_field(8, int(v))
        out += _int_field(20, 7)
    elif isinstance(value, (list, tuple)):
        for v in value:
            out += _tag(7, 5) + struct.pack("<f", float(v))
        out += _int_field(20, 6)
    else:
        raise TypeError(f"unsupported attribute {name}={value!r}")
    return out


def node(op_type: str, inputs, outputs, name="", **attrs) -> bytes:
    out = b""
    for i in inputs:
        out += _str_field(1, i)
    for o in outputs:
        out += _str_field(2, o)
    if name:
        out += _str_field(3, name)
    out += _str_field(4, op_type)
    for k, v in attrs.items():
        out += _len_field(5, attr(k, v))
    return out


def value_info(name: str, dtype: np.dtype, shape) -> bytes:
    dims = b""
    for d in shape:
        dims += _len_field(1, _int_field(1, int(d)))    # Dimension.dim_value
    tensor_type = _int_field(1, NP2ONNX[np.dtype(dtype)]) + _len_field(2, dims)
    return _str_field(1, name) + _len_field(2, _len_field(1, tensor_type))


def graph(nodes, name, initializers, inputs, outputs) -> bytes:
    out = b""
    for n in nodes:
        out += _len_field(1, n)
    out += _str_field(2, name)
    for t in initializers:
        out += _len_field(5, t)
    for vi in inputs:
        out += _len_field(11, vi)
    for vi in outputs:
        out += _len_field(12, vi)
    return out


def model(graph_bytes: bytes, opset: int = 13, ir_version: int = 8) -> bytes:
    out = _int_field(1, ir_version)
    out += _str_field(2, "paddle_tpu")
    out += _str_field(3, "0.1")
    out += _len_field(7, graph_bytes)
    out += _len_field(8, _int_field(2, opset))   # OperatorSetId{version}
    return out


# ---------------------------------------------------------------------------
# decoder (round-trip tests + in-repo evaluator)
# ---------------------------------------------------------------------------

def _iter_fields(buf: bytes):
    i = 0
    while i < len(buf):
        tag, i = _read_varint(buf, i)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            val, i = _read_varint(buf, i)
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            val = buf[i:i + ln]
            i += ln
        elif wire == 5:
            val = struct.unpack("<f", buf[i:i + 4])[0]
            i += 4
        elif wire == 1:
            val = struct.unpack("<d", buf[i:i + 8])[0]
            i += 8
        else:
            raise ValueError(f"wire type {wire}")
        yield field, wire, val


def _read_varint(buf: bytes, i: int):
    shift = n = 0
    while True:
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7


def parse_tensor(buf: bytes):
    dims, dt, name, raw = [], FLOAT, "", b""
    for f, w, v in _iter_fields(buf):
        if f == 1:
            dims.append(v)
        elif f == 2:
            dt = v
        elif f == 8:
            name = v.decode()
        elif f == 9:
            raw = v
    arr = np.frombuffer(raw, ONNX2NP[dt]).reshape(dims)
    return name, arr


def parse_node(buf: bytes):
    n = {"input": [], "output": [], "op_type": "", "name": "", "attrs": {}}
    for f, w, v in _iter_fields(buf):
        if f == 1:
            n["input"].append(v.decode())
        elif f == 2:
            n["output"].append(v.decode())
        elif f == 3:
            n["name"] = v.decode()
        elif f == 4:
            n["op_type"] = v.decode()
        elif f == 5:
            name, val = _parse_attr(v)
            n["attrs"][name] = val
    return n


def _parse_attr(buf: bytes):
    name, atype = "", None
    sval = fval = ival = tval = None
    ints, floats = [], []
    for f, w, v in _iter_fields(buf):
        if f == 1:
            name = v.decode()
        elif f == 2:
            fval = v
        elif f == 3:
            ival = v
        elif f == 4:
            sval = v.decode()
        elif f == 5:
            tval = parse_tensor(v)[1]
        elif f == 7:
            floats.append(v)
        elif f == 8:
            ints.append(v)
        elif f == 20:
            atype = v
    val = {1: fval, 2: ival, 3: sval, 4: tval, 6: floats, 7: ints}.get(atype)
    return name, val


def parse_value_info(buf: bytes):
    name, dtype, shape = "", None, []
    for f, w, v in _iter_fields(buf):
        if f == 1:
            name = v.decode()
        elif f == 2:
            for f2, _, v2 in _iter_fields(v):           # TypeProto
                if f2 == 1:                             # tensor_type
                    for f3, _, v3 in _iter_fields(v2):
                        if f3 == 1:
                            dtype = ONNX2NP[v3]
                        elif f3 == 2:                   # shape
                            for f4, _, v4 in _iter_fields(v3):
                                if f4 == 1:             # dim
                                    for f5, _, v5 in _iter_fields(v4):
                                        if f5 == 1:
                                            shape.append(v5)
    return name, dtype, shape


def parse_model(buf: bytes):
    out = {"ir_version": None, "opset": None, "graph": None}
    for f, w, v in _iter_fields(buf):
        if f == 1:
            out["ir_version"] = v
        elif f == 7:
            out["graph"] = parse_graph(v)
        elif f == 8:
            for f2, _, v2 in _iter_fields(v):
                if f2 == 2:
                    out["opset"] = v2
    return out


def parse_graph(buf: bytes):
    g = {"nodes": [], "name": "", "initializers": {}, "inputs": [],
         "outputs": []}
    for f, w, v in _iter_fields(buf):
        if f == 1:
            g["nodes"].append(parse_node(v))
        elif f == 2:
            g["name"] = v.decode()
        elif f == 5:
            name, arr = parse_tensor(v)
            g["initializers"][name] = arr
        elif f == 11:
            g["inputs"].append(parse_value_info(v))
        elif f == 12:
            g["outputs"].append(parse_value_info(v))
    return g
