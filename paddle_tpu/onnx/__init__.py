"""paddle.onnx (reference: ``paddle.onnx.export`` delegating to the external
paddle2onnx package; SURVEY.md §2.2).

TPU-native: the model is functionalized (the same bridge @to_static uses),
traced to a jaxpr, and converted equation-by-equation to an ONNX graph
serialized with an in-repo protobuf writer (``proto.py`` — the onnx package
is not in the image). Covers the MLP/CNN inference subset; unsupported
primitives raise by name, and ``paddle.jit.save`` (StableHLO) remains the
fully-general portable format."""
from __future__ import annotations

import numpy as np

from .export import export_traced
from . import proto, ref_eval  # noqa: F401

__all__ = ["export", "export_traced"]


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Export an eval-mode Layer to ``<path>.onnx``.

    ``input_spec``: list of example Tensors or InputSpec (static shapes
    required, as in the reference exporter)."""
    import jax.numpy as jnp
    from ..framework.core import Tensor
    from ..framework.functional import FunctionalModule
    from ..jit.api import InputSpec

    if input_spec is None:
        raise ValueError("paddle.onnx.export needs input_spec (example "
                         "Tensors or InputSpec with static shapes)")
    examples = []
    for spec in input_spec:
        if isinstance(spec, Tensor):
            examples.append(spec._data)
        elif isinstance(spec, InputSpec):
            if any(d is None or d == -1 for d in spec.shape):
                raise ValueError(
                    "paddle.onnx.export needs STATIC shapes; dynamic dims "
                    f"in {spec} — export one model per bucket, or use "
                    "paddle.jit.save (StableHLO) for shape polymorphism")
            examples.append(jnp.zeros([int(d) for d in spec.shape],
                                      spec.dtype))
        else:
            examples.append(jnp.asarray(np.asarray(spec)))

    was_training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()
    try:
        fm = FunctionalModule(layer, training=False)
        p_arrs = fm.param_arrays()
        b_arrs = fm.buffer_arrays()
        key = fm.next_key()

        def fwd(*xs):
            out, _ = fm(p_arrs, b_arrs, key, *xs)
            return out

        blob = export_traced(fwd, examples, opset=opset_version)
    finally:
        if was_training and hasattr(layer, "train"):
            layer.train()
    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(blob)
    return out_path
