"""paddle.onnx (reference: thin ``paddle.onnx.export`` delegating to the
external paddle2onnx package; SURVEY.md §2.2). The TPU build's portable
export format is serialized StableHLO (``paddle.jit.save``) — ONNX export
would need paddle2onnx, which is not in the image."""
from __future__ import annotations


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "paddle.onnx.export requires the external paddle2onnx package (not "
        "in the TPU build). Use paddle.jit.save(layer, path, input_spec) — "
        "serialized StableHLO is the portable inference format here; "
        "paddle.inference.create_predictor loads it.")
