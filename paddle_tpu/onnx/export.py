"""jaxpr → ONNX graph conversion (reference: ``paddle2onnx``'s
Program→ONNX op mappers; SURVEY.md §2.2 "ONNX export").

TPU-native path: the model is traced to a jaxpr through the same
functionalization ``@to_static`` uses, then each jaxpr equation maps to an
ONNX node. Covered primitive subset (the MLP/CNN inference families):
dot_general, conv_general_dilated, reduce_window (max/avg pool), the
elementwise/activation set, reductions, reshape/transpose/broadcast,
concatenate/slice/pad, select_n, cast. Unsupported primitives raise with
the primitive's name so coverage gaps are explicit, never silent."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import proto


class _Ctx:
    def __init__(self):
        self.nodes = []
        self.inits = []
        self.names = {}
        self.counter = [0]

    def name_of(self, var):
        key = id(var)
        if key not in self.names:
            self.names[key] = f"v{len(self.names)}"
        return self.names[key]

    def fresh(self, hint):
        self.counter[0] += 1
        return f"{hint}_{self.counter[0]}"

    def const(self, arr, hint="const"):
        name = self.fresh(hint)
        self.inits.append(proto.tensor_proto(name, np.asarray(arr)))
        return name

    def emit(self, op, inputs, n_out=1, hint=None, **attrs):
        outs = [self.fresh((hint or op).lower()) for _ in range(n_out)]
        self.nodes.append(proto.node(op, inputs, outs, **attrs))
        return outs[0] if n_out == 1 else outs


def _np_of(var, env):
    return env[id(var)]


def _lower_eqn(ctx, eqn, env):
    """env: id(var) -> ONNX value name."""
    prim = eqn.primitive.name
    invals = []
    for v in eqn.invars:
        if isinstance(v, jax.extend.core.Literal):
            invals.append(ctx.const(np.asarray(v.val), "lit"))
        else:
            invals.append(env[id(v)])

    def out(name):
        env[id(eqn.outvars[0])] = name

    p = eqn.params
    simple = {
        "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
        "max": "Max", "min": "Min", "pow": "Pow", "rem": None,
        "tanh": "Tanh", "exp": "Exp", "log": "Log", "neg": "Neg",
        "abs": "Abs", "sqrt": "Sqrt", "rsqrt": None, "logistic": "Sigmoid",
        "floor": "Floor", "ceil": "Ceil", "round": "Round", "sign": "Sign",
        "sin": "Sin", "cos": "Cos", "erf": "Erf", "sinh": "Sinh",
        "cosh": "Cosh", "atan": "Atan", "asin": "Asin", "acos": "Acos",
        "and": "And", "or": "Or", "not": "Not", "xor": "Xor",
        "eq": "Equal", "ne": None, "lt": "Less", "le": "LessOrEqual",
        "gt": "Greater", "ge": "GreaterOrEqual",
    }
    if prim in simple and simple[prim]:
        out(ctx.emit(simple[prim], invals))
    elif prim == "rsqrt":
        s = ctx.emit("Sqrt", invals)
        one = ctx.const(np.ones((), eqn.outvars[0].aval.dtype))
        out(ctx.emit("Div", [one, s]))
    elif prim == "ne":
        e = ctx.emit("Equal", invals)
        out(ctx.emit("Not", [e]))
    elif prim == "rem":
        # lax.rem is TRUNCATED remainder == ONNX Mod with fmod=1
        out(ctx.emit("Mod", invals, fmod=1))
    elif prim == "integer_pow":
        y = ctx.const(np.asarray(p["y"], eqn.invars[0].aval.dtype))
        out(ctx.emit("Pow", [invals[0], y]))
    elif prim == "dot_general":
        out(_lower_dot(ctx, eqn, invals))
    elif prim == "conv_general_dilated":
        out(_lower_conv(ctx, eqn, invals))
    elif prim == "reduce_window_max":
        out(_lower_pool(ctx, eqn, invals, "MaxPool"))
    elif prim == "reduce_window_sum":
        out(_lower_pool(ctx, eqn, invals, "SumPool"))
    elif prim in ("reduce_sum", "reduce_max", "reduce_min", "reduce_prod"):
        op = {"reduce_sum": "ReduceSum", "reduce_max": "ReduceMax",
              "reduce_min": "ReduceMin", "reduce_prod": "ReduceProd"}[prim]
        axes = ctx.const(np.asarray(p["axes"], np.int64))
        out(ctx.emit(op, [invals[0], axes], keepdims=0))
    elif prim == "argmax":
        am = ctx.emit("ArgMax", invals, axis=int(p["axes"][0]), keepdims=0)
        want = np.dtype(p["index_dtype"])
        if want != np.int64:     # ONNX ArgMax always emits int64
            am = ctx.emit("Cast", [am], to=int(proto.NP2ONNX[want]))
        out(am)
    elif prim == "reshape":
        shape = ctx.const(np.asarray(eqn.outvars[0].aval.shape, np.int64))
        out(ctx.emit("Reshape", [invals[0], shape]))
    elif prim == "squeeze":
        axes = ctx.const(np.asarray(p["dimensions"], np.int64))
        out(ctx.emit("Squeeze", [invals[0], axes]))
    elif prim == "expand_dims":
        axes = ctx.const(np.asarray(p["dimensions"], np.int64))
        out(ctx.emit("Unsqueeze", [invals[0], axes]))
    elif prim == "transpose":
        out(ctx.emit("Transpose", invals, perm=list(p["permutation"])))
    elif prim == "broadcast_in_dim":
        out(_lower_broadcast(ctx, eqn, invals))
    elif prim == "concatenate":
        out(ctx.emit("Concat", invals, axis=int(p["dimension"])))
    elif prim == "slice":
        starts = ctx.const(np.asarray(p["start_indices"], np.int64))
        ends = ctx.const(np.asarray(p["limit_indices"], np.int64))
        axes = ctx.const(np.arange(len(p["start_indices"]), dtype=np.int64))
        steps = ctx.const(np.asarray(p["strides"] or
                                     [1] * len(p["start_indices"]), np.int64))
        out(ctx.emit("Slice", [invals[0], starts, ends, axes, steps]))
    elif prim == "pad":
        lo = [c[0] for c in p["padding_config"]]
        hi = [c[1] for c in p["padding_config"]]
        if any(c[2] != 0 for c in p["padding_config"]):
            raise NotImplementedError("onnx export: interior padding")
        pads = ctx.const(np.asarray(lo + hi, np.int64))
        out(ctx.emit("Pad", [invals[0], pads, invals[1]]))
    elif prim == "select_n":
        if len(eqn.invars) != 3 or \
                eqn.invars[0].aval.dtype != np.dtype(np.bool_):
            raise NotImplementedError(
                "onnx export: select_n with >2 cases / integer predicate")
        # jax select_n(pred, on_false, on_true) -> Where(pred, true, false)
        out(ctx.emit("Where", [invals[0], invals[2], invals[1]]))
    elif prim == "convert_element_type":
        out(ctx.emit("Cast", invals,
                     to=int(proto.NP2ONNX[np.dtype(p["new_dtype"])])))
    elif prim == "stop_gradient":
        env[id(eqn.outvars[0])] = invals[0]
    elif prim == "custom_jvp_call" or prim == "custom_vjp_call":
        cj = p["call_jaxpr"]
        _inline(ctx, cj.jaxpr if hasattr(cj, "jaxpr") else cj,
                eqn, env, invals, consts=getattr(cj, "consts", ()))
    elif prim in ("pjit", "jit", "closed_call"):
        _inline(ctx, p["jaxpr"].jaxpr, eqn, env, invals,
                consts=p["jaxpr"].consts)
    else:
        raise NotImplementedError(
            f"onnx export: unsupported primitive '{prim}' — the portable "
            "fallback is paddle.jit.save (StableHLO)")


def _inline(ctx, jaxpr, eqn, env, invals, consts=()):
    inner = {}
    for cv, c in zip(jaxpr.constvars, consts):
        inner[id(cv)] = ctx.const(np.asarray(c), "w")
    for v, name in zip(jaxpr.invars, invals):
        inner[id(v)] = name
    _lower_jaxpr(ctx, jaxpr, inner)
    for ov, iv in zip(eqn.outvars, jaxpr.outvars):
        if isinstance(iv, jax.extend.core.Literal):
            env[id(ov)] = ctx.const(np.asarray(iv.val), "lit")
        else:
            env[id(ov)] = inner[id(iv)]


def _lower_dot(ctx, eqn, invals):
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    ln, rn = lhs.ndim, rhs.ndim
    nb = len(lb)
    # standard matmul patterns ONLY: contract last of lhs with
    # second-to-last (or only) dim of rhs, batch dims leading and aligned,
    # and rhs has no extra non-batch dims that MatMul would broadcast into
    # a transposed result
    if (list(lb) == list(range(nb)) and list(rb) == list(range(nb))
            and len(lc) == 1 and len(rc) == 1 and lc[0] == ln - 1
            and ((nb == 0 and ln >= 1
                  and ((rn == 2 and rc[0] == 0) or (rn == 1 and rc[0] == 0)))
                 or (nb > 0 and ln - nb == 2 and rn - nb == 2
                     and rc[0] == rn - 2))):
        # MatMul broadcast matches dot_general ONLY for these shapes: a
        # batched vector operand would broadcast into a transposed result
        return ctx.emit("MatMul", invals)
    if len(lc) == 1 and len(rc) == 1 and not lb and not rb and rn <= 2:
        # contract arbitrary single dims: transpose into matmul form
        a = invals[0]
        if lc[0] != ln - 1:
            perm = [d for d in range(ln) if d != lc[0]] + [lc[0]]
            a = ctx.emit("Transpose", [a], perm=perm)
        b = invals[1]
        if rn == 2 and rc[0] != 0:
            b = ctx.emit("Transpose", [b], perm=[1, 0])
        return ctx.emit("MatMul", [a, b])
    raise NotImplementedError(
        f"onnx export: dot_general dims {eqn.params['dimension_numbers']}")


def _lower_conv(ctx, eqn, invals):
    p = eqn.params
    dn = p["dimension_numbers"]
    if dn.lhs_spec[:2] != (0, 1) or dn.out_spec[:2] != (0, 1) or \
            dn.rhs_spec[:2] != (0, 1):
        raise NotImplementedError("onnx export: conv layout != NCHW/OIHW")
    if any(d != 1 for d in p.get("lhs_dilation", ())):
        raise NotImplementedError(
            "onnx export: transposed convolution (lhs_dilation) — map to "
            "ConvTranspose is not implemented")
    if p.get("batch_group_count", 1) != 1:
        raise NotImplementedError("onnx export: batch_group_count != 1")
    pads_lo = [lo for lo, _ in p["padding"]]
    pads_hi = [hi for _, hi in p["padding"]]
    attrs = dict(strides=list(p["window_strides"]),
                 pads=pads_lo + pads_hi,
                 dilations=list(p["rhs_dilation"]),
                 group=int(p["feature_group_count"]))
    return ctx.emit("Conv", invals, **attrs)


def _lower_pool(ctx, eqn, invals, kind):
    p = eqn.params
    dims = p["window_dimensions"]
    if dims[0] != 1 or dims[1] != 1:
        raise NotImplementedError("onnx export: pooling over batch/channel")
    if any(d != 1 for d in p.get("window_dilation", ())) or \
            any(d != 1 for d in p.get("base_dilation", ())):
        raise NotImplementedError("onnx export: dilated pooling")
    if p["window_strides"][0] != 1 or p["window_strides"][1] != 1 or \
            p["padding"][0] != (0, 0) or p["padding"][1] != (0, 0):
        raise NotImplementedError(
            "onnx export: stride/padding on batch/channel dims")
    strides = list(p["window_strides"])[2:]
    pads = p["padding"]
    attrs = dict(kernel_shape=list(dims)[2:], strides=strides,
                 pads=[lo for lo, _ in pads[2:]] + [hi for _, hi in pads[2:]])
    if kind == "MaxPool":
        return ctx.emit("MaxPool", invals, **attrs)
    # SumPool = AveragePool * window size
    ap = ctx.emit("AveragePool", invals, count_include_pad=1, **attrs)
    n = int(np.prod(list(dims)[2:]))
    scale = ctx.const(np.asarray(n, eqn.outvars[0].aval.dtype))
    return ctx.emit("Mul", [ap, scale])


def _lower_broadcast(ctx, eqn, invals):
    p = eqn.params
    in_aval = eqn.invars[0].aval
    out_shape = p["shape"]
    bdims = p["broadcast_dimensions"]
    # reshape to out rank with 1s, then Expand
    interm = [1] * len(out_shape)
    for i, d in enumerate(bdims):
        interm[d] = in_aval.shape[i]
    name = invals[0]
    if tuple(interm) != tuple(in_aval.shape):
        shape = ctx.const(np.asarray(interm, np.int64))
        name = ctx.emit("Reshape", [name, shape])
    if tuple(interm) != tuple(out_shape):
        shape = ctx.const(np.asarray(out_shape, np.int64))
        name = ctx.emit("Expand", [name, shape])
    return name


def _lower_jaxpr(ctx, jaxpr, env):
    for eqn in jaxpr.eqns:
        _lower_eqn(ctx, eqn, env)


def export_traced(fn, example_args, graph_name="paddle_tpu_model",
                  opset=13):
    """Trace ``fn(*example_args)`` (pure, arrays in/out) and return ONNX
    model bytes."""
    closed = jax.make_jaxpr(fn)(*example_args)
    jaxpr = closed.jaxpr
    ctx = _Ctx()
    env = {}
    inputs = []
    for v, a in zip(jaxpr.invars, example_args):
        name = ctx.fresh("input")
        env[id(v)] = name
        inputs.append(proto.value_info(name, np.asarray(a).dtype,
                                       np.asarray(a).shape))
    for cv, c in zip(jaxpr.constvars, closed.consts):
        env[id(cv)] = ctx.const(np.asarray(c), "w")
    _lower_jaxpr(ctx, jaxpr, env)
    outputs = []
    out_names = []
    for v in jaxpr.outvars:
        if isinstance(v, jax.extend.core.Literal):
            out_names.append(ctx.const(np.asarray(v.val), "lit"))
            aval_dtype, aval_shape = np.asarray(v.val).dtype, np.asarray(v.val).shape
        else:
            out_names.append(env[id(v)])
            aval_dtype, aval_shape = v.aval.dtype, v.aval.shape
        outputs.append(proto.value_info(out_names[-1], aval_dtype,
                                        aval_shape))
    # ONNX graph outputs must be produced by a node, once: wrap outputs
    # that alias an input/initializer (or repeat a name) in Identity
    produced = set()
    node_outs = {f for n in ctx.nodes for f in proto.parse_node(n)["output"]}
    for i, name in enumerate(out_names):
        if name not in node_outs or name in produced:
            alias = ctx.fresh("out")
            ctx.nodes.append(proto.node("Identity", [name], [alias]))
            out_names[i] = alias
            v = jaxpr.outvars[i]
            dt = (np.asarray(v.val).dtype
                  if isinstance(v, jax.extend.core.Literal) else v.aval.dtype)
            sh = (np.asarray(v.val).shape
                  if isinstance(v, jax.extend.core.Literal) else v.aval.shape)
            outputs[i] = proto.value_info(alias, dt, sh)
        produced.add(out_names[i])
    g = proto.graph(ctx.nodes, graph_name, ctx.inits, inputs, outputs)
    return proto.model(g, opset=opset)
