"""paddle.vision (reference: ``python/paddle/vision/`` — SURVEY.md §2.2)."""
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401


def set_image_backend(backend):
    pass


def get_image_backend():
    return "numpy"


def image_load(path, backend=None):
    """reference: ``paddle.vision.image_load`` — loads an image as HWC
    uint8. Zero-egress build: PNG/BMP via stdlib-adjacent decoders when
    PIL is absent."""
    import numpy as np
    try:
        from PIL import Image
        return np.asarray(Image.open(path).convert("RGB"))
    except ImportError:
        pass
    import struct
    import zlib
    data = open(path, "rb").read()
    if data[:8] == b"\x89PNG\r\n\x1a\n":
        pos, w = 8, None
        idat = b""
        while pos < len(data):
            ln, typ = struct.unpack(">I4s", data[pos:pos + 8])
            chunk = data[pos + 8:pos + 8 + ln]
            if typ == b"IHDR":
                w, h, depth, color = struct.unpack(">IIBB", chunk[:10])
                interlace = chunk[12]
                if depth != 8 or color not in (2, 6) or interlace != 0:
                    raise ValueError("stdlib PNG path supports 8-bit "
                                     "non-interlaced RGB/RGBA only")
                nch = 3 if color == 2 else 4
            elif typ == b"IDAT":
                idat += chunk
            pos += 12 + ln
        raw = zlib.decompress(idat)
        stride = w * nch
        out = np.empty((h, stride), np.uint8)
        prev = np.zeros(stride, np.uint8)
        p = 0
        for row in range(h):
            f = raw[p]
            line = np.frombuffer(raw[p + 1:p + 1 + stride],
                                 np.uint8).astype(np.int32)
            p += 1 + stride
            if f == 0:
                rec = line
            elif f == 2:               # up
                rec = (line + prev) % 256
            elif f == 1:               # sub: per-channel cumulative sum
                cols = line.reshape(w, nch)
                rec = np.cumsum(cols, axis=0, dtype=np.int64) % 256
                rec = rec.reshape(stride).astype(np.int32)
            else:                      # average / paeth need the scalar loop
                rec = np.zeros(stride, np.int32)
                for i in range(stride):
                    a = rec[i - nch] if i >= nch else 0
                    b = int(prev[i])
                    if f == 3:
                        rec[i] = (line[i] + (a + b) // 2) % 256
                    else:                       # paeth
                        c = int(prev[i - nch]) if i >= nch else 0
                        pa, pb, pc = abs(b - c), abs(a - c), abs(a + b - 2 * c)
                        pred = a if pa <= pb and pa <= pc else \
                            (b if pb <= pc else c)
                        rec[i] = (line[i] + pred) % 256
            out[row] = rec.astype(np.uint8)
            prev = out[row]
        img = out.reshape(h, w, nch)
        return img[:, :, :3]
    raise ValueError(f"image_load: unsupported format for {path!r} "
                     "(stdlib path reads PNG; install PIL for more)")
