"""paddle.vision.datasets (reference: ``python/paddle/vision/datasets/`` —
Cifar10/100, MNIST, Flowers; SURVEY.md §2.2).

Zero-egress environment: loaders read standard local archive layouts if
present (``download=True`` raises a clear error when files are missing) and a
``FakeData`` dataset provides deterministic synthetic data for tests/benches.
"""
from __future__ import annotations

import gzip
import os
import pickle
import tarfile

import numpy as np

from ...io import Dataset

_DEFAULT_ROOT = os.path.expanduser("~/.cache/paddle/dataset")


class FakeData(Dataset):
    """Deterministic synthetic image classification dataset."""

    def __init__(self, size=256, image_shape=(3, 32, 32), num_classes=10,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        rng = np.random.RandomState(seed)
        self.images = rng.randint(0, 256, (size,) + self.image_shape[1:] +
                                  (self.image_shape[0],), dtype=np.uint8)
        self.labels = rng.randint(0, num_classes, (size,), dtype=np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = np.transpose(img.astype(np.float32) / 255.0, (2, 0, 1))
        return img, int(self.labels[idx])

    def __len__(self):
        return self.size


class Cifar10(Dataset):
    """CIFAR-10 from the standard ``cifar-10-python.tar.gz`` / extracted
    ``cifar-10-batches-py`` layout under ``data_file`` or the default cache."""

    MEAN = [0.4914, 0.4822, 0.4465]
    STD = [0.2470, 0.2435, 0.2616]

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="cv2"):
        self.mode = mode
        self.transform = transform
        data, labels = self._load(data_file)
        self.data = data
        self.labels = labels

    def _candidate_paths(self, data_file):
        cands = []
        if data_file:
            cands.append(data_file)
        cands += [
            os.path.join(_DEFAULT_ROOT, "cifar", "cifar-10-python.tar.gz"),
            os.path.join(_DEFAULT_ROOT, "cifar-10-python.tar.gz"),
            os.path.join(_DEFAULT_ROOT, "cifar", "cifar-10-batches-py"),
        ]
        return cands

    def _load(self, data_file):
        batches = [f"data_batch_{i}" for i in range(1, 6)] \
            if self.mode == "train" else ["test_batch"]
        for path in self._candidate_paths(data_file):
            if not path or not os.path.exists(path):
                continue
            if path.endswith(".tar.gz"):
                data, labels = [], []
                with tarfile.open(path) as tf:
                    for b in batches:
                        f = tf.extractfile(f"cifar-10-batches-py/{b}")
                        d = pickle.load(f, encoding="bytes")
                        data.append(d[b"data"])
                        labels.extend(d[b"labels"])
                return (np.concatenate(data).reshape(-1, 3, 32, 32),
                        np.asarray(labels, np.int64))
            if os.path.isdir(path):
                data, labels = [], []
                for b in batches:
                    with open(os.path.join(path, b), "rb") as f:
                        d = pickle.load(f, encoding="bytes")
                    data.append(d[b"data"])
                    labels.extend(d[b"labels"])
                return (np.concatenate(data).reshape(-1, 3, 32, 32),
                        np.asarray(labels, np.int64))
        raise FileNotFoundError(
            "CIFAR-10 archive not found locally and downloads are disabled in "
            "this environment; place cifar-10-python.tar.gz under "
            f"{_DEFAULT_ROOT}/cifar/ or use vision.datasets.FakeData")

    def __getitem__(self, idx):
        img = np.transpose(self.data[idx], (1, 2, 0))  # HWC uint8
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = np.transpose(img.astype(np.float32) / 255.0, (2, 0, 1))
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    def _load(self, data_file):
        fname = "train" if self.mode == "train" else "test"
        for path in [data_file,
                     os.path.join(_DEFAULT_ROOT, "cifar", "cifar-100-python.tar.gz")]:
            if not path or not os.path.exists(path):
                continue
            with tarfile.open(path) as tf:
                f = tf.extractfile(f"cifar-100-python/{fname}")
                d = pickle.load(f, encoding="bytes")
            return (d[b"data"].reshape(-1, 3, 32, 32),
                    np.asarray(d[b"fine_labels"], np.int64))
        raise FileNotFoundError("CIFAR-100 archive not found locally")


class MNIST(Dataset):
    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.transform = transform
        prefix = "train" if mode == "train" else "t10k"
        root = os.path.join(_DEFAULT_ROOT, "mnist")
        image_path = image_path or os.path.join(root, f"{prefix}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(root, f"{prefix}-labels-idx1-ubyte.gz")
        if not (os.path.exists(image_path) and os.path.exists(label_path)):
            raise FileNotFoundError(
                f"MNIST files not found at {root}; downloads disabled — "
                "use vision.datasets.FakeData for synthetic data")
        with gzip.open(image_path, "rb") as f:
            buf = f.read()
            self.images = np.frombuffer(buf, np.uint8, offset=16).reshape(-1, 28, 28)
        with gzip.open(label_path, "rb") as f:
            buf = f.read()
            self.labels = np.frombuffer(buf, np.uint8, offset=8).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = (img.astype(np.float32) / 255.0)[None]
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class _CachedVisionDataset(Dataset):
    """Reference vision datasets in the zero-egress build: resolve the
    archive from the shared ~/.cache/paddle/dataset root and raise with
    the expected path on a miss (reference:
    ``python/paddle/vision/datasets/``)."""

    _filename = None

    def __init__(self, data_file=None, mode="train", transform=None, **kw):
        self.mode = mode
        self.transform = transform
        if data_file is None:
            from ...utils import dataset_cache_path
            data_file = dataset_cache_path(self._filename)
        if not os.path.exists(data_file):
            raise IOError(
                f"{type(self).__name__}: no network egress in the TPU "
                f"build — place the reference archive at {data_file}")
        self.data_file = data_file
        self._load()

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        img, label = self.samples[i]
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class Flowers:
    """102-category flowers. The raw 102flowers.tgz needs PIL jpeg
    decoding (not in this build) — use :class:`FlowersArrays` with a
    pre-extracted ``flowers_<mode>.npz``; this class exists to give that
    guidance at construction time."""

    def __init__(self, *a, **kw):
        raise NotImplementedError(
            "Flowers: jpeg decoding is unavailable offline; extract the "
            "archive to flowers_<mode>.npz ({'images': uint8 NHWC, "
            "'labels': int64}) and use vision.datasets.FlowersArrays")


class FlowersArrays(_CachedVisionDataset):
    """Flowers from a pre-extracted ``flowers_<mode>.npz`` (images uint8
    NHWC + labels int64) — the decoded-array path for offline machines."""

    def __init__(self, data_file=None, mode="train", transform=None, **kw):
        self._filename = f"flowers_{mode}.npz"
        super().__init__(data_file, mode, transform, **kw)

    def _load(self):
        blob = np.load(self.data_file)
        self.samples = [(blob["images"][i], int(blob["labels"][i]))
                        for i in range(len(blob["labels"]))]


class VOC2012(_CachedVisionDataset):
    """Pascal VOC 2012 segmentation pairs from a pre-extracted
    ``voc2012_<mode>.npz`` ({'images': uint8 NHWC, 'masks': uint8 NHW})."""

    def __init__(self, data_file=None, mode="train", transform=None, **kw):
        self._filename = f"voc2012_{mode}.npz"
        super().__init__(data_file, mode, transform, **kw)

    def _load(self):
        blob = np.load(self.data_file)
        self.samples = [(blob["images"][i], blob["masks"][i])
                        for i in range(len(blob["images"]))]
