"""paddle.vision.ops (reference: ``python/paddle/vision/ops.py`` — nms,
box coders, roi_align, yolo post-processing over phi kernels; SURVEY.md §2.2,
§2.4 config 3 "PP-YOLOE").

TPU-native notes: NMS is inherently sequential; XLA-friendly form is the
fixed-iteration suppression loop (lax.fori_loop over a static max-box count)
so the op jits with static shapes. roi_align uses bilinear gather — XLA
batches the gathers.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..autograd.tape import apply

__all__ = ["nms", "box_area", "box_iou", "distance2bbox", "roi_align",
           "yolo_box", "generate_proposals", "box_coder"]


def box_area(boxes):
    def fn(b):
        return (b[..., 2] - b[..., 0]) * (b[..., 3] - b[..., 1])
    return apply(fn, boxes, op_name="box_area")


def _iou_matrix(a, b):
    """a [N,4], b [M,4] xyxy → [N,M] IoU (pure jnp)."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter,
                               1e-10)


def box_iou(boxes1, boxes2):
    return apply(_iou_matrix, boxes1, boxes2, op_name="box_iou")


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS. Returns kept indices sorted by descending score
    (reference contract). Category-aware when category_idxs given."""
    b = boxes._data if isinstance(boxes, Tensor) else jnp.asarray(boxes)
    n = b.shape[0]
    s = (scores._data if isinstance(scores, Tensor)
         else jnp.asarray(scores)) if scores is not None \
        else jnp.arange(n, 0, -1, dtype=jnp.float32)
    order = jnp.argsort(-s)
    bs = b[order]
    iou = _iou_matrix(bs, bs)
    if category_idxs is not None:
        c = (category_idxs._data if isinstance(category_idxs, Tensor)
             else jnp.asarray(category_idxs))[order]
        same = c[:, None] == c[None, :]
        iou = jnp.where(same, iou, 0.0)

    # fixed-iteration suppression in sorted space: box i is kept unless a
    # higher-scored kept box overlaps it above the threshold
    def body(i, keep):
        sup = jnp.logical_and(keep, iou[:, i] > iou_threshold)
        sup = jnp.logical_and(sup, jnp.arange(n) < i)   # only earlier boxes
        return keep.at[i].set(~jnp.any(sup))

    keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    # output length is data-dependent → extract indices host-side (eager op,
    # reference contract returns a variable-length index tensor)
    import numpy as np
    keep_np = np.asarray(jax.device_get(keep))
    order_np = np.asarray(jax.device_get(order))
    idx = order_np[keep_np]
    if top_k is not None:
        idx = idx[:top_k]
    return Tensor(idx.astype("int64"))


def distance2bbox(points, distance, max_shapes=None):
    """Anchor-free head decode (PP-YOLOE): points [..., 2] + ltrb distances
    [..., 4] → xyxy boxes."""
    def fn(p, d):
        x1 = p[..., 0] - d[..., 0]
        y1 = p[..., 1] - d[..., 1]
        x2 = p[..., 0] + d[..., 2]
        y2 = p[..., 1] + d[..., 3]
        out = jnp.stack([x1, y1, x2, y2], -1)
        if max_shapes is not None:
            h, w = max_shapes[0], max_shapes[1]
            out = jnp.stack([jnp.clip(out[..., 0], 0, w),
                             jnp.clip(out[..., 1], 0, h),
                             jnp.clip(out[..., 2], 0, w),
                             jnp.clip(out[..., 3], 0, h)], -1)
        return out

    return apply(fn, points, distance, op_name="distance2bbox")


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign: x [N,C,H,W], boxes [R,4] xyxy (in image coords), boxes_num
    [N] rois per image. Output [R, C, out_h, out_w]."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def fn(feat, rois, rois_num):
        n, c, h, w = feat.shape
        r = rois.shape[0]
        # image index per roi from boxes_num
        img_idx = jnp.repeat(jnp.arange(n), rois_num, total_repeat_length=r)
        off = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - off
        y1 = rois[:, 1] * spatial_scale - off
        x2 = rois[:, 2] * spatial_scale - off
        y2 = rois[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3)
        rh = jnp.maximum(y2 - y1, 1e-3)
        sr = sampling_ratio if sampling_ratio > 0 else 2
        # sample grid: oh*ow bins × sr×sr points per bin, bilinear each
        ys = (y1[:, None] + (jnp.arange(oh * sr) + 0.5)[None, :]
              * rh[:, None] / (oh * sr))                       # [R, oh*sr]
        xs = (x1[:, None] + (jnp.arange(ow * sr) + 0.5)[None, :]
              * rw[:, None] / (ow * sr))                       # [R, ow*sr]

        def bilinear(img, yy, xx):
            # img [C,H,W]; yy [P], xx [Q] → [C,P,Q]
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
            y1i = jnp.clip(y0 + 1, 0, h - 1)
            x1i = jnp.clip(x0 + 1, 0, w - 1)
            wy = jnp.clip(yy, 0, h - 1) - y0
            wx = jnp.clip(xx, 0, w - 1) - x0
            v00 = img[:, y0][:, :, x0]
            v01 = img[:, y0][:, :, x1i]
            v10 = img[:, y1i][:, :, x0]
            v11 = img[:, y1i][:, :, x1i]
            return (v00 * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
                    + v01 * (1 - wy)[None, :, None] * wx[None, None, :]
                    + v10 * wy[None, :, None] * (1 - wx)[None, None, :]
                    + v11 * wy[None, :, None] * wx[None, None, :])

        def per_roi(i):
            img = feat[img_idx[i]]
            vals = bilinear(img, ys[i], xs[i])       # [C, oh*sr, ow*sr]
            vals = vals.reshape(c, oh, sr, ow, sr)
            return vals.mean(axis=(2, 4))            # [C, oh, ow]

        return jax.vmap(per_roi)(jnp.arange(r))

    return apply(fn, x, boxes, boxes_num, op_name="roi_align")


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLO head output [N, A*(5+C), H, W] into boxes+scores
    (reference yolo_box semantics, simplified: returns (boxes, scores))."""
    na = len(anchors) // 2

    def fn(p, imgs):
        n, _, h, w = p.shape
        p = p.reshape(n, na, 5 + class_num, h, w)
        gx = (jnp.arange(w)[None, None, None, :] + 0.5 * (scale_x_y - 1)
              + jax.nn.sigmoid(p[:, :, 0]) * scale_x_y) / w
        gy = (jnp.arange(h)[None, None, :, None] + 0.5 * (scale_x_y - 1)
              + jax.nn.sigmoid(p[:, :, 1]) * scale_x_y) / h
        aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
        ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]
        bw = jnp.exp(p[:, :, 2]) * aw / (w * downsample_ratio)
        bh = jnp.exp(p[:, :, 3]) * ah / (h * downsample_ratio)
        conf = jax.nn.sigmoid(p[:, :, 4])
        cls = jax.nn.sigmoid(p[:, :, 5:])
        scores = conf[:, :, None] * cls              # [n, a, C, h, w]
        imh = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        imw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (gx - bw / 2) * imw
        y1 = (gy - bh / 2) * imh
        x2 = (gx + bw / 2) * imw
        y2 = (gy + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(n, -1, 4)
        scores = scores.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
        mask = scores.max(-1, keepdims=True) >= conf_thresh
        scores = jnp.where(mask, scores, 0.0)
        return boxes, scores

    return apply(fn, x, img_size, op_name="yolo_box")


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0):
    raise NotImplementedError("box_coder: use distance2bbox / yolo_box "
                              "decoders in the TPU build")


def generate_proposals(*a, **kw):
    raise NotImplementedError("RPN generate_proposals is two-stage-detector "
                              "specific; the TPU build ships anchor-free "
                              "decode (distance2bbox) + nms")
