"""paddle.vision.ops (reference: ``python/paddle/vision/ops.py`` — nms,
box coders, roi_align, yolo post-processing over phi kernels; SURVEY.md §2.2,
§2.4 config 3 "PP-YOLOE").

TPU-native notes: NMS is inherently sequential; XLA-friendly form is the
fixed-iteration suppression loop (lax.fori_loop over a static max-box count)
so the op jits with static shapes. roi_align uses bilinear gather — XLA
batches the gathers.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..autograd.tape import apply

__all__ = ["nms", "box_area", "box_iou", "distance2bbox", "roi_align",
           "yolo_box", "generate_proposals", "box_coder", "roi_pool",
           "ps_roi_pool", "deform_conv2d", "matrix_nms", "prior_box",
           "distribute_fpn_proposals", "RoIAlign", "RoIPool", "PSRoIPool",
           "DeformConv2D"]


def box_area(boxes):
    def fn(b):
        return (b[..., 2] - b[..., 0]) * (b[..., 3] - b[..., 1])
    return apply(fn, boxes, op_name="box_area")


def _iou_matrix(a, b):
    """a [N,4], b [M,4] xyxy → [N,M] IoU (pure jnp)."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter,
                               1e-10)


def box_iou(boxes1, boxes2):
    return apply(_iou_matrix, boxes1, boxes2, op_name="box_iou")


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS. Returns kept indices sorted by descending score
    (reference contract). Category-aware when category_idxs given."""
    b = boxes._data if isinstance(boxes, Tensor) else jnp.asarray(boxes)
    n = b.shape[0]
    s = (scores._data if isinstance(scores, Tensor)
         else jnp.asarray(scores)) if scores is not None \
        else jnp.arange(n, 0, -1, dtype=jnp.float32)
    order = jnp.argsort(-s)
    bs = b[order]
    iou = _iou_matrix(bs, bs)
    if category_idxs is not None:
        c = (category_idxs._data if isinstance(category_idxs, Tensor)
             else jnp.asarray(category_idxs))[order]
        same = c[:, None] == c[None, :]
        iou = jnp.where(same, iou, 0.0)

    # fixed-iteration suppression in sorted space: box i is kept unless a
    # higher-scored kept box overlaps it above the threshold
    def body(i, keep):
        sup = jnp.logical_and(keep, iou[:, i] > iou_threshold)
        sup = jnp.logical_and(sup, jnp.arange(n) < i)   # only earlier boxes
        return keep.at[i].set(~jnp.any(sup))

    keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    # output length is data-dependent → extract indices host-side (eager op,
    # reference contract returns a variable-length index tensor)
    import numpy as np
    keep_np = np.asarray(jax.device_get(keep))
    order_np = np.asarray(jax.device_get(order))
    idx = order_np[keep_np]
    if top_k is not None:
        idx = idx[:top_k]
    return Tensor(idx.astype("int64"))


def distance2bbox(points, distance, max_shapes=None):
    """Anchor-free head decode (PP-YOLOE): points [..., 2] + ltrb distances
    [..., 4] → xyxy boxes."""
    def fn(p, d):
        x1 = p[..., 0] - d[..., 0]
        y1 = p[..., 1] - d[..., 1]
        x2 = p[..., 0] + d[..., 2]
        y2 = p[..., 1] + d[..., 3]
        out = jnp.stack([x1, y1, x2, y2], -1)
        if max_shapes is not None:
            h, w = max_shapes[0], max_shapes[1]
            out = jnp.stack([jnp.clip(out[..., 0], 0, w),
                             jnp.clip(out[..., 1], 0, h),
                             jnp.clip(out[..., 2], 0, w),
                             jnp.clip(out[..., 3], 0, h)], -1)
        return out

    return apply(fn, points, distance, op_name="distance2bbox")


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign: x [N,C,H,W], boxes [R,4] xyxy (in image coords), boxes_num
    [N] rois per image. Output [R, C, out_h, out_w]."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def fn(feat, rois, rois_num):
        n, c, h, w = feat.shape
        r = rois.shape[0]
        # image index per roi from boxes_num
        img_idx = jnp.repeat(jnp.arange(n), rois_num, total_repeat_length=r)
        off = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - off
        y1 = rois[:, 1] * spatial_scale - off
        x2 = rois[:, 2] * spatial_scale - off
        y2 = rois[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3)
        rh = jnp.maximum(y2 - y1, 1e-3)
        sr = sampling_ratio if sampling_ratio > 0 else 2
        # sample grid: oh*ow bins × sr×sr points per bin, bilinear each
        ys = (y1[:, None] + (jnp.arange(oh * sr) + 0.5)[None, :]
              * rh[:, None] / (oh * sr))                       # [R, oh*sr]
        xs = (x1[:, None] + (jnp.arange(ow * sr) + 0.5)[None, :]
              * rw[:, None] / (ow * sr))                       # [R, ow*sr]

        def bilinear(img, yy, xx):
            # img [C,H,W]; yy [P], xx [Q] → [C,P,Q]
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
            y1i = jnp.clip(y0 + 1, 0, h - 1)
            x1i = jnp.clip(x0 + 1, 0, w - 1)
            wy = jnp.clip(yy, 0, h - 1) - y0
            wx = jnp.clip(xx, 0, w - 1) - x0
            v00 = img[:, y0][:, :, x0]
            v01 = img[:, y0][:, :, x1i]
            v10 = img[:, y1i][:, :, x0]
            v11 = img[:, y1i][:, :, x1i]
            return (v00 * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
                    + v01 * (1 - wy)[None, :, None] * wx[None, None, :]
                    + v10 * wy[None, :, None] * (1 - wx)[None, None, :]
                    + v11 * wy[None, :, None] * wx[None, None, :])

        def per_roi(i):
            img = feat[img_idx[i]]
            vals = bilinear(img, ys[i], xs[i])       # [C, oh*sr, ow*sr]
            vals = vals.reshape(c, oh, sr, ow, sr)
            return vals.mean(axis=(2, 4))            # [C, oh, ow]

        return jax.vmap(per_roi)(jnp.arange(r))

    return apply(fn, x, boxes, boxes_num, op_name="roi_align")


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLO head output [N, A*(5+C), H, W] into boxes+scores
    (reference yolo_box semantics, simplified: returns (boxes, scores))."""
    na = len(anchors) // 2

    def fn(p, imgs):
        n, _, h, w = p.shape
        p = p.reshape(n, na, 5 + class_num, h, w)
        gx = (jnp.arange(w)[None, None, None, :] + 0.5 * (scale_x_y - 1)
              + jax.nn.sigmoid(p[:, :, 0]) * scale_x_y) / w
        gy = (jnp.arange(h)[None, None, :, None] + 0.5 * (scale_x_y - 1)
              + jax.nn.sigmoid(p[:, :, 1]) * scale_x_y) / h
        aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
        ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]
        bw = jnp.exp(p[:, :, 2]) * aw / (w * downsample_ratio)
        bh = jnp.exp(p[:, :, 3]) * ah / (h * downsample_ratio)
        conf = jax.nn.sigmoid(p[:, :, 4])
        cls = jax.nn.sigmoid(p[:, :, 5:])
        scores = conf[:, :, None] * cls              # [n, a, C, h, w]
        imh = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        imw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (gx - bw / 2) * imw
        y1 = (gy - bh / 2) * imh
        x2 = (gx + bw / 2) * imw
        y2 = (gy + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(n, -1, 4)
        scores = scores.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
        mask = scores.max(-1, keepdims=True) >= conf_thresh
        scores = jnp.where(mask, scores, 0.0)
        return boxes, scores

    return apply(fn, x, img_size, op_name="yolo_box")


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0):
    raise NotImplementedError("box_coder: use distance2bbox / yolo_box "
                              "decoders in the TPU build")


def generate_proposals(*a, **kw):
    raise NotImplementedError("RPN generate_proposals is two-stage-detector "
                              "specific; the TPU build ships anchor-free "
                              "decode (distance2bbox) + nms")


# ---------------------------------------------------------------------------
# round-4 detection surface: roi_pool / ps_roi_pool / deform_conv2d /
# matrix_nms / prior_box / distribute_fpn_proposals (+ Layer wrappers)
# ---------------------------------------------------------------------------

def _roi_bins(rois, spatial_scale, oh, ow, h, w):
    """Quantized roi_pool bin masks (reference roi_pool quantization:
    rounded roi corners, floor/ceil bin edges). Returns per-bin row/col
    membership masks [R, oh, H], [R, ow, W] and the empty-bin flags."""
    rsw = jnp.round(rois[:, 0] * spatial_scale)
    rsh = jnp.round(rois[:, 1] * spatial_scale)
    rew = jnp.round(rois[:, 2] * spatial_scale)
    reh = jnp.round(rois[:, 3] * spatial_scale)
    roi_w = jnp.maximum(rew - rsw + 1.0, 1.0)
    roi_h = jnp.maximum(reh - rsh + 1.0, 1.0)
    bin_h = roi_h / oh
    bin_w = roi_w / ow
    ih = jnp.arange(oh, dtype=jnp.float32)
    iw = jnp.arange(ow, dtype=jnp.float32)
    hs = jnp.clip(jnp.floor(ih[None] * bin_h[:, None]) + rsh[:, None], 0, h)
    he = jnp.clip(jnp.ceil((ih[None] + 1) * bin_h[:, None]) + rsh[:, None],
                  0, h)
    ws = jnp.clip(jnp.floor(iw[None] * bin_w[:, None]) + rsw[:, None], 0, w)
    we = jnp.clip(jnp.ceil((iw[None] + 1) * bin_w[:, None]) + rsw[:, None],
                  0, w)
    hh = jnp.arange(h, dtype=jnp.float32)
    ww = jnp.arange(w, dtype=jnp.float32)
    mask_h = (hh[None, None, :] >= hs[:, :, None]) & \
             (hh[None, None, :] < he[:, :, None])           # [R, oh, H]
    mask_w = (ww[None, None, :] >= ws[:, :, None]) & \
             (ww[None, None, :] < we[:, :, None])           # [R, ow, W]
    empty = (he <= hs)[:, :, None] | (we <= ws)[:, None, :]  # [R, oh, ow]
    return mask_h, mask_w, empty


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """RoIPool (reference ``paddle.vision.ops.roi_pool``): max over
    quantized bins. x [N,C,H,W], boxes [R,4] xyxy, boxes_num [N] →
    [R, C, oh, ow]. TPU-native: per-bin membership masks + two masked max
    reductions (no data-dependent slicing; jits with static shapes)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def fn(feat, rois, rois_num):
        n, c, h, w = feat.shape
        r = rois.shape[0]
        img_idx = jnp.repeat(jnp.arange(n), rois_num, total_repeat_length=r)
        mask_h, mask_w, empty = _roi_bins(rois, spatial_scale, oh, ow, h, w)
        fi = feat[img_idx]                                   # [R, C, H, W]
        neg = jnp.asarray(-3.4e38, fi.dtype)
        # max over W per bin_w: [R,C,H,1,W] x [R,1,1,ow,W] -> [R,C,H,ow]
        t = jnp.where(mask_w[:, None, None, :, :],
                      fi[:, :, :, None, :], neg).max(axis=-1)
        # max over H per bin_h: [R,C,1,H,ow] x [R,1,oh,H,1] -> [R,C,oh,ow]
        out = jnp.where(mask_h[:, None, :, :, None],
                        t[:, :, None, :, :], neg).max(axis=3)
        return jnp.where(empty[:, None], 0.0, out).astype(feat.dtype)

    return apply(fn, x, boxes, boxes_num, op_name="roi_pool")


def ps_roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """Position-sensitive RoI average pool (reference ``ps_roi_pool``):
    input channels C = out_c·oh·ow, bin (i, j) reads channel slice
    ``c_out·oh·ow + i·ow + j``; returns [R, out_c, oh, ow]."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def fn(feat, rois, rois_num):
        n, c, h, w = feat.shape
        assert c % (oh * ow) == 0, \
            f"ps_roi_pool needs channels divisible by {oh * ow}, got {c}"
        out_c = c // (oh * ow)
        r = rois.shape[0]
        img_idx = jnp.repeat(jnp.arange(n), rois_num, total_repeat_length=r)
        mask_h, mask_w, empty = _roi_bins(rois, spatial_scale, oh, ow, h, w)
        fi = feat[img_idx].reshape(r, out_c, oh, ow, h, w)
        mh = mask_h[:, None, :, None, :, None].astype(fi.dtype)
        mw = mask_w[:, None, None, :, None, :].astype(fi.dtype)
        m = mh * mw                                         # [R,1,oh,ow,H,W]
        s = (fi * m).sum(axis=(-2, -1))
        cnt = jnp.maximum(m.sum(axis=(-2, -1)), 1.0)
        out = s / cnt
        return jnp.where(empty[:, None], 0.0, out).astype(feat.dtype)

    return apply(fn, x, boxes, boxes_num, op_name="ps_roi_pool")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None):
    """Deformable conv v1/v2 (reference ``paddle.vision.ops.deform_conv2d``
    over the phi ``deformable_conv`` kernel). x [N,Cin,H,W]; offset
    [N, 2·dg·kh·kw, Ho, Wo] ordered (dy, dx) per kernel point; mask (v2)
    [N, dg·kh·kw, Ho, Wo]; weight [Cout, Cin//groups, kh, kw].

    TPU-native: bilinear-sample every kernel tap for every output site in
    one vectorized gather (zero outside the feature map), then contract
    taps×channels with the weights on the MXU via einsum — no im2col
    scratch in HBM beyond the sampled taps, fully differentiable."""
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else dilation

    def fn(xa, off, wgt, *rest):
        msk = rest[0] if mask is not None else None
        n, cin, h, w = xa.shape
        cout, cin_g, kh, kw = wgt.shape
        dg = deformable_groups
        ho = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        wo = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        k = kh * kw
        off = off.reshape(n, dg, k, 2, ho, wo)
        ky = jnp.repeat(jnp.arange(kh) * dh, kw)              # [k]
        kx = jnp.tile(jnp.arange(kw) * dw, kh)                # [k]
        gy = (jnp.arange(ho) * sh - ph)[None, :, None] + ky[:, None, None]
        gx = (jnp.arange(wo) * sw - pw)[None, None, :] + kx[:, None, None]
        ys = gy[None, None] + off[:, :, :, 0]                 # [N,dg,k,ho,wo]
        xs = gx[None, None] + off[:, :, :, 1]

        y0 = jnp.floor(ys)
        x0 = jnp.floor(xs)
        wy = ys - y0
        wx = xs - x0

        def gather(yi, xi):
            valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
            yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
            xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
            # per-dg channel slice shares its sampling grid
            xg = xa.reshape(n, dg, cin // dg, h, w)
            flat = xg.reshape(n, dg, cin // dg, h * w)
            idx = (yc * w + xc).reshape(n, dg, -1)            # [N,dg,k*ho*wo]
            vals = jnp.take_along_axis(flat, idx[:, :, None, :], axis=-1)
            vals = vals.reshape(n, dg, cin // dg, k, ho, wo)
            return vals * valid[:, :, None].astype(xa.dtype)

        v00 = gather(y0, x0)
        v01 = gather(y0, x0 + 1)
        v10 = gather(y0 + 1, x0)
        v11 = gather(y0 + 1, x0 + 1)
        wyc = wy[:, :, None]
        wxc = wx[:, :, None]
        sampled = (v00 * (1 - wyc) * (1 - wxc) + v01 * (1 - wyc) * wxc +
                   v10 * wyc * (1 - wxc) + v11 * wyc * wxc)
        if msk is not None:
            sampled = sampled * msk.reshape(n, dg, 1, k, ho, wo)
        sampled = sampled.reshape(n, cin, k, ho, wo)
        xg = sampled.reshape(n, groups, cin // groups, k, ho, wo)
        wg = wgt.reshape(groups, cout // groups, cin_g, k)
        out = jnp.einsum("ngckhw,gock->ngohw", xg, wg, optimize=True)
        out = out.reshape(n, cout, ho, wo)
        if bias is not None:
            out = out + rest[-1][None, :, None, None]
        return out

    args = (x, offset, weight)
    if mask is not None:
        args = args + (mask,)
    if bias is not None:
        args = args + (bias,)
    return apply(fn, *args, op_name="deform_conv2d")


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, normalized=True):
    """Matrix NMS (reference ``matrix_nms``, the SOLOv2 decay NMS) —
    inherently parallel (one IoU matrix, no sequential suppression), the
    NMS variant that actually fits the TPU. bboxes [N,4], scores [C,N].
    Returns (out [M,6] = (label, score, x1, y1, x2, y2), index [M])."""
    import numpy as np

    bx = bboxes._data if isinstance(bboxes, Tensor) else jnp.asarray(bboxes)
    sc = scores._data if isinstance(scores, Tensor) else jnp.asarray(scores)
    n_cls, n = sc.shape
    k = min(int(nms_top_k), n)
    off = 0.0 if normalized else 1.0

    def iou_off(a, b):
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt + off, 0)
        inter = wh[..., 0] * wh[..., 1]
        area = lambda t: ((t[:, 2] - t[:, 0] + off)
                          * (t[:, 3] - t[:, 1] + off))
        return inter / jnp.maximum(area(a)[:, None] + area(b)[None, :]
                                   - inter, 1e-10)

    def per_class(s):
        # reference order: prefilter by RAW score, then decay, then
        # post_threshold filters the decayed scores
        s = jnp.where(s >= score_threshold, s, -jnp.inf)
        order = jnp.argsort(-s)[:k]
        bs = bx[order]
        ss = s[order]
        iou = iou_off(bs, bs)
        tri = jnp.tril(iou, k=-1)          # iou with higher-scored boxes
        max_iou = tri.max(axis=1)          # per box: worst overlap above it
        if use_gaussian:
            # reference kernel: exp((compensate² - iou²) * sigma) — sigma
            # MULTIPLIES (paddle's gaussian_sigma=2.0 is the paper's 1/σ)
            decay = jnp.exp((max_iou[None, :] ** 2 - tri ** 2)
                            * gaussian_sigma)
        else:
            decay = (1.0 - tri) / jnp.maximum(1.0 - max_iou[None, :], 1e-10)
        decay = jnp.where(jnp.tril(jnp.ones_like(tri), k=-1) > 0, decay,
                          jnp.inf).min(axis=1)
        decay = jnp.where(jnp.isinf(decay), 1.0, decay)
        return order, jnp.where(jnp.isfinite(ss), ss * decay, -jnp.inf)

    # one batched device computation + ONE host sync for all classes
    orders, dscores = jax.vmap(per_class)(sc)        # [C, k] each
    orders = np.asarray(jax.device_get(orders))
    dscores = np.asarray(jax.device_get(dscores))
    bx_np = np.asarray(jax.device_get(bx))
    rows = []
    for c in range(n_cls):
        keep = dscores[c] >= max(float(post_threshold), 1e-38)
        on, dn = orders[c][keep], dscores[c][keep]
        if len(on):
            rows.append(np.column_stack([
                np.full(len(on), c, np.float32), dn.astype(np.float32),
                bx_np[on].astype(np.float32),
                on.astype(np.float32)]))
    if not rows:
        return (Tensor(jnp.zeros((0, 6), jnp.float32)),
                Tensor(jnp.zeros((0,), jnp.int32)))
    cat = np.concatenate(rows)
    cat = cat[np.argsort(-cat[:, 1])][: int(keep_top_k)]
    return (Tensor(jnp.asarray(cat[:, :6], jnp.float32)),
            Tensor(jnp.asarray(cat[:, 6], jnp.int32)))


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False):
    """SSD prior (anchor) boxes (reference ``prior_box``): for each input
    cell, emit anchors of the min/max sizes and aspect ratios, normalized
    by the image size. Returns (boxes [H, W, P, 4], variances same)."""
    import numpy as np

    feat = input._data if isinstance(input, Tensor) else jnp.asarray(input)
    img = image._data if isinstance(image, Tensor) else jnp.asarray(image)
    h, w = feat.shape[-2], feat.shape[-1]
    imh, imw = int(img.shape[-2]), int(img.shape[-1])
    step_h = steps[1] or imh / h
    step_w = steps[0] or imw / w
    ars = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - a) > 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    whs = []
    for mi, ms in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                mx = max_sizes[mi]
                whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if max_sizes:
                mx = max_sizes[mi]
                whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    p = len(whs)
    cx = (np.arange(w) + offset) * step_w
    cy = (np.arange(h) + offset) * step_h
    boxes = np.zeros((h, w, p, 4), np.float32)
    for pi, (bw, bh) in enumerate(whs):
        boxes[:, :, pi, 0] = (cx[None, :] - bw / 2) / imw
        boxes[:, :, pi, 1] = (cy[:, None] - bh / 2) / imh
        boxes[:, :, pi, 2] = (cx[None, :] + bw / 2) / imw
        boxes[:, :, pi, 3] = (cy[:, None] + bh / 2) / imh
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    vars_ = np.broadcast_to(np.asarray(variance, np.float32),
                            boxes.shape).copy()
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(vars_))


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None):
    """Assign RoIs to FPN levels by scale (reference
    ``distribute_fpn_proposals``): level = floor(refer_level +
    log2(sqrt(area)/refer_scale)). Returns (rois per level, restore index
    [N,1], rois_num per level or None)."""
    import numpy as np

    rois = np.asarray(fpn_rois._data if isinstance(fpn_rois, Tensor)
                      else fpn_rois)
    off = 1.0 if pixel_offset else 0.0
    ws = rois[:, 2] - rois[:, 0] + off
    hs = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.maximum(ws * hs, 1e-12))
    lvl = np.floor(refer_level + np.log2(scale / refer_scale + 1e-8))
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    nums_in = None
    if rois_num is not None:
        nums_in = np.asarray(rois_num._data if isinstance(rois_num, Tensor)
                             else rois_num).reshape(-1)
        img_idx = np.repeat(np.arange(len(nums_in)), nums_in)
    multi_rois, out_nums, order = [], [], []
    for l in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == l)[0]
        multi_rois.append(Tensor(jnp.asarray(rois[idx])))
        order.extend(idx.tolist())
        if nums_in is not None:
            # reference contract: PER-IMAGE roi counts at this level
            per_img = np.bincount(img_idx[idx], minlength=len(nums_in))
            out_nums.append(Tensor(jnp.asarray(per_img.astype(np.int32))))
    restore = np.empty((len(order), 1), np.int32)
    restore[np.asarray(order, np.int64), 0] = np.arange(len(order))
    return (multi_rois, Tensor(jnp.asarray(restore)),
            out_nums if nums_in is not None else None)


class RoIAlign:
    """Layer wrapper (reference ``paddle.vision.ops.RoIAlign``)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class PSRoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return ps_roi_pool(x, boxes, boxes_num, self.output_size,
                           self.spatial_scale)


from ..nn.layer import Layer as _Layer          # noqa: E402
from ..nn.initializer import XavierUniform as _XavierUniform  # noqa: E402


class DeformConv2D(_Layer):
    """Owns weight/bias; offset (and mask, v2) come in at forward —
    reference ``paddle.vision.ops.DeformConv2D`` contract."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._attrs = dict(stride=stride, padding=padding,
                           dilation=dilation,
                           deformable_groups=deformable_groups,
                           groups=groups)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, *ks],
            attr=weight_attr or _XavierUniform())
        self.bias = None if bias_attr is False else \
            self.create_parameter([out_channels], attr=bias_attr,
                                  is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             mask=mask, **self._attrs)
