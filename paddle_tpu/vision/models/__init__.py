"""paddle.vision.models (reference: ``python/paddle/vision/models/``)."""
from .resnet import (  # noqa: F401
    ResNet, BasicBlock, BottleneckBlock, resnet18, resnet34, resnet50,
    resnet101, resnet152, wide_resnet50_2, resnext50_32x4d,
    resnext101_32x4d, resnext101_64x4d, resnext152_32x4d,
    wide_resnet101_2,
)
from .lenet import LeNet  # noqa: F401
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
from .mobilenet import MobileNetV1, MobileNetV2, mobilenet_v1, mobilenet_v2  # noqa: F401
from .extras import (  # noqa: F401
    AlexNet, alexnet, SqueezeNet, squeezenet1_0, squeezenet1_1,
    MobileNetV3Small, MobileNetV3Large, mobilenet_v3_small,
    mobilenet_v3_large, ShuffleNetV2, shufflenet_v2_x1_0,
    DenseNet, densenet121,
)
from .inception import (  # noqa: F401
    GoogLeNet, googlenet, InceptionV3, inception_v3,
)
from .vit import (  # noqa: F401
    VisionTransformer, vit_small_patch16_224, vit_base_patch16_224,
    vit_large_patch16_224,
)
