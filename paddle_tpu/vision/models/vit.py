"""Vision Transformer (reference: PaddleClas ``ppcls/arch/backbone/
model_zoo/vision_transformer.py`` — ViT-B/16 family; the zoos are
separate repos per SURVEY.md §2.4, so the in-repo equivalent follows the
paddle.vision.models convention).

TPU-first notes: patch embedding is ONE conv (= a [P²·C, D] matmul on
the MXU after im2col), the encoder is pre-LN blocks whose attention
rides the shared ``F.scaled_dot_product_attention`` path (flash kernel
on TPU), and all sequence lengths are static (196 + 1 cls token for
224²/16) so the whole forward is a single fused XLA program.
"""
from __future__ import annotations

import numpy as np

from ... import nn
from ...nn.initializer import Normal, Constant, TruncatedNormal


class _MLP(nn.Layer):
    def __init__(self, dim, hidden, dropout=0.0):
        super().__init__()
        self.fc1 = nn.Linear(dim, hidden)
        self.fc2 = nn.Linear(hidden, dim)
        self.act = nn.GELU()
        self.drop = nn.Dropout(dropout)

    def forward(self, x):
        return self.drop(self.fc2(self.drop(self.act(self.fc1(x)))))


class _Attention(nn.Layer):
    def __init__(self, dim, num_heads, attn_dropout=0.0, dropout=0.0):
        super().__init__()
        self.num_heads = num_heads
        self.head_dim = dim // num_heads
        self.qkv = nn.Linear(dim, dim * 3)
        self.proj = nn.Linear(dim, dim)
        self.attn_dropout = attn_dropout
        self.drop = nn.Dropout(dropout)

    def forward(self, x):
        from ...nn import functional as F
        b, s, d = x.shape
        qkv = self.qkv(x).reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = (qkv[:, :, i] for i in range(3))   # [b, s, h, hd]
        out = F.scaled_dot_product_attention(
            q, k, v, is_causal=False, dropout_p=self.attn_dropout,
            training=self.training)
        return self.drop(self.proj(out.reshape([b, s, d])))


class _Block(nn.Layer):
    def __init__(self, dim, num_heads, mlp_ratio=4.0, dropout=0.0,
                 attn_dropout=0.0, epsilon=1e-6):
        super().__init__()
        self.norm1 = nn.LayerNorm(dim, epsilon=epsilon)
        self.attn = _Attention(dim, num_heads, attn_dropout, dropout)
        self.norm2 = nn.LayerNorm(dim, epsilon=epsilon)
        self.mlp = _MLP(dim, int(dim * mlp_ratio), dropout)

    def forward(self, x):
        x = x + self.attn(self.norm1(x))
        return x + self.mlp(self.norm2(x))


class VisionTransformer(nn.Layer):
    """ViT backbone + classification head (PaddleClas signature subset)."""

    def __init__(self, img_size=224, patch_size=16, in_channels=3,
                 num_classes=1000, embed_dim=768, depth=12, num_heads=12,
                 mlp_ratio=4.0, dropout=0.0, attn_dropout=0.0,
                 epsilon=1e-6):
        super().__init__()
        self.num_classes = num_classes
        self.embed_dim = embed_dim
        n_patches = (img_size // patch_size) ** 2
        self.patch_embed = nn.Conv2D(in_channels, embed_dim,
                                     kernel_size=patch_size,
                                     stride=patch_size)
        init = TruncatedNormal(std=0.02)
        self.cls_token = self.create_parameter(
            [1, 1, embed_dim], attr=None, dtype="float32",
            default_initializer=Constant(0.0))
        self.pos_embed = self.create_parameter(
            [1, n_patches + 1, embed_dim], attr=None, dtype="float32",
            default_initializer=init)
        self.pos_drop = nn.Dropout(dropout)
        self.blocks = nn.LayerList([
            _Block(embed_dim, num_heads, mlp_ratio, dropout, attn_dropout,
                   epsilon) for _ in range(depth)])
        self.norm = nn.LayerNorm(embed_dim, epsilon=epsilon)
        self.head = nn.Linear(embed_dim, num_classes,
                              weight_attr=Normal(0.0, 0.02)) \
            if num_classes > 0 else None

    def forward_features(self, x):
        from ...ops import manipulation as manip
        b = x.shape[0]
        x = self.patch_embed(x)                       # [b, D, H/P, W/P]
        x = x.flatten(2).transpose([0, 2, 1])         # [b, N, D]
        cls = manip.expand(self.cls_token, [b, 1, self.embed_dim])
        x = manip.concat([cls, x], axis=1) + self.pos_embed
        x = self.pos_drop(x)
        for blk in self.blocks:
            x = blk(x)
        return self.norm(x)

    def forward(self, x):
        feats = self.forward_features(x)
        if self.head is None:
            return feats
        return self.head(feats[:, 0])                 # cls token


def vit_base_patch16_224(**kwargs):
    kwargs.setdefault("embed_dim", 768)
    kwargs.setdefault("depth", 12)
    kwargs.setdefault("num_heads", 12)
    return VisionTransformer(**kwargs)


def vit_large_patch16_224(**kwargs):
    kwargs.setdefault("embed_dim", 1024)
    kwargs.setdefault("depth", 24)
    kwargs.setdefault("num_heads", 16)
    return VisionTransformer(**kwargs)


def vit_small_patch16_224(**kwargs):
    kwargs.setdefault("embed_dim", 384)
    kwargs.setdefault("depth", 12)
    kwargs.setdefault("num_heads", 6)
    return VisionTransformer(**kwargs)
