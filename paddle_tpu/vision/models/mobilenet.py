"""MobileNet V1/V2 (reference: ``python/paddle/vision/models/mobilenetv{1,2}.py``)."""
from ... import nn


class _ConvBNReLU(nn.Sequential):
    def __init__(self, in_c, out_c, kernel=3, stride=1, groups=1, relu6=False):
        pad = (kernel - 1) // 2
        super().__init__(
            nn.Conv2D(in_c, out_c, kernel, stride=stride, padding=pad,
                      groups=groups, bias_attr=False),
            nn.BatchNorm2D(out_c),
            nn.ReLU6() if relu6 else nn.ReLU())


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(int(ch * scale), 8)

        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
            [(512, 1024, 2), (1024, 1024, 1)]
        layers = [_ConvBNReLU(3, c(32), stride=2)]
        for in_c, out_c, s in cfg:
            layers.append(_ConvBNReLU(c(in_c), c(in_c), stride=s, groups=c(in_c)))
            layers.append(_ConvBNReLU(c(in_c), c(out_c), kernel=1))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


class _InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNReLU(inp, hidden, kernel=1, relu6=True))
        layers += [
            _ConvBNReLU(hidden, hidden, stride=stride, groups=hidden, relu6=True),
            nn.Conv2D(hidden, oup, 1, bias_attr=False),
            nn.BatchNorm2D(oup)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = max(int(32 * scale), 8)
        last_c = max(int(1280 * scale), 8)
        layers = [_ConvBNReLU(3, in_c, stride=2, relu6=True)]
        for t, ch, n, s in cfg:
            out_c = max(int(ch * scale), 8)
            for i in range(n):
                layers.append(_InvertedResidual(in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        layers.append(_ConvBNReLU(in_c, last_c, kernel=1, relu6=True))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    model = MobileNetV1(scale=scale, **kwargs)
    if pretrained:
        from ._utils import load_pretrained
        load_pretrained(model, f"mobilenetv1_{scale}")
    return model


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    model = MobileNetV2(scale=scale, **kwargs)
    if pretrained:
        from ._utils import load_pretrained
        load_pretrained(model, f"mobilenetv2_{scale}")
    return model
