"""Vision zoo batch 2 (reference: ``python/paddle/vision/models/`` —
``alexnet.py``, ``squeezenet.py``, ``mobilenetv3.py``,
``shufflenetv2.py``, ``densenet.py``, ``wide_resnet`` variants of
``resnet.py``). Implementations follow the reference topologies; all are
XLA-compiled conv stacks — no per-model kernels needed on TPU."""
from __future__ import annotations

from ... import nn


__all__ = ["AlexNet", "alexnet", "SqueezeNet", "squeezenet1_0",
           "squeezenet1_1", "MobileNetV3Small", "MobileNetV3Large",
           "mobilenet_v3_small", "mobilenet_v3_large", "ShuffleNetV2",
           "shufflenet_v2_x1_0", "DenseNet", "densenet121"]


class AlexNet(nn.Layer):
    def __init__(self, num_classes=1000, dropout=0.5):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2))
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        self.classifier = nn.Sequential(
            nn.Dropout(dropout), nn.Linear(256 * 36, 4096), nn.ReLU(),
            nn.Dropout(dropout), nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        return self.classifier(x.flatten(1))


def alexnet(pretrained=False, **kwargs):
    model = AlexNet(**kwargs)
    if pretrained:
        from ._utils import load_pretrained
        load_pretrained(model, "alexnet")
    return model


class _Fire(nn.Layer):
    def __init__(self, in_c, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Sequential(nn.Conv2D(in_c, squeeze, 1), nn.ReLU())
        self.e1 = nn.Sequential(nn.Conv2D(squeeze, e1, 1), nn.ReLU())
        self.e3 = nn.Sequential(nn.Conv2D(squeeze, e3, 3, padding=1),
                                nn.ReLU())

    def forward(self, x):
        s = self.squeeze(x)
        from ...ops import concat
        return concat([self.e1(s), self.e3(s)], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000):
        super().__init__()
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            nn.AdaptiveAvgPool2D(1))

    def forward(self, x):
        return self.classifier(self.features(x)).flatten(1)


def squeezenet1_0(pretrained=False, **kwargs):
    model = SqueezeNet("1.0", **kwargs)
    if pretrained:
        from ._utils import load_pretrained
        load_pretrained(model, "squeezenet1_0")
    return model


def squeezenet1_1(pretrained=False, **kwargs):
    model = SqueezeNet("1.1", **kwargs)
    if pretrained:
        from ._utils import load_pretrained
        load_pretrained(model, "squeezenet1_1")
    return model


class _SE(nn.Layer):
    def __init__(self, c, r=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(c, c // r, 1)
        self.fc2 = nn.Conv2D(c // r, c, 1)

    def forward(self, x):
        s = self.fc2(nn.functional.relu(self.fc1(self.pool(x))))
        return x * nn.functional.hardsigmoid(s)


class _InvertedResidualV3(nn.Layer):
    def __init__(self, in_c, exp, out_c, k, stride, se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        Act = nn.Hardswish if act == "hs" else nn.ReLU
        if exp != in_c:
            layers += [nn.Conv2D(in_c, exp, 1, bias_attr=False),
                       nn.BatchNorm2D(exp), Act()]
        layers += [nn.Conv2D(exp, exp, k, stride=stride,
                             padding=(k - 1) // 2, groups=exp,
                             bias_attr=False),
                   nn.BatchNorm2D(exp), Act()]
        if se:
            layers.append(_SE(exp))
        layers += [nn.Conv2D(exp, out_c, 1, bias_attr=False),
                   nn.BatchNorm2D(out_c)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_V3_SMALL = [  # k, exp, out, se, act, stride
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hs", 2),
    (5, 240, 40, True, "hs", 1), (5, 240, 40, True, "hs", 1),
    (5, 120, 48, True, "hs", 1), (5, 144, 48, True, "hs", 1),
    (5, 288, 96, True, "hs", 2), (5, 576, 96, True, "hs", 1),
    (5, 576, 96, True, "hs", 1)]
_V3_LARGE = [
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hs", 2), (3, 200, 80, False, "hs", 1),
    (3, 184, 80, False, "hs", 1), (3, 184, 80, False, "hs", 1),
    (3, 480, 112, True, "hs", 1), (3, 672, 112, True, "hs", 1),
    (5, 672, 160, True, "hs", 2), (5, 960, 160, True, "hs", 1),
    (5, 960, 160, True, "hs", 1)]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_exp, num_classes=1000, scale=1.0,
                 with_pool=True, head_width=1280):
        super().__init__()
        self.with_pool = with_pool
        self.num_classes = num_classes

        def c(ch):
            return max(int(ch * scale + 4) // 8 * 8, 8)

        layers = [nn.Conv2D(3, c(16), 3, stride=2, padding=1,
                            bias_attr=False),
                  nn.BatchNorm2D(c(16)), nn.Hardswish()]
        in_c = c(16)
        for k, exp, out, se, act, s in cfg:
            layers.append(_InvertedResidualV3(in_c, c(exp), c(out), k, s,
                                              se, act))
            in_c = c(out)
        layers += [nn.Conv2D(in_c, c(last_exp), 1, bias_attr=False),
                   nn.BatchNorm2D(c(last_exp)), nn.Hardswish()]
        self.features = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(c(last_exp), head_width), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(head_width, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 576, num_classes, scale, with_pool,
                         head_width=1024)   # reference small-variant head


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 960, num_classes, scale, with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    model = MobileNetV3Small(scale=scale, **kwargs)
    if pretrained:
        from ._utils import load_pretrained
        load_pretrained(model, f"mobilenet_v3_small_{scale}")
    return model


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    model = MobileNetV3Large(scale=scale, **kwargs)
    if pretrained:
        from ._utils import load_pretrained
        load_pretrained(model, f"mobilenet_v3_large_{scale}")
    return model


def _channel_shuffle(x, groups):
    from ...nn.functional import channel_shuffle
    return channel_shuffle(x, groups)


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride > 1:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=stride, padding=1,
                          groups=in_c, bias_attr=False),
                nn.BatchNorm2D(in_c),
                nn.Conv2D(in_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), nn.ReLU())
            b2_in = in_c
        else:
            self.branch1 = None
            b2_in = in_c // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(b2_in, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), nn.ReLU(),
            nn.Conv2D(branch_c, branch_c, 3, stride=stride, padding=1,
                      groups=branch_c, bias_attr=False),
            nn.BatchNorm2D(branch_c),
            nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), nn.ReLU())

    def forward(self, x):
        from ...ops import concat, split
        if self.stride == 1:
            x1, x2 = split(x, 2, axis=1)
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    _CH = {0.5: (48, 96, 192, 1024), 1.0: (116, 232, 464, 1024),
           1.5: (176, 352, 704, 1024), 2.0: (244, 488, 976, 2048)}

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True,
                 act="relu"):
        super().__init__()
        c1, c2, c3, c_out = self._CH[scale]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, 24, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(24), nn.ReLU())
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_c = 24
        for out_c, repeat in ((c1, 4), (c2, 8), (c3, 4)):
            units = [_ShuffleUnit(in_c, out_c, 2)]
            units += [_ShuffleUnit(out_c, out_c, 1) for _ in range(repeat - 1)]
            stages.append(nn.Sequential(*units))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_c, c_out, 1, bias_attr=False),
            nn.BatchNorm2D(c_out), nn.ReLU())
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        self.with_pool = with_pool
        if num_classes > 0:
            self.fc = nn.Linear(c_out, num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    model = ShuffleNetV2(scale=1.0, **kwargs)
    if pretrained:
        from ._utils import load_pretrained
        load_pretrained(model, "shufflenet_v2_x1_0")
    return model


class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth, bn_size):
        super().__init__()
        self.block = nn.Sequential(
            nn.BatchNorm2D(in_c), nn.ReLU(),
            nn.Conv2D(in_c, bn_size * growth, 1, bias_attr=False),
            nn.BatchNorm2D(bn_size * growth), nn.ReLU(),
            nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                      bias_attr=False))

    def forward(self, x):
        from ...ops import concat
        return concat([x, self.block(x)], axis=1)


class DenseNet(nn.Layer):
    def __init__(self, layers=121, growth_rate=32, bn_size=4,
                 num_classes=1000, with_pool=True):
        super().__init__()
        cfgs = {121: (6, 12, 24, 16), 161: (6, 12, 36, 24),
                169: (6, 12, 32, 32), 201: (6, 12, 48, 32)}
        block_cfg = cfgs[layers]
        init_c = 2 * growth_rate
        feats = [nn.Conv2D(3, init_c, 7, stride=2, padding=3,
                           bias_attr=False),
                 nn.BatchNorm2D(init_c), nn.ReLU(),
                 nn.MaxPool2D(3, stride=2, padding=1)]
        c = init_c
        for i, n in enumerate(block_cfg):
            for _ in range(n):
                feats.append(_DenseLayer(c, growth_rate, bn_size))
                c += growth_rate
            if i != len(block_cfg) - 1:
                feats += [nn.BatchNorm2D(c), nn.ReLU(),
                          nn.Conv2D(c, c // 2, 1, bias_attr=False),
                          nn.AvgPool2D(2, stride=2)]
                c //= 2
        feats += [nn.BatchNorm2D(c), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        self.with_pool = with_pool
        if num_classes > 0:
            self.classifier = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def densenet121(pretrained=False, **kwargs):
    model = DenseNet(121, **kwargs)
    if pretrained:
        from ._utils import load_pretrained
        load_pretrained(model, "densenet121")
    return model

