"""Pretrained-weight loading shared by the vision zoo (reference:
``python/paddle/utils/download.py`` + per-model ``model_urls`` tables in
``python/paddle/vision/models/*.py``).

Zero-egress build: weights resolve through the local cache
(``~/.cache/paddle_tpu/weights``) via
:func:`paddle_tpu.utils.get_weights_path_from_url`; a cache miss raises
with the exact path to drop the file at. The URL table keeps the
reference's canonical filenames so a user can copy weights straight from
an upstream cache."""
from __future__ import annotations

# canonical upstream URL table (filenames define the cache keys)
model_urls = {
    "resnet18": "https://paddle-hapi.bj.bcebos.com/models/resnet18.pdparams",
    "resnet34": "https://paddle-hapi.bj.bcebos.com/models/resnet34.pdparams",
    "resnet50": "https://paddle-hapi.bj.bcebos.com/models/resnet50.pdparams",
    "resnet101":
        "https://paddle-hapi.bj.bcebos.com/models/resnet101.pdparams",
    "resnet152":
        "https://paddle-hapi.bj.bcebos.com/models/resnet152.pdparams",
    "vgg16": "https://paddle-hapi.bj.bcebos.com/models/vgg16.pdparams",
    "vgg19": "https://paddle-hapi.bj.bcebos.com/models/vgg19.pdparams",
    "mobilenetv1_1.0":
        "https://paddle-hapi.bj.bcebos.com/models/mobilenetv1_1.0.pdparams",
    "mobilenetv2_1.0":
        "https://paddle-hapi.bj.bcebos.com/models/mobilenet_v2_x1.0.pdparams",
    "lenet": "https://paddle-hapi.bj.bcebos.com/models/lenet.pdparams",
    "alexnet": "https://paddle-hapi.bj.bcebos.com/models/alexnet.pdparams",
    "squeezenet1_0":
        "https://paddle-hapi.bj.bcebos.com/models/squeezenet1_0.pdparams",
    "squeezenet1_1":
        "https://paddle-hapi.bj.bcebos.com/models/squeezenet1_1.pdparams",
    "mobilenet_v3_small_1.0":
        "https://paddle-hapi.bj.bcebos.com/models/mobilenet_v3_small_x1.0.pdparams",
    "mobilenet_v3_large_1.0":
        "https://paddle-hapi.bj.bcebos.com/models/mobilenet_v3_large_x1.0.pdparams",
    "shufflenet_v2_x1_0":
        "https://paddle-hapi.bj.bcebos.com/models/shufflenet_v2_x1_0.pdparams",
    "densenet121":
        "https://paddle-hapi.bj.bcebos.com/models/densenet121.pdparams",
    "googlenet":
        "https://paddle-hapi.bj.bcebos.com/models/googlenet.pdparams",
    "inception_v3":
        "https://paddle-hapi.bj.bcebos.com/models/inception_v3.pdparams",
}


def load_pretrained(model, arch):
    """Load cached pretrained weights into ``model`` (strict key match)."""
    from ...utils import get_weights_path_from_url
    import paddle_tpu as paddle
    url = model_urls.get(arch)
    if url is None:
        raise ValueError(f"no pretrained weights registered for '{arch}'")
    path = get_weights_path_from_url(url)
    state = paddle.load(path)
    missing, unexpected = model.set_state_dict(state)
    if missing or unexpected:
        raise RuntimeError(
            f"pretrained state_dict mismatch for {arch}: "
            f"missing={list(missing)[:5]} unexpected={list(unexpected)[:5]}")
    return model
