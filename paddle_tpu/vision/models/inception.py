"""GoogLeNet + InceptionV3 (reference:
``python/paddle/vision/models/googlenet.py``, ``inceptionv3.py``)."""
from __future__ import annotations

from ... import nn
from ...ops import concat


class _BNConv(nn.Sequential):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0):
        super().__init__(
            nn.Conv2D(in_c, out_c, kernel, stride=stride, padding=padding,
                      bias_attr=False),
            nn.BatchNorm2D(out_c), nn.ReLU())


class _Inception(nn.Layer):
    """GoogLeNet inception block (1x1 / 3x3 / 5x5 / pool branches)."""

    def __init__(self, in_c, c1, c3r, c3, c5r, c5, pool_proj):
        super().__init__()
        self.b1 = _BNConv(in_c, c1, 1)
        self.b2 = nn.Sequential(_BNConv(in_c, c3r, 1),
                                _BNConv(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_BNConv(in_c, c5r, 1),
                                _BNConv(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                _BNConv(in_c, pool_proj, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                      axis=1)


class GoogLeNet(nn.Layer):
    """Returns (logits, out1, out2) in train mode — out1 is the
    shallow (after-4a) head, out2 the deeper (after-4d) head,
    matching the reference's tuple order."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _BNConv(3, 64, 7, stride=2, padding=3),
            nn.MaxPool2D(3, stride=2, ceil_mode=True),
            _BNConv(64, 64, 1), _BNConv(64, 192, 3, padding=1),
            nn.MaxPool2D(3, stride=2, ceil_mode=True))
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, ceil_mode=True)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, ceil_mode=True)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool5 = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.2)
            self.fc = nn.Linear(1024, num_classes)
            # aux heads (train-mode deep supervision)
            self.aux1 = nn.Sequential(
                nn.AdaptiveAvgPool2D(4), _BNConv(512, 128, 1), nn.Flatten(),
                nn.Linear(128 * 16, 1024), nn.ReLU(), nn.Dropout(0.7),
                nn.Linear(1024, num_classes))
            self.aux2 = nn.Sequential(
                nn.AdaptiveAvgPool2D(4), _BNConv(528, 128, 1), nn.Flatten(),
                nn.Linear(128 * 16, 1024), nn.ReLU(), nn.Dropout(0.7),
                nn.Linear(1024, num_classes))

    def forward(self, x):
        x = self.pool3(self.i3b(self.i3a(self.stem(x))))
        x = self.i4a(x)
        a1 = self.aux1(x) if self.training and self.num_classes > 0 else None
        x = self.i4d(self.i4c(self.i4b(x)))
        a2 = self.aux2(x) if self.training and self.num_classes > 0 else None
        x = self.pool4(self.i4e(x))
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(x.flatten(1)))
        if self.training and self.num_classes > 0:
            # reference order: (logits, out1 = after-4a head, out2 =
            # after-4d head)
            return x, a1, a2
        return x


def googlenet(pretrained=False, **kwargs):
    model = GoogLeNet(**kwargs)
    if pretrained:
        from ._utils import load_pretrained
        load_pretrained(model, "googlenet")
    return model


class _InceptionA(nn.Layer):
    def __init__(self, in_c, pool_feat):
        super().__init__()
        self.b1 = _BNConv(in_c, 64, 1)
        self.b5 = nn.Sequential(_BNConv(in_c, 48, 1),
                                _BNConv(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_BNConv(in_c, 64, 1),
                                _BNConv(64, 96, 3, padding=1),
                                _BNConv(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _BNConv(in_c, pool_feat, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)], 1)


class _InceptionB(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3 = _BNConv(in_c, 384, 3, stride=2)
        self.b3d = nn.Sequential(_BNConv(in_c, 64, 1),
                                 _BNConv(64, 96, 3, padding=1),
                                 _BNConv(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b3d(x), self.pool(x)], 1)


class _InceptionC(nn.Layer):
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _BNConv(in_c, 192, 1)
        self.b7 = nn.Sequential(
            _BNConv(in_c, c7, 1), _BNConv(c7, c7, (1, 7), padding=(0, 3)),
            _BNConv(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(
            _BNConv(in_c, c7, 1), _BNConv(c7, c7, (7, 1), padding=(3, 0)),
            _BNConv(c7, c7, (1, 7), padding=(0, 3)),
            _BNConv(c7, c7, (7, 1), padding=(3, 0)),
            _BNConv(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _BNConv(in_c, 192, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)], 1)


class _InceptionD(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3 = nn.Sequential(_BNConv(in_c, 192, 1),
                                _BNConv(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _BNConv(in_c, 192, 1),
            _BNConv(192, 192, (1, 7), padding=(0, 3)),
            _BNConv(192, 192, (7, 1), padding=(3, 0)),
            _BNConv(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b7(x), self.pool(x)], 1)


class _InceptionE(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _BNConv(in_c, 320, 1)
        self.b3_stem = _BNConv(in_c, 384, 1)
        self.b3_a = _BNConv(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _BNConv(384, 384, (3, 1), padding=(1, 0))
        self.bd_stem = nn.Sequential(_BNConv(in_c, 448, 1),
                                     _BNConv(448, 384, 3, padding=1))
        self.bd_a = _BNConv(384, 384, (1, 3), padding=(0, 1))
        self.bd_b = _BNConv(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _BNConv(in_c, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        d = self.bd_stem(x)
        return concat([self.b1(x),
                       concat([self.b3_a(s), self.b3_b(s)], 1),
                       concat([self.bd_a(d), self.bd_b(d)], 1),
                       self.bp(x)], 1)


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _BNConv(3, 32, 3, stride=2), _BNConv(32, 32, 3),
            _BNConv(32, 64, 3, padding=1), nn.MaxPool2D(3, stride=2),
            _BNConv(64, 80, 1), _BNConv(80, 192, 3),
            nn.MaxPool2D(3, stride=2))
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64), _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160),
            _InceptionC(768, 160), _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(x.flatten(1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    model = InceptionV3(**kwargs)
    if pretrained:
        from ._utils import load_pretrained
        load_pretrained(model, "inception_v3")
    return model
