"""paddle.vision.transforms (reference: ``python/paddle/vision/transforms/`` —
numpy/HWC-based preprocessing; SURVEY.md §2.2)."""
from __future__ import annotations

import numbers

import numpy as np

from ...framework.core import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


def _to_hwc_array(img):
    if isinstance(img, Tensor):
        return img.numpy()
    return np.asarray(img)


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        arr = arr.astype(np.float32)
        if arr.max() > 1.0 + 1e-6 or arr.dtype == np.uint8:
            arr = arr / 255.0
        if self.data_format == "CHW":
            arr = np.transpose(arr, (2, 0, 1))
        return Tensor(arr)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        super().__init__(keys)
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        is_tensor = isinstance(img, Tensor)
        arr = img.numpy() if is_tensor else np.asarray(img, np.float32)
        shape = [-1, 1, 1] if self.data_format == "CHW" else [1, 1, -1]
        arr = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return Tensor(arr.astype(np.float32)) if is_tensor else arr.astype(np.float32)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        import jax
        import jax.numpy as jnp
        squeeze = arr.ndim == 2
        if squeeze:
            arr = arr[:, :, None]
        method = {"bilinear": "linear", "nearest": "nearest",
                  "bicubic": "cubic"}.get(self.interpolation, "linear")
        out = jax.image.resize(jnp.asarray(arr, jnp.float32),
                               (self.size[0], self.size[1], arr.shape[2]), method)
        out = np.asarray(out)
        if arr.dtype == np.uint8:
            out = np.clip(out, 0, 255).astype(np.uint8)
        return out[:, :, 0] if squeeze else out


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) \
                else (self.padding,) * 4
            if len(p) == 2:
                p = (p[0], p[1], p[0], p[1])
            pads = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pads)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return arr[i:i + th, j:j + tw]


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(_to_hwc_array(img)[:, ::-1])
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(_to_hwc_array(img)[::-1])
        return img


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self._resize = Resize(self.size, interpolation)

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                return self._resize(arr[i:i + th, j:j + tw])
        return self._resize(CenterCrop(min(h, w))(arr))


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return np.transpose(arr, self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        arr = _to_hwc_array(img).astype(np.float32)
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return np.clip(arr * f, 0, 255).astype(np.uint8)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        super().__init__(keys)
        self.brightness = brightness
        self.contrast = contrast

    def _apply_image(self, img):
        arr = _to_hwc_array(img).astype(np.float32)
        if self.brightness:
            arr = arr * np.random.uniform(max(0, 1 - self.brightness),
                                          1 + self.brightness)
        if self.contrast:
            mean = arr.mean()
            arr = (arr - mean) * np.random.uniform(max(0, 1 - self.contrast),
                                                   1 + self.contrast) + mean
        return np.clip(arr, 0, 255).astype(np.uint8)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        p = padding if isinstance(padding, (list, tuple)) else (padding,) * 4
        if len(p) == 2:
            p = (p[0], p[1], p[0], p[1])
        self.p = p
        self.fill = fill

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        pads = [(self.p[1], self.p[3]), (self.p[0], self.p[2])] + \
            [(0, 0)] * (arr.ndim - 2)
        return np.pad(arr, pads, constant_values=self.fill)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return np.ascontiguousarray(_to_hwc_array(img)[:, ::-1])


def vflip(img):
    return np.ascontiguousarray(_to_hwc_array(img)[::-1])


def crop(img, top, left, height, width):
    return _to_hwc_array(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def pad(img, padding, fill=0, padding_mode="constant"):
    return Pad(padding, fill, padding_mode)(img)
