"""paddle.vision.transforms (reference: ``python/paddle/vision/transforms/`` —
numpy/HWC-based preprocessing; SURVEY.md §2.2)."""
from __future__ import annotations

import numbers

import numpy as np

from ...framework.core import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


def _inverse_warp(arr, ys, xs, interpolation="nearest", fill=0,
                  out_shape=None):
    """Sample ``arr`` (HWC or HW numpy) at source coordinates (ys, xs) —
    the shared inverse-map warp behind RandomRotation / RandomAffine /
    RandomPerspective. Out-of-bounds pixels get ``fill``."""
    h, w = arr.shape[:2]
    shape = ((out_shape or ys.shape) + arr.shape[2:])

    def gather(yi, xi):
        inb = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        src = arr.astype(np.float32)[np.clip(yi, 0, h - 1),
                                     np.clip(xi, 0, w - 1)]
        m = inb[..., None] if arr.ndim == 3 else inb
        return np.where(m, src, float(fill))

    if interpolation == "nearest":
        out = gather(np.round(ys).astype(np.int64),
                     np.round(xs).astype(np.int64))
    else:
        y0 = np.floor(ys).astype(np.int64)
        x0 = np.floor(xs).astype(np.int64)
        wy = (ys - y0)[..., None] if arr.ndim == 3 else ys - y0
        wx = (xs - x0)[..., None] if arr.ndim == 3 else xs - x0
        out = (gather(y0, x0) * (1 - wy) * (1 - wx)
               + gather(y0, x0 + 1) * (1 - wy) * wx
               + gather(y0 + 1, x0) * wy * (1 - wx)
               + gather(y0 + 1, x0 + 1) * wy * wx)
    out = out.reshape(shape)
    if arr.dtype == np.uint8:
        out = np.clip(out, 0, 255).astype(np.uint8)
    return out


def _to_hwc_array(img):
    if isinstance(img, Tensor):
        return img.numpy()
    return np.asarray(img)


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        arr = arr.astype(np.float32)
        if arr.max() > 1.0 + 1e-6 or arr.dtype == np.uint8:
            arr = arr / 255.0
        if self.data_format == "CHW":
            arr = np.transpose(arr, (2, 0, 1))
        return Tensor(arr)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        super().__init__(keys)
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        is_tensor = isinstance(img, Tensor)
        arr = img.numpy() if is_tensor else np.asarray(img, np.float32)
        shape = [-1, 1, 1] if self.data_format == "CHW" else [1, 1, -1]
        arr = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return Tensor(arr.astype(np.float32)) if is_tensor else arr.astype(np.float32)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        import jax
        import jax.numpy as jnp
        squeeze = arr.ndim == 2
        if squeeze:
            arr = arr[:, :, None]
        method = {"bilinear": "linear", "nearest": "nearest",
                  "bicubic": "cubic"}.get(self.interpolation, "linear")
        out = jax.image.resize(jnp.asarray(arr, jnp.float32),
                               (self.size[0], self.size[1], arr.shape[2]), method)
        out = np.asarray(out)
        if arr.dtype == np.uint8:
            out = np.clip(out, 0, 255).astype(np.uint8)
        return out[:, :, 0] if squeeze else out


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) \
                else (self.padding,) * 4
            if len(p) == 2:
                p = (p[0], p[1], p[0], p[1])
            pads = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pads)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return arr[i:i + th, j:j + tw]


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(_to_hwc_array(img)[:, ::-1])
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(_to_hwc_array(img)[::-1])
        return img


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self._resize = Resize(self.size, interpolation)

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                return self._resize(arr[i:i + th, j:j + tw])
        return self._resize(CenterCrop(min(h, w))(arr))


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return np.transpose(arr, self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        arr = _to_hwc_array(img).astype(np.float32)
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return np.clip(arr * f, 0, 255).astype(np.uint8)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        super().__init__(keys)
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        self.hue = hue

    def _apply_image(self, img):
        arr = _to_hwc_array(img).astype(np.float32)
        if self.brightness:
            arr = arr * np.random.uniform(max(0, 1 - self.brightness),
                                          1 + self.brightness)
        if self.contrast:
            mean = arr.mean()
            arr = (arr - mean) * np.random.uniform(max(0, 1 - self.contrast),
                                                   1 + self.contrast) + mean
        if (self.saturation or self.hue) and arr.ndim == 3 \
                and arr.shape[-1] == 3:
            hsv = _rgb_to_hsv(np.clip(arr, 0, 255) / 255.0)
            if self.saturation:
                f = np.random.uniform(max(0, 1 - self.saturation),
                                      1 + self.saturation)
                hsv[..., 1] = np.clip(hsv[..., 1] * f, 0, 1)
            if self.hue:
                hsv[..., 0] = (hsv[..., 0]
                               + np.random.uniform(-self.hue, self.hue)) % 1.0
            arr = _hsv_to_rgb(hsv) * 255.0
        return np.clip(arr, 0, 255).astype(np.uint8)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        p = padding if isinstance(padding, (list, tuple)) else (padding,) * 4
        if len(p) == 2:
            p = (p[0], p[1], p[0], p[1])
        self.p = p
        self.fill = fill

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        pads = [(self.p[1], self.p[3]), (self.p[0], self.p[2])] + \
            [(0, 0)] * (arr.ndim - 2)
        return np.pad(arr, pads, constant_values=self.fill)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return np.ascontiguousarray(_to_hwc_array(img)[:, ::-1])


def vflip(img):
    return np.ascontiguousarray(_to_hwc_array(img)[::-1])


def crop(img, top, left, height, width):
    return _to_hwc_array(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def pad(img, padding, fill=0, padding_mode="constant"):
    return Pad(padding, fill, padding_mode)(img)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        arr = _to_hwc_array(img).astype(np.float32)
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        mean = arr.mean()
        return np.clip((arr - mean) * f + mean, 0, 255).astype(np.uint8)


def _rgb_to_hsv(arr):
    """arr float [H, W, 3] in [0, 1] -> hsv same shape."""
    r, g, b = arr[..., 0], arr[..., 1], arr[..., 2]
    mx = arr.max(-1)
    mn = arr.min(-1)
    diff = mx - mn + 1e-12
    h = np.zeros_like(mx)
    h = np.where(mx == r, (g - b) / diff % 6.0, h)
    h = np.where(mx == g, (b - r) / diff + 2.0, h)
    h = np.where(mx == b, (r - g) / diff + 4.0, h)
    h = h / 6.0
    s = np.where(mx > 0, diff / (mx + 1e-12), 0.0)
    return np.stack([h, s, mx], -1)


def _hsv_to_rgb(hsv):
    h, s, v = hsv[..., 0] * 6.0, hsv[..., 1], hsv[..., 2]
    i = np.floor(h).astype(np.int32) % 6
    f = h - np.floor(h)
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    out = np.select(
        [(i == 0)[..., None], (i == 1)[..., None], (i == 2)[..., None],
         (i == 3)[..., None], (i == 4)[..., None], (i == 5)[..., None]],
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, t], -1), np.stack([p, q, v], -1),
         np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    return out


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        if arr.ndim != 3 or arr.shape[-1] != 3:
            return arr            # grayscale has no saturation
        arr = arr.astype(np.float32) / 255.0
        hsv = _rgb_to_hsv(arr)
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        hsv[..., 1] = np.clip(hsv[..., 1] * f, 0, 1)
        return np.clip(_hsv_to_rgb(hsv) * 255.0, 0, 255).astype(np.uint8)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value          # in [0, 0.5]

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        if arr.ndim != 3 or arr.shape[-1] != 3:
            return arr            # grayscale has no hue
        arr = arr.astype(np.float32) / 255.0
        hsv = _rgb_to_hsv(arr)
        shift = np.random.uniform(-self.value, self.value)
        hsv[..., 0] = (hsv[..., 0] + shift) % 1.0
        return np.clip(_hsv_to_rgb(hsv) * 255.0, 0, 255).astype(np.uint8)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.n = num_output_channels

    def _apply_image(self, img):
        arr = _to_hwc_array(img).astype(np.float32)
        if arr.ndim == 2:
            g = arr               # already single-channel
        elif arr.shape[-1] == 1:
            g = arr[..., 0]
        else:
            g = (0.299 * arr[..., 0] + 0.587 * arr[..., 1]
                 + 0.114 * arr[..., 2])
        out = np.repeat(g[..., None], self.n, axis=-1)
        return np.clip(out, 0, 255).astype(np.uint8)


class RandomRotation(BaseTransform):
    """Rotation by a uniform angle in ``degrees`` — supports nearest and
    bilinear interpolation, custom ``center``, and ``expand`` (canvas
    grows to fit the rotated image); no scipy dependency."""

    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, (int, float)):
            degrees = (-float(degrees), float(degrees))
        if interpolation not in ("nearest", "bilinear"):
            raise NotImplementedError(
                f"RandomRotation: interpolation {interpolation!r} "
                "unsupported (nearest/bilinear)")
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        h, w = arr.shape[:2]
        ang = np.deg2rad(np.random.uniform(*self.degrees))
        if self.center is not None:
            cx, cy = float(self.center[0]), float(self.center[1])
        else:
            cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
        if self.expand:
            # output canvas bounding the rotated input rectangle
            oh = int(np.ceil(abs(h * np.cos(ang)) + abs(w * np.sin(ang))))
            ow = int(np.ceil(abs(h * np.sin(ang)) + abs(w * np.cos(ang))))
            ocy, ocx = (oh - 1) / 2.0, (ow - 1) / 2.0
        else:
            oh, ow, ocy, ocx = h, w, cy, cx
        yy, xx = np.meshgrid(np.arange(oh), np.arange(ow), indexing="ij")
        # inverse map: output pixel -> source coordinate
        ys = cy + (yy - ocy) * np.cos(ang) - (xx - ocx) * np.sin(ang)
        xs = cx + (yy - ocy) * np.sin(ang) + (xx - ocx) * np.cos(ang)
        return _inverse_warp(arr, ys, xs, self.interpolation, self.fill,
                             out_shape=(oh, ow))


class RandomErasing(BaseTransform):
    """Randomly erase a rectangle (reference:
    ``paddle.vision.transforms.RandomErasing``). Operates on tensors or
    HWC arrays; ``value`` may be a float, per-channel sequence, or
    'random'."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        if np.random.uniform() >= self.prob:
            return img
        arr = _to_hwc_array(img)
        if not (self.inplace and isinstance(img, np.ndarray)):
            arr = arr.copy()
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = np.random.uniform(*self.scale) * area
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w and eh > 0 and ew > 0:
                y = np.random.randint(0, h - eh + 1)
                x = np.random.randint(0, w - ew + 1)
                c = arr.shape[2] if arr.ndim == 3 else 1
                if isinstance(self.value, str) and self.value == "random":
                    patch = np.random.standard_normal((eh, ew, c))
                else:
                    patch = np.broadcast_to(
                        np.asarray(self.value, np.float32), (eh, ew, c))
                patch = patch.reshape((eh, ew, c) if arr.ndim == 3
                                      else (eh, ew))
                if arr.dtype == np.uint8:
                    patch = np.clip(patch, 0, 255).astype(np.uint8)
                arr[y:y + eh, x:x + ew] = patch
                break
        return arr


class GaussianBlur(BaseTransform):
    """Separable Gaussian blur (reference:
    ``paddle.vision.transforms.GaussianBlur``); sigma drawn uniformly
    from the given range per call."""

    def __init__(self, kernel_size=3, sigma=(0.1, 2.0), keys=None):
        super().__init__(keys)
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        if isinstance(sigma, (int, float)):
            sigma = (float(sigma), float(sigma))
        self.kernel_size = kernel_size
        self.sigma = sigma

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        dtype = arr.dtype
        out = arr.astype(np.float32)
        sig = np.random.uniform(*self.sigma)

        def kernel(k):
            r = np.arange(k) - (k - 1) / 2.0
            g = np.exp(-(r ** 2) / (2 * sig * sig))
            return g / g.sum()

        kx, ky = kernel(self.kernel_size[0]), kernel(self.kernel_size[1])
        # reflect-pad + correlate along each axis
        py, px = len(ky) // 2, len(kx) // 2
        if out.ndim == 2:
            out = out[..., None]
        pad = np.pad(out, ((py, py), (0, 0), (0, 0)), mode="reflect")
        out = sum(pad[i:i + out.shape[0]] * ky[i]
                  for i in range(len(ky)))
        pad = np.pad(out, ((0, 0), (px, px), (0, 0)), mode="reflect")
        out = sum(pad[:, i:i + out.shape[1]] * kx[i]
                  for i in range(len(kx)))
        out = out.reshape(arr.shape)
        if dtype == np.uint8:
            out = np.clip(np.round(out), 0, 255).astype(np.uint8)
        return out


class RandomAffine(BaseTransform):
    """Random affine (rotation, translation, scale, shear) via the shared
    inverse-map warp (reference: ``paddle.vision.transforms.RandomAffine``)."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        if isinstance(degrees, (int, float)):
            degrees = (-float(degrees), float(degrees))
        self.degrees = degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        h, w = arr.shape[:2]
        ang = np.deg2rad(np.random.uniform(*self.degrees))
        tx = ty = 0.0
        if self.translate is not None:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        sc = np.random.uniform(*self.scale) if self.scale else 1.0
        shx = shy = 0.0
        if self.shear is not None:
            sh = self.shear
            if isinstance(sh, (int, float)):
                sh = (-float(sh), float(sh))
            shx = np.deg2rad(np.random.uniform(sh[0], sh[1]))
            if len(sh) == 4:
                shy = np.deg2rad(np.random.uniform(sh[2], sh[3]))
        if self.center is not None:
            cx, cy = float(self.center[0]), float(self.center[1])
        else:
            cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
        # forward matrix M = T(center+t) @ R(ang) @ Shear @ S(sc) @ T(-center)
        cos, sin = np.cos(ang), np.sin(ang)
        rs = np.array([[cos, -sin], [sin, cos]]) @ \
            np.array([[1.0, np.tan(shx)], [np.tan(shy), 1.0]]) * sc
        inv = np.linalg.inv(rs)
        yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
        dx = xx - cx - tx
        dy = yy - cy - ty
        xs = cx + inv[0, 0] * dx + inv[0, 1] * dy
        ys = cy + inv[1, 0] * dx + inv[1, 1] * dy
        return _inverse_warp(arr, ys, xs, self.interpolation, self.fill)


class RandomPerspective(BaseTransform):
    """Random four-point perspective warp (reference:
    ``paddle.vision.transforms.RandomPerspective``)."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        if np.random.uniform() >= self.prob:
            return img
        arr = _to_hwc_array(img)
        h, w = arr.shape[:2]
        d = self.distortion_scale
        dx, dy = w * d / 2, h * d / 2
        src = np.array([[0, 0], [w - 1, 0], [w - 1, h - 1], [0, h - 1]],
                       np.float64)
        # inward-only corner jitter (reference semantics): the warped
        # quad stays convex, so the homography is always well-posed
        ox = np.random.uniform(0, max(dx, 1e-9), 4)
        oy = np.random.uniform(0, max(dy, 1e-9), 4)
        if d == 0:
            ox = oy = np.zeros(4)
        inward = np.array([[1, 1], [-1, 1], [-1, -1], [1, -1]], np.float64)
        dst = src + inward * np.stack([ox, oy], axis=1)
        # homography mapping dst -> src (inverse map for output sampling)
        A, b = [], []
        for (xd, yd), (xs_, ys_) in zip(dst, src):
            A.append([xd, yd, 1, 0, 0, 0, -xs_ * xd, -xs_ * yd])
            A.append([0, 0, 0, xd, yd, 1, -ys_ * xd, -ys_ * yd])
            b.extend([xs_, ys_])
        hcoef = np.linalg.solve(np.asarray(A, np.float64),
                                np.asarray(b, np.float64))
        H = np.append(hcoef, 1.0).reshape(3, 3)
        yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
        den = H[2, 0] * xx + H[2, 1] * yy + H[2, 2]
        xs = (H[0, 0] * xx + H[0, 1] * yy + H[0, 2]) / den
        ys = (H[1, 0] * xx + H[1, 1] * yy + H[1, 2]) / den
        return _inverse_warp(arr, ys, xs, self.interpolation, self.fill)
