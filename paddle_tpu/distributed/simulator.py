"""Thread-rank simulator: per-rank SPMD semantics in one process.

The reference tests distributed code by spawning N processes on one host with
TCP rendezvous (SURVEY.md §4, "multi-node is simulated by multi-process on one
host"). On TPU the perf path is single-controller SPMD (mesh + shardings), so
per-rank *processes* are unnecessary — but the imperative collective API
(``dist.all_reduce`` on same-shape per-rank tensors) still needs per-rank
execution contexts for API/test parity. This module provides them as threads:
``spawn(fn, nprocs=N)`` runs ``fn`` in N threads, each with a thread-local
rank; collectives rendezvous through an in-memory exchange (the TCPStore
analogue, reference ``paddle/fluid/distributed/store/tcp_store.cc``).

Real multi-host jobs don't use this: ``launch`` starts one process per host
and collectives run over the global mesh (see collective.py multihost path).
"""
from __future__ import annotations

import threading
from typing import Any, Callable

_tls = threading.local()

# fault-injection hook (distributed/fault.py installs it when a FaultPlan
# is active): fn(rank, tag) called at every rendezvous exchange entry.
# Plain-list indirection keeps the no-plan path a single None check and
# avoids a module import cycle (fault.py imports simulator).
_FAULT_HOOK: list = [None]


class RankFailure(RuntimeError):
    """A peer rank died while this rank was blocked on a collective.

    The structured replacement for a bare hang/timeout: names the dead
    rank, the collective tag/seq it never entered, and the op kind — the
    signal the elastic train loop keys its shrink protocol on."""

    def __init__(self, rank, seq=None, op=None, message=None):
        self.rank = rank
        self.seq = seq
        self.op = op
        super().__init__(
            message or f"rank {rank} failed (never entered collective "
                       f"seq {seq!r}, op {op!r})")


class SimulatedRankKill(BaseException):
    """Raised inside a simulated rank's thread(s) when a FaultPlan kills
    it. BaseException on purpose: library code catching ``Exception``
    must not swallow a kill — only the elastic loop (or the simulator's
    worker harness) handles it, mirroring a real SIGKILL's
    uncatchability."""

    def __init__(self, rank, where):
        self.rank = rank
        self.where = where
        super().__init__(f"simulated kill of rank {rank} at {where}")


class _Rendezvous:
    """Blocking all-to-all meeting point, one slot list per (tag, round).

    Each tag gets its OWN condition variable (all sharing one lock): with
    comm/compute overlap, dozens of async bucket collectives wait
    concurrently, and a single shared condition turns every deposit into
    an O(waiters) thundering herd — per-tag conditions wake only that
    collective's participants."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._lock = threading.Lock()
        self._conds: dict[Any, threading.Condition] = {}
        self._slots: dict[Any, dict[int, Any]] = {}
        self._done: dict[Any, int] = {}
        self.failed = False  # set when any rank dies; unblocks waiters
        self.dead: set = set()  # ranks killed by fault injection (elastic)

    def _cond_for(self, tag):
        # caller holds self._lock
        c = self._conds.get(tag)
        if c is None:
            c = self._conds[tag] = threading.Condition(self._lock)
        return c

    def abort(self):
        """Mark the world failed and unblock every waiter."""
        with self._lock:
            self.failed = True
            for c in self._conds.values():
                c.notify_all()

    def mark_dead(self, rank: int):
        """Record an elastic rank death and wake every waiter so blocked
        survivors surface a structured :class:`RankFailure` instead of a
        hang (unlike :meth:`abort`, the world stays usable — groups that
        exclude the dead rank keep exchanging)."""
        with self._lock:
            self.dead.add(rank)
            for c in self._conds.values():
                c.notify_all()

    def revive(self, rank: int):
        """Re-admit a previously dead rank (elastic regrow)."""
        with self._lock:
            self.dead.discard(rank)

    def purge(self):
        """Drop all parked exchange state (slots/conds of collectives the
        dead rank never completed). Only safe at an elastic rebuild
        barrier, when every surviving rank is out of the collective path
        (the KV-store membership barrier guarantees exactly that)."""
        with self._lock:
            for c in self._conds.values():
                c.notify_all()
            self._slots.clear()
            self._done.clear()
            self._conds.clear()

    def _dead_participant(self, participants):
        for r in participants:
            if r in self.dead:
                return r
        return None

    def exchange(self, tag, rank: int, value, participants: tuple[int, ...]):
        """Deposit ``value`` for ``rank``; block until every participant has
        deposited; return {rank: value} for the full group."""
        hook = _FAULT_HOOK[0]
        if hook is not None:
            hook(rank, tag)      # may kill/delay this rank (fault.py)
        n = len(participants)
        with self._lock:
            dead = self._dead_participant(participants)
            if dead is not None:
                raise RankFailure(dead, seq=tag[-1] if isinstance(tag, tuple)
                                  else tag,
                                  op=tag[0] if isinstance(tag, tuple) else None)
            cond = self._cond_for(tag)
            slot = self._slots.setdefault(tag, {})
            slot[rank] = value
            if len(slot) == n:
                cond.notify_all()
            else:
                cond.wait_for(
                    lambda: self.failed
                    or self._dead_participant(participants) is not None
                    or len(self._slots.get(tag, {})) == n,
                    timeout=60)
                if self.failed:
                    raise RuntimeError(
                        f"collective '{tag}' aborted: a peer rank failed")
                dead = self._dead_participant(participants)
                if dead is not None and len(
                        self._slots.get(tag, {})) != n:
                    raise RankFailure(
                        dead, seq=tag[-1] if isinstance(tag, tuple) else tag,
                        op=tag[0] if isinstance(tag, tuple) else None)
                if len(self._slots.get(tag, {})) != n:
                    raise TimeoutError(
                        f"collective '{tag}' timed out: "
                        f"{sorted(self._slots.get(tag, {}))} of {participants}")
            result = dict(self._slots[tag])
            # last reader cleans the slot (and its condition)
            self._done[tag] = self._done.get(tag, 0) + 1
            if self._done[tag] == n:
                del self._slots[tag]
                del self._done[tag]
                self._conds.pop(tag, None)
            return result

    def put(self, tag, value):
        key = ("p2p", tag)
        with self._lock:
            self._slots.setdefault(key, {})[0] = value
            self._cond_for(key).notify_all()

    def get(self, tag):
        key = ("p2p", tag)
        with self._lock:
            cond = self._cond_for(key)
            cond.wait_for(lambda: key in self._slots, timeout=120)
            if key not in self._slots:
                raise TimeoutError(f"recv '{tag}' timed out")
            v = self._slots.pop(key)[0]
            self._conds.pop(key, None)
            return v


class SimWorld:
    """One simulated job: world size, rendezvous, per-group op counters."""

    def __init__(self, nprocs: int):
        self.nprocs = nprocs
        self.rendezvous = _Rendezvous(nprocs)
        self._counter_lock = threading.Lock()

    # -- elastic membership (fault injection / shrink / regrow) -------------
    @property
    def dead_ranks(self) -> set:
        return set(self.rendezvous.dead)

    def mark_dead(self, rank: int):
        self.rendezvous.mark_dead(rank)

    def revive(self, rank: int):
        self.rendezvous.revive(rank)

    def next_tag(self, kind: str, group_key):
        # per-thread per-group sequence number keeps concurrent collectives
        # on the same group correctly paired across ranks
        seqs = getattr(_tls, "seqs", None)
        if seqs is None:
            seqs = _tls.seqs = {}
        k = (kind, group_key)
        seqs[k] = seqs.get(k, 0) + 1
        return (kind, group_key, seqs[k])


_active_world: SimWorld | None = None


def active_world() -> SimWorld | None:
    return _active_world if getattr(_tls, "rank", None) is not None else None


def current_rank() -> int | None:
    return getattr(_tls, "rank", None)


def in_simulation() -> bool:
    return current_rank() is not None


def adopt_rank(rank: int, seqs: dict | None = None):
    """Adopt a simulated rank identity on the CURRENT thread.

    Used by the comm-overlap dispatch threads (distributed/comm/bucketer.py):
    an async bucket collective runs on a worker thread spawned by a rank's
    backward, and must rendezvous AS that rank. ``seqs`` seeds the thread's
    collective-sequence counters — overlap dispatch passes a namespaced
    dict whose counters start from a negative per-(scheduler, bucket,
    round) base so worker tags can never collide with the owning thread's
    (positive, monotonic) sequence numbers on the same group."""
    _tls.rank = rank
    _tls.seqs = seqs if seqs is not None else {}


def reset_seqs():
    """Reset THIS thread's per-group collective sequence counters.

    Elastic rebuild primitive: after a shrink/regrow barrier every
    surviving rank resets its counters together (the rebuilt world may
    reuse a previous generation's group rank-set, and ranks that lived
    through different failure paths hold divergent counters — aligned
    restart keeps tags pairing deterministically)."""
    _tls.seqs = {}


def run(fn: Callable, nprocs: int, args=(), propagate=True):
    """Run ``fn(*args)`` on ``nprocs`` simulated ranks; returns list of per-rank
    return values. Exceptions in any rank re-raise in the caller."""
    global _active_world
    if _active_world is not None and in_simulation():
        raise RuntimeError("nested spawn() inside a simulated rank")
    world = SimWorld(nprocs)
    _active_world = world
    results: list[Any] = [None] * nprocs
    errors: list[BaseException | None] = [None] * nprocs

    def worker(rank):
        _tls.rank = rank
        _tls.seqs = {}
        try:
            results[rank] = fn(*args)
        except SimulatedRankKill as e:
            # an injected kill that escaped the rank's own handling: the
            # rank is already marked dead (fault.py does it before
            # raising), so survivors get structured RankFailures — do NOT
            # abort the world, the elastic loop may shrink and continue
            results[rank] = e
        except BaseException as e:  # noqa: BLE001 — reported to caller
            errors[rank] = e
            # unblock peers waiting on this rank
            world.rendezvous.abort()
        finally:
            _tls.rank = None

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(nprocs)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    _active_world = None
    if propagate:
        for r, e in enumerate(errors):
            if e is not None:
                raise RuntimeError(f"simulated rank {r} failed: {e!r}") from e
    return results
