"""paddle.distributed.sharding — group_sharded user API (reference:
``python/paddle/distributed/sharding/group_sharded.py`` —
``group_sharded_parallel(model, optimizer, level='os'|'os_g'|'p_g_os',
offload=...)`` and ``save_group_sharded_model``; SURVEY.md §2.3 "Sharding
stage 3")."""
from __future__ import annotations

import os

from ..fleet.meta_parallel.sharding import (
    DygraphShardingOptimizer, GroupShardedOptimizerStage2,
    GroupShardedStage2, GroupShardedStage3,
)


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=2 ** 23,
                           segment_size=2 ** 20, sync_comm=False, dp_group=None,
                           exclude_layer=None, comm_config=None):
    """level: 'os' (stage 1), 'os_g' (stage 2), 'p_g_os' (stage 3).

    ``comm_config``: optional dict for the per-rank gradient exchange
    (``fuse_grad_size_in_MB``, ``quantization``, ``block_size``,
    ``error_feedback`` — see ``distributed.comm.GradientBucketer``);
    defaults to the fleet strategy's comm knobs.
    """
    if level == "os":
        opt = DygraphShardingOptimizer(optimizer, comm_config=comm_config)
        return model, opt, scaler
    if level == "os_g":
        opt = GroupShardedOptimizerStage2(optimizer, comm_config=comm_config)
        wrapped = GroupShardedStage2(model, opt, group=group,
                                     sync_buffers=sync_buffers,
                                     buffer_max_size=buffer_max_size,
                                     dp_group=dp_group)
        return wrapped, opt, scaler
    if level == "p_g_os":
        opt = GroupShardedOptimizerStage2(optimizer, comm_config=comm_config)
        wrapped = GroupShardedStage3(model, opt, group=group,
                                     sync_buffers=sync_buffers,
                                     segment_size=segment_size, offload=offload,
                                     dp_group=dp_group, exclude_layer=exclude_layer)
        return wrapped, opt, scaler
    raise ValueError(f"unknown group_sharded level {level!r} "
                     "(expected 'os', 'os_g', or 'p_g_os')")


def save_group_sharded_model(model, output, optimizer=None):
    """Gather shards and save (rank 0 semantics; gathering is implicit —
    ``state_dict`` reads global arrays)."""
    from ...framework.io import save
    inner = getattr(model, "_layer", model)
    os.makedirs(output, exist_ok=True)
    save(inner.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
