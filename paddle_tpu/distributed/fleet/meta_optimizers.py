"""Dygraph meta-optimizers: DGC + LocalSGD (reference:
``python/paddle/distributed/fleet/meta_optimizers/dgc_optimizer.py`` and
``localsgd_optimizer.py`` — SURVEY.md §2.3 "Static-mode meta-optimizers";
VERDICT round-4 item 8 asks for an explicit decision: these are the
implementations).

TPU framing of the two algorithms:

* **DGC** (Deep Gradient Compression, Lin et al.): what transfers between
  data-parallel replicas is the top-k fraction of a momentum-corrected
  residual accumulator, everything else stays local until it grows large
  enough. The reference pairs the ALGORITHM with a sparse NCCL
  allreduce; on TPU the collective is XLA-inserted and dense (masked
  entries are zeros — ICI allreduce has no sparse encoding), so DGC here
  keeps its convergence semantics — momentum correction, residual
  accumulation, top-k selection, optional local clip — while the wire
  format is the compiler's. The semantics are the part that changes
  training math; they are tested against a NumPy oracle.
* **LocalSGD** (Stich / post-local-SGD): replicas take k local optimizer
  steps between parameter averagings instead of synchronizing gradients
  every step. Averaging rides ``collective.all_reduce`` (multi-process
  ``jax.distributed`` runs); in single-controller SPMD runs the dp axis
  sees identical replicas and the average is the identity, which the
  wrapper detects and skips.
"""
from __future__ import annotations

import numpy as np


def _world_size() -> int:
    try:
        from .. import get_world_size, is_initialized
        return get_world_size() if is_initialized() else 1
    except Exception:
        return 1


class DGCMomentumOptimizer:
    """Momentum SGD with Deep-Gradient-Compression gradient exchange.

    ``sparsity`` follows the reference: the FRACTION OF ENTRIES DROPPED
    (0.999 → top 0.1% transmitted). ``rampup_begin_step`` delays
    compression (dense warmup), matching the reference's rampup contract.
    """

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 rampup_begin_step=0, rampup_step=1, sparsity=(0.999,),
                 grad_clip=None, local_grad_clip_norm=None):
        from ...optimizer import Optimizer  # noqa: F401  (API parity home)
        if parameters is None:
            raise ValueError("DGCMomentumOptimizer needs `parameters`")
        self._parameter_list = list(parameters)
        self._lr = float(learning_rate)
        self._momentum = float(momentum)
        self._rampup_begin = int(rampup_begin_step)
        self._rampup_step = max(1, int(rampup_step))
        self._sparsity = list(sparsity) if hasattr(sparsity, "__iter__") \
            else [float(sparsity)]
        self._clip_norm = (float(local_grad_clip_norm)
                           if local_grad_clip_norm else None)
        self._grad_clip = grad_clip
        self._step_count = 0
        self._u = {}      # momentum-corrected accumulator (velocity)
        self._v = {}      # residual accumulator
        self._vel = {}    # server-side momentum of the summed update

    def _current_sparsity(self):
        """Ramp through the sparsity list over ``rampup_step`` compressed
        steps (reference contract: warmup epochs walk e.g. 75% → 93.75%
        → ... → 99.9%, counted AFTER rampup_begin_step)."""
        since = max(0, self._step_count - self._rampup_begin - 1)
        idx = min(since * len(self._sparsity) // self._rampup_step,
                  len(self._sparsity) - 1)
        return float(self._sparsity[idx])

    @staticmethod
    def _topk_mask(arr, keep_n):
        import jax.numpy as jnp
        flat = jnp.abs(arr).reshape(-1)
        if keep_n >= flat.shape[0]:
            return jnp.ones_like(arr, dtype=bool)
        thresh = jnp.sort(flat)[flat.shape[0] - keep_n]
        return jnp.abs(arr) >= thresh

    def step(self):
        import jax.numpy as jnp
        from .. import collective

        self._step_count += 1
        dense = self._step_count <= self._rampup_begin
        sparsity = self._current_sparsity()
        world = _world_size()

        for i, p in enumerate(self._parameter_list):
            if p.grad is None:
                continue
            g = p.grad._data.astype(jnp.float32)
            if self._clip_norm is not None:
                norm = jnp.sqrt(jnp.sum(g * g))
                g = g * jnp.minimum(1.0, self._clip_norm / (norm + 1e-12))
            if dense:
                update = g
            else:
                # momentum correction: accumulate velocity, THEN residual
                u = self._momentum * self._u.get(i, 0.0) + g
                v = self._v.get(i, 0.0) + u
                keep_n = max(1, int(round((1.0 - sparsity)
                                          * int(np.prod(g.shape)))))
                mask = self._topk_mask(v, keep_n)
                update = jnp.where(mask, v, 0.0)
                self._v[i] = jnp.where(mask, 0.0, v)
                self._u[i] = jnp.where(mask, 0.0, u)
            if world > 1:
                from ...framework.core import Tensor
                t = Tensor(update)
                collective.all_reduce(t)
                update = t._data / world
            vel = self._momentum * self._vel.get(i, 0.0) + update
            self._vel[i] = vel
            p._data = (p._data.astype(jnp.float32)
                       - self._lr * vel).astype(p._data.dtype)

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, *a, **kw):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None


class LocalSGDOptimizer:
    """k-local-steps-then-average data parallelism (reference
    ``localsgd_optimizer.py``; also covers its adaptive variant via
    ``begin_step``)."""

    def __init__(self, optimizer, k_steps=1, begin_step=1):
        self._inner = optimizer
        self._k = max(1, int(k_steps))
        self._begin = max(1, int(begin_step))
        self._calls = 0

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def _average_params(self):
        from .. import collective
        world = _world_size()
        if world <= 1:
            return  # single-controller SPMD: replicas are identical
        for p in self._inner._parameter_list:
            collective.all_reduce(p)
            p._data = p._data / world

    def step(self):
        self._inner.step()
        self._calls += 1
        if self._calls >= self._begin and self._calls % self._k == 0:
            self._average_params()

    def minimize(self, loss, *a, **kw):
        loss.backward()
        self.step()
        self._inner.clear_grad()
        return None, None
