"""Dygraph meta-optimizers: DGC + LocalSGD (reference:
``python/paddle/distributed/fleet/meta_optimizers/dgc_optimizer.py`` and
``localsgd_optimizer.py`` — SURVEY.md §2.3 "Static-mode meta-optimizers";
VERDICT round-4 item 8 asks for an explicit decision: these are the
implementations).

TPU framing of the two algorithms:

* **DGC** (Deep Gradient Compression, Lin et al.): what transfers between
  data-parallel replicas is the top-k fraction of a momentum-corrected
  residual accumulator, everything else stays local until it grows large
  enough. The reference pairs the ALGORITHM with a sparse NCCL
  allreduce; on TPU the collective is XLA-inserted and dense (masked
  entries are zeros — ICI allreduce has no sparse encoding), so DGC here
  keeps its convergence semantics — momentum correction, residual
  accumulation, top-k selection, optional local clip — while the wire
  format is the compiler's. The semantics are the part that changes
  training math; they are tested against a NumPy oracle. Momentum lives
  ONLY in the local correction once compression engages (``u = m·u + g``):
  the synced sparse update is applied with plain SGD, mirroring the
  reference's momentum-then-SGD switch at ``rampup_begin_step`` (round-5
  ADVICE item 1 — the previous double-EMA deviated from the reference).
* **LocalSGD** (Stich / post-local-SGD): replicas take k local optimizer
  steps between parameter averagings instead of synchronizing gradients
  every step. Averaging rides the ``distributed.comm`` bucketer over
  ``collective.all_reduce`` (multi-process ``jax.distributed`` runs); in
  single-controller SPMD runs the dp axis sees identical replicas and the
  average is the identity, which the wrapper detects and skips.

Both route their exchange through :class:`distributed.comm
.GradientBucketer`, so the fleet strategy's ``fuse_grad_size_in_MB`` /
``comm_quantization`` knobs apply to the meta-optimizers too.
"""
from __future__ import annotations

import numpy as np


def _world_size() -> int:
    try:
        from .. import get_world_size, is_initialized
        return get_world_size() if is_initialized() else 1
    except Exception:
        return 1


class DGCMomentumOptimizer:
    """Momentum SGD with Deep-Gradient-Compression gradient exchange.

    ``sparsity`` follows the reference: the FRACTION OF ENTRIES DROPPED
    (0.999 → top 0.1% transmitted). ``rampup_begin_step`` delays
    compression (dense warmup), matching the reference's rampup contract.
    ``grad_clip`` (a ``paddle.nn.ClipGradBy*``) is applied to the raw
    gradients before any DGC math, like the base ``Optimizer`` contract.
    """

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 rampup_begin_step=0, rampup_step=1, sparsity=(0.999,),
                 grad_clip=None, local_grad_clip_norm=None,
                 fuse_grad_size_in_MB=32, comm_quantization=None,
                 comm_configs=None):
        from ...optimizer import Optimizer  # noqa: F401  (API parity home)
        if parameters is None:
            raise ValueError("DGCMomentumOptimizer needs `parameters`")
        self._parameter_list = list(parameters)
        self._lr = float(learning_rate)
        self._momentum = float(momentum)
        self._rampup_begin = int(rampup_begin_step)
        self._rampup_step = max(1, int(rampup_step))
        self._sparsity = list(sparsity) if hasattr(sparsity, "__iter__") \
            else [float(sparsity)]
        self._clip_norm = (float(local_grad_clip_norm)
                           if local_grad_clip_norm else None)
        self._grad_clip = grad_clip
        self._step_count = 0
        self._u = {}      # momentum-corrected accumulator (velocity)
        self._v = {}      # residual accumulator
        self._vel = {}    # momentum of the synced update (dense warmup only)
        cfg = dict(comm_configs or {})
        self._comm_kwargs = {"fuse_grad_size_in_MB": fuse_grad_size_in_MB,
                             "quantization": comm_quantization,
                             "block_size": cfg.get("block_size", 256),
                             "error_feedback": cfg.get("error_feedback",
                                                       False)}
        self._bucketer = None

    def _current_sparsity(self):
        """Ramp through the sparsity list over ``rampup_step`` compressed
        steps (reference contract: warmup epochs walk e.g. 75% → 93.75%
        → ... → 99.9%, counted AFTER rampup_begin_step)."""
        since = max(0, self._step_count - self._rampup_begin - 1)
        idx = min(since * len(self._sparsity) // self._rampup_step,
                  len(self._sparsity) - 1)
        return float(self._sparsity[idx])

    @staticmethod
    def _topk_mask(arr, keep_n):
        import jax.numpy as jnp
        flat = jnp.abs(arr).reshape(-1)
        if keep_n >= flat.shape[0]:
            return jnp.ones_like(arr, dtype=bool)
        thresh = jnp.sort(flat)[flat.shape[0] - keep_n]
        return jnp.abs(arr) >= thresh

    def _exchange_updates(self, updates):
        """Average the per-param updates across replicas through the
        fusion bucketer (one collective per bucket, optionally quantized)
        instead of one dense per-tensor call each."""
        from ..comm import GradientBucketer
        from ..collective import ReduceOp
        if self._bucketer is None:
            self._bucketer = GradientBucketer(self._parameter_list,
                                              **self._comm_kwargs)
        return self._bucketer.sync_arrays(updates, op=ReduceOp.AVG)

    def step(self):
        import jax.numpy as jnp

        self._step_count += 1
        dense = self._step_count <= self._rampup_begin
        sparsity = self._current_sparsity()
        world = _world_size()

        grads = [p.grad for p in self._parameter_list]
        if self._grad_clip is not None:
            present = [(p, g) for p, g in zip(self._parameter_list, grads)
                       if g is not None]
            clipped = dict(zip((id(p) for p, _ in present),
                               (g for _, g in self._grad_clip(present))))
            grads = [clipped.get(id(p), g)
                     for p, g in zip(self._parameter_list, grads)]

        updates = [None] * len(self._parameter_list)
        for i, (p, g_t) in enumerate(zip(self._parameter_list, grads)):
            if g_t is None:
                continue
            g = g_t._data.astype(jnp.float32)
            if self._clip_norm is not None:
                norm = jnp.sqrt(jnp.sum(g * g))
                g = g * jnp.minimum(1.0, self._clip_norm / (norm + 1e-12))
            if dense:
                updates[i] = g
            else:
                # momentum correction: accumulate velocity, THEN residual
                u = self._momentum * self._u.get(i, 0.0) + g
                v = self._v.get(i, 0.0) + u
                keep_n = max(1, int(round((1.0 - sparsity)
                                          * int(np.prod(g.shape)))))
                mask = self._topk_mask(v, keep_n)
                updates[i] = jnp.where(mask, v, 0.0)
                self._v[i] = jnp.where(mask, 0.0, v)
                self._u[i] = jnp.where(mask, 0.0, u)

        if world > 1:
            updates = self._exchange_updates(updates)

        for i, p in enumerate(self._parameter_list):
            if updates[i] is None:
                continue
            update = jnp.asarray(updates[i], jnp.float32)
            if dense:
                # warmup: classic momentum SGD on the dense synced grad
                vel = self._momentum * self._vel.get(i, 0.0) + update
                self._vel[i] = vel
                delta = vel
            else:
                # compressed regime: plain SGD — momentum already lives in
                # the local correction u (reference dgc_momentum op)
                delta = update
            p._data = (p._data.astype(jnp.float32)
                       - self._lr * delta).astype(p._data.dtype)

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, *a, **kw):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None


class LocalSGDOptimizer:
    """k-local-steps-then-average data parallelism (reference
    ``localsgd_optimizer.py``; also covers its adaptive variant via
    ``begin_step``)."""

    def __init__(self, optimizer, k_steps=1, begin_step=1,
                 fuse_grad_size_in_MB=32, comm_quantization=None,
                 comm_configs=None):
        self._inner = optimizer
        self._k = max(1, int(k_steps))
        self._begin = max(1, int(begin_step))
        self._calls = 0
        cfg = dict(comm_configs or {})
        self._comm_kwargs = {"fuse_grad_size_in_MB": fuse_grad_size_in_MB,
                             "quantization": comm_quantization,
                             "block_size": cfg.get("block_size", 256),
                             "error_feedback": cfg.get("error_feedback",
                                                       False)}
        self._bucketer = None

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def _average_params(self):
        world = _world_size()
        if world <= 1:
            return  # single-controller SPMD: replicas are identical
        from ..comm import GradientBucketer
        from ..collective import ReduceOp
        if self._bucketer is None:
            self._bucketer = GradientBucketer(self._inner._parameter_list,
                                              **self._comm_kwargs)
        self._bucketer.sync_params(op=ReduceOp.AVG)

    def step(self):
        self._inner.step()
        self._calls += 1
        if self._calls >= self._begin and self._calls % self._k == 0:
            self._average_params()

    def minimize(self, loss, *a, **kw):
        loss.backward()
        self.step()
        self._inner.clear_grad()
        return None, None
