"""DistributedStrategy (reference: ``python/paddle/distributed/fleet/base/
distributed_strategy.py`` backed by ``distributed_strategy.proto`` — nested
configs: hybrid_configs {dp,mp,pp,sharding,sep degrees + pp/mp/sharding
sub-configs}, amp_configs, recompute_configs, sharding_configs; SURVEY.md
§5.6).

TPU-native: a plain typed config tree (no proto — serializes via to_dict/
from_dict for reproducible runs); the degrees drive mesh construction
(mesh.init_mesh) instead of NCCL ring creation.
"""
from __future__ import annotations

import copy
import json
import os


_HYBRID_DEFAULTS = {
    "dp_degree": 1,
    "mp_degree": 1,
    "pp_degree": 1,
    "sharding_degree": 1,
    "sep_degree": 1,
    "order": ["dp", "pp", "sharding", "sep", "mp"],
    "pp_configs": {
        "micro_batch_size": 1,
        "accumulate_steps": 1,
        "schedule_mode": "1F1B",  # FThenB | 1F1B | ZBH1
        "p2p_overlap": True,
    },
    "mp_configs": {
        "sync_param": False,
        "sync_grad": False,
        "sync_moment": False,
    },
    "sharding_configs": {
        "stage": 1,
        "offload": False,
        "segment_size": 2 ** 20,
    },
}

_AMP_DEFAULTS = {
    "init_loss_scaling": 2 ** 15,
    "incr_every_n_steps": 1000,
    "decr_every_n_nan_or_inf": 2,
    "incr_ratio": 2.0,
    "decr_ratio": 0.5,
    "use_dynamic_loss_scaling": True,
    "custom_white_list": [],
    "custom_black_list": [],
    "level": "O1",
    "dtype": "float16",
    "use_fp16_guard": False,
}

_RECOMPUTE_DEFAULTS = {
    "checkpoints": [],
    "enable_offload": False,
}

_COMM_DEFAULTS = {
    "block_size": 256,        # elements per quantization block
    "error_feedback": False,  # carry compression error into the next round
}


def _merge(defaults, override):
    out = copy.deepcopy(defaults)
    for k, v in (override or {}).items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _merge(out[k], v)
        else:
            out[k] = v
    return out


class DistributedStrategy:
    def __init__(self):
        self._hybrid_configs = copy.deepcopy(_HYBRID_DEFAULTS)
        self._amp_configs = copy.deepcopy(_AMP_DEFAULTS)
        self._recompute_configs = copy.deepcopy(_RECOMPUTE_DEFAULTS)
        self._sharding_configs = {}
        self.amp = False
        self.recompute = False
        self.sharding = False
        self.heter_ccl_mode = False
        self.find_unused_parameters = False
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        # dp-axis meta-optimizers (reference dgc_optimizer / localsgd_
        # optimizer); realized by fleet.meta_optimizers wrappers
        self.dgc = False
        self.dgc_configs = {"rampup_begin_step": 0, "rampup_step": 1,
                            "sparsity": [0.999]}
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 1, "begin_step": 1}
        self.fuse_all_reduce_ops = True  # advisory on TPU (XLA fuses)
        self.nccl_comm_num = 1           # accepted, meaningless on ICI
        # gradient-communication policy (distributed.comm): fusion bucket
        # size for the imperative dp/sharding exchange (0 → per-tensor),
        # wire quantization scheme (None/"fp32" | "bf16" | "int8"), and
        # codec sub-config
        self.fuse_grad_size_in_MB = 32
        self.comm_quantization = None
        self._comm_configs = copy.deepcopy(_COMM_DEFAULTS)
        # comm/compute overlap (ready-bucket scheduling): each fusion
        # bucket's collective dispatches the moment its last gradient
        # lands in backward; False restores the barrier-at-step exchange.
        # PADDLE_COMM_OVERLAP=0 flips the process-wide default.
        self.comm_overlap = os.environ.get(
            "PADDLE_COMM_OVERLAP", "1").lower() not in ("0", "false", "off")
        # auto-parallel mesh search (reference: strategy.auto / the
        # rule-based tuner): with auto_search=True and a model spec in
        # auto_search_configs, fleet.init runs the cost-model Tuner over
        # the available chips and installs the best plan's degrees
        self.auto_search = False
        self.auto_search_configs = {}    # model=<cfg>|ModelSpec fields,
        #                                  seq_len, global_batch, chip

    # -- hybrid --------------------------------------------------------------
    @property
    def hybrid_configs(self):
        return self._hybrid_configs

    @hybrid_configs.setter
    def hybrid_configs(self, configs):
        self._hybrid_configs = _merge(_HYBRID_DEFAULTS, configs)

    @property
    def amp_configs(self):
        return self._amp_configs

    @amp_configs.setter
    def amp_configs(self, configs):
        self._amp_configs = _merge(_AMP_DEFAULTS, configs)

    @property
    def recompute_configs(self):
        return self._recompute_configs

    @recompute_configs.setter
    def recompute_configs(self, configs):
        self._recompute_configs = _merge(_RECOMPUTE_DEFAULTS, configs)

    @property
    def comm_configs(self):
        return self._comm_configs

    @comm_configs.setter
    def comm_configs(self, configs):
        self._comm_configs = _merge(_COMM_DEFAULTS, configs)

    @property
    def sharding_configs(self):
        return self._sharding_configs

    @sharding_configs.setter
    def sharding_configs(self, configs):
        self._sharding_configs = dict(configs)

    def degrees(self):
        h = self._hybrid_configs
        return {
            "dp": int(h["dp_degree"]),
            "pp": int(h["pp_degree"]),
            "sharding": int(h["sharding_degree"]),
            "sep": int(h["sep_degree"]),
            "mp": int(h["mp_degree"]),
        }

    # -- serialization (the proto's job in the reference) --------------------
    def to_dict(self):
        return {
            "hybrid_configs": self._hybrid_configs,
            "amp": self.amp, "amp_configs": self._amp_configs,
            "recompute": self.recompute,
            "recompute_configs": self._recompute_configs,
            "sharding": self.sharding, "sharding_configs": self._sharding_configs,
            "fuse_grad_size_in_MB": self.fuse_grad_size_in_MB,
            "comm_quantization": self.comm_quantization,
            "comm_configs": self._comm_configs,
            "comm_overlap": self.comm_overlap,
        }

    def __repr__(self):
        return "DistributedStrategy(" + json.dumps(self.to_dict(), indent=2) + ")"

    @classmethod
    def from_dict(cls, d):
        s = cls()
        s.hybrid_configs = d.get("hybrid_configs", {})
        s.amp = d.get("amp", False)
        s.amp_configs = d.get("amp_configs", {})
        s.recompute = d.get("recompute", False)
        s.recompute_configs = d.get("recompute_configs", {})
        s.sharding = d.get("sharding", False)
        s.sharding_configs = d.get("sharding_configs", {})
        s.fuse_grad_size_in_MB = d.get("fuse_grad_size_in_MB", 32)
        s.comm_quantization = d.get("comm_quantization", None)
        s.comm_configs = d.get("comm_configs", {})
        s.comm_overlap = d.get("comm_overlap", s.comm_overlap)
        return s
