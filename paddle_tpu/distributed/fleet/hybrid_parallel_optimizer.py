"""HybridParallelOptimizer (reference: ``python/paddle/distributed/fleet/
meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py`` —
hybrid-aware global-norm grad clip across mp/pp/sharding groups + delegation
to DygraphShardingOptimizer; SURVEY.md §2.3 "Fleet facade").

TPU-native: eager tensors are *global* arrays over the mesh, so a global
norm computed with ordinary ops is already correct across every axis — the
reference's cross-group norm allreduce ladder collapses. What remains is
(a) stage-1 sharding delegation, (b) distributed-param handling for clip,
(c) in per-rank execution (thread simulator / one process per host), the
data-parallel gradient exchange itself — routed through the
``distributed.comm`` bucketer so one (optionally quantized) collective
covers many tensors instead of a per-tensor fp32 call each.
"""
from __future__ import annotations

from .meta_parallel.sharding import DygraphShardingOptimizer


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._hcg = hcg
        self._strategy = strategy
        self._comm_bucketer = None
        self._overlap_sched = None
        sharding_degree = 1
        if strategy is not None:
            sharding_degree = strategy.degrees().get("sharding", 1)
        if sharding_degree > 1:
            stage = strategy.hybrid_configs.get("sharding_configs", {}).get("stage", 1)
            if stage == 1:
                optimizer = DygraphShardingOptimizer(optimizer, hcg)
        self._inner_opt = optimizer
        self._maybe_install_overlap()

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    # -- comm/compute overlap ------------------------------------------------
    def _dp_exchange_applies(self):
        """Same eligibility gate as ``_maybe_sync_dp_grads`` (per-rank
        tiers only, dp>1, not a meta-optimizer that owns its exchange)."""
        s = self._strategy
        if s is None or s.degrees().get("dp", 1) <= 1:
            return False
        from .meta_optimizers import DGCMomentumOptimizer, LocalSGDOptimizer
        if isinstance(self._inner_opt, (DGCMomentumOptimizer,
                                        LocalSGDOptimizer)):
            return False
        import jax
        from .. import simulator
        from ..parallel_env import get_world_size
        if simulator.active_world() is None and jax.process_count() <= 1:
            return False
        return get_world_size() > 1

    def _maybe_install_overlap(self):
        """Register a tape grad-ready hook so each fusion bucket's dp
        collective dispatches DURING backward (ready-bucket scheduling);
        ``step()`` then only waits on the handles. Installed at
        construction — the optimizer exists before the first backward, the
        reducer-hook shape of the reference."""
        if not getattr(self._strategy, "comm_overlap", True):
            return
        if not self._dp_exchange_applies():
            return
        import weakref
        from ...autograd import tape
        ref = weakref.ref(self)

        def _ready(t):
            opt = ref()
            if opt is None:
                tape.unregister_grad_ready_callback(_ready)
                return
            opt._on_grad_ready(t)

        self._overlap_cb = tape.register_grad_ready_callback(_ready)

    def _overlap_params(self):
        return [p for p in getattr(self._inner_opt, "_parameter_list", [])
                if p is not None and getattr(p, "trainable", True)]

    def _on_grad_ready(self, t):
        sched = self._overlap_sched
        if sched is None:
            params = self._overlap_params()
            if not params:
                return
            from ..comm import GradientBucketer, ReadyBucketScheduler
            from ..collective import ReduceOp
            sched = self._overlap_sched = ReadyBucketScheduler(
                GradientBucketer.from_strategy(params, self._strategy),
                name="hpo", op=ReduceOp.AVG)
        sched.mark_ready(t)

    def _consume_overlap(self):
        """True when a live overlap round covered the dp exchange."""
        sched = self._overlap_sched
        if sched is None:
            return False
        if not sched.matches(self._overlap_params()):
            sched.close()
            self._overlap_sched = None     # layout changed — rebuild
            return False
        sched.finish()
        return True

    # -- per-rank dp gradient exchange ---------------------------------------
    def _maybe_sync_dp_grads(self):
        """Bucketed (and, per strategy, quantized) dp grad exchange for the
        per-rank tiers. The SPMD/mesh perf path never reaches this (XLA
        inserts the reduction); meta-optimizers that own their exchange
        (DGC/LocalSGD) are left alone; world size 1 is a no-op. AVG over
        already-AVG'd identical grads is idempotent, so composition with
        ``DataParallel``'s backward hook stays correct."""
        s = self._strategy
        if s is None or s.degrees().get("dp", 1) <= 1:
            return
        from .meta_optimizers import DGCMomentumOptimizer, LocalSGDOptimizer
        if isinstance(self._inner_opt, (DGCMomentumOptimizer,
                                        LocalSGDOptimizer)):
            return
        import jax
        from .. import simulator
        from ..parallel_env import get_world_size
        if simulator.active_world() is None and jax.process_count() <= 1:
            return
        if get_world_size() <= 1:
            return
        params = [p for p in getattr(self._inner_opt, "_parameter_list", [])
                  if p is not None and getattr(p, "trainable", True)]
        if not params:
            return
        from ..comm import GradientBucketer
        b = self._comm_bucketer
        if b is None or [id(p) for p in b._params] != [id(p) for p in params]:
            b = self._comm_bucketer = GradientBucketer.from_strategy(params, s)
        from ..collective import ReduceOp
        b.sync_grads(op=ReduceOp.AVG)

    def step(self):
        if not self._consume_overlap():
            self._maybe_sync_dp_grads()
        self._inner_opt.step()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self._inner_opt.clear_grad()
        return None, None

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    clear_gradients = clear_grad
