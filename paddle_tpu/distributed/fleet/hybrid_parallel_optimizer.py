"""HybridParallelOptimizer (reference: ``python/paddle/distributed/fleet/
meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py`` —
hybrid-aware global-norm grad clip across mp/pp/sharding groups + delegation
to DygraphShardingOptimizer; SURVEY.md §2.3 "Fleet facade").

TPU-native: eager tensors are *global* arrays over the mesh, so a global
norm computed with ordinary ops is already correct across every axis — the
reference's cross-group norm allreduce ladder collapses. What remains is
(a) stage-1 sharding delegation, (b) distributed-param handling for clip.
"""
from __future__ import annotations

from .meta_parallel.sharding import DygraphShardingOptimizer


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._hcg = hcg
        self._strategy = strategy
        sharding_degree = 1
        if strategy is not None:
            sharding_degree = strategy.degrees().get("sharding", 1)
        if sharding_degree > 1:
            stage = strategy.hybrid_configs.get("sharding_configs", {}).get("stage", 1)
            if stage == 1:
                optimizer = DygraphShardingOptimizer(optimizer, hcg)
        self._inner_opt = optimizer

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self._inner_opt.step()
        self._inner_opt.clear_grad()
        return None, None

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    clear_gradients = clear_grad
