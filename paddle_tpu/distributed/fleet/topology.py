"""Hybrid topology (reference: ``python/paddle/distributed/fleet/base/
topology.py`` — ``CommunicateTopology`` N-D rank mesh in order
[dp, pp, sharding, sep, mp] + ``HybridCommunicateGroup`` creating one NCCL
group per axis; SURVEY.md §2.3 "Hybrid composition").

TPU-native: the topology IS the jax mesh (mesh.py). A "comm group per axis"
degenerates to a named mesh axis — collectives on it are emitted by XLA from
shardings. This class keeps the reference's coordinate math and getters for
API parity (model code asks it for world sizes / groups), with ranks meaning
*device* coordinates in the single-controller mesh.
"""
from __future__ import annotations

import itertools

import numpy as np

from .. import mesh as mesh_mod
from ..collective import Group
from ..parallel_env import get_rank


class CommunicateTopology:
    def __init__(self, hybrid_group_names=None, dims=None):
        self._parallel_names = list(hybrid_group_names or
                                    ["data", "pipe", "sharding", "sep", "model"])
        self._dims = list(dims or [1] * len(self._parallel_names))
        self._coord2rank = {}
        self._rank2coord = {}
        for rank, coord in enumerate(itertools.product(*[range(d) for d in self._dims])):
            self._coord2rank[coord] = rank
            self._rank2coord[rank] = coord

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return int(np.prod(self._dims))

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        """All ranks whose coordinate on ``axis_name`` equals ``index``."""
        axis = self._parallel_names.index(axis_name)
        return sorted(r for coord, r in self._coord2rank.items()
                      if coord[axis] == index)

    def get_comm_list(self, axis_name):
        """List of rank-lists, one per communicator along ``axis_name``."""
        axis = self._parallel_names.index(axis_name)
        others = [range(d) for i, d in enumerate(self._dims) if i != axis]
        comm_list = []
        for fixed in itertools.product(*others):
            ranks = []
            for i in range(self._dims[axis]):
                coord = list(fixed)
                coord.insert(axis, i)
                ranks.append(self._coord2rank[tuple(coord)])
            comm_list.append(ranks)
        return comm_list


# axis-name translation: reference parallel names -> mesh axis names
_NAME2AXIS = {"data": "dp", "pipe": "pp", "sharding": "sharding",
              "sep": "sep", "model": "mp"}


class HybridCommunicateGroup:
    """Per-axis groups + coordinate getters. In mesh mode "my rank" is the
    process rank (0 in single-controller); world sizes come from the mesh."""

    def __init__(self, topology: CommunicateTopology | None = None):
        if topology is None:
            m = mesh_mod.get_mesh()
            dims = [int(m.shape.get(ax, 1)) for ax in mesh_mod.HYBRID_AXES]
            topology = CommunicateTopology(dims=dims)
        self._topo = topology
        self.nranks = topology.world_size()
        self.global_rank = max(get_rank(), 0)
        coord = self._topo.get_coord(self.global_rank)
        names = self._topo.get_hybrid_group_names()
        self._coord = dict(zip(names, coord))
        self._groups = {}
        for name in names:
            axis = _NAME2AXIS.get(name, name)
            # the group containing this rank along `name`
            for ranks in self._topo.get_comm_list(name):
                if self.global_rank in ranks:
                    self._groups[name] = Group(ranks, axis=axis, name=f"{name}_group")
                    break

    # -- degrees -------------------------------------------------------------
    def get_data_parallel_world_size(self):
        return self._topo.get_dim("data")

    def get_model_parallel_world_size(self):
        return self._topo.get_dim("model")

    def get_pipe_parallel_world_size(self):
        return self._topo.get_dim("pipe")

    def get_sharding_parallel_world_size(self):
        return self._topo.get_dim("sharding")

    def get_sep_parallel_world_size(self):
        return self._topo.get_dim("sep")

    # -- my coordinates ------------------------------------------------------
    def get_data_parallel_rank(self):
        return self._coord["data"]

    def get_model_parallel_rank(self):
        return self._coord["model"]

    def get_stage_id(self):
        return self._coord["pipe"]

    def get_sharding_parallel_rank(self):
        return self._coord["sharding"]

    def get_sep_parallel_rank(self):
        return self._coord["sep"]

    # -- groups --------------------------------------------------------------
    def get_data_parallel_group(self):
        return self._groups["data"]

    def get_model_parallel_group(self):
        return self._groups["model"]

    def get_pipe_parallel_group(self):
        return self._groups["pipe"]

    def get_sharding_parallel_group(self):
        return self._groups["sharding"]

    def get_sep_parallel_group(self):
        return self._groups["sep"]

    def get_check_parallel_group(self, *a, **k):
        return Group(list(range(self.nranks)), name="check_group")

    def get_data_parallel_group_src_rank(self):
        return self._groups["data"].ranks[0]

    def get_model_parallel_group_src_rank(self):
        return self._groups["model"].ranks[0]

    # pipeline neighbours (used by p2p schedules)
    def get_p2p_groups(self):
        return None

    @property
    def topology(self):
        return self._topo


_hcg: HybridCommunicateGroup | None = None


def set_hybrid_communicate_group(hcg):
    global _hcg
    _hcg = hcg


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    global _hcg
    if _hcg is None:
        _hcg = HybridCommunicateGroup()
    return _hcg
