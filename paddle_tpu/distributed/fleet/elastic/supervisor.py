"""Checkpoint-restart supervision (SURVEY.md §5.3 "TPU equivalent": slice
failure → restart loop + checkpoint-resume + deterministic data skip).

The reference recovers NCCL-job failures by killing and relaunching trainers
from the launcher; on TPU the same supervisor drives in-process retry with
state restored from the latest complete checkpoint.
"""
from __future__ import annotations

import os
import shutil
import time

from ....framework import io as fio


class CheckpointManager:
    """Step-tagged checkpoints with atomic completion marker + retention."""

    def __init__(self, directory, keep=3):
        self.directory = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    def _dir(self, step):
        return os.path.join(self.directory, f"step_{step}")

    def save(self, step, state):
        d = self._dir(step)
        tmp = d + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        fio.save(state, os.path.join(tmp, "state.pdz"))
        os.replace(tmp, d)                      # atomic completion
        self._retain()
        return d

    def steps(self):
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("step_") and not name.endswith(".tmp"):
                try:
                    out.append(int(name.split("_", 1)[1]))
                except ValueError:
                    pass
        return sorted(out)

    def latest_step(self):
        s = self.steps()
        return s[-1] if s else None

    def load(self, step=None):
        step = step if step is not None else self.latest_step()
        if step is None:
            return None, None
        return step, fio.load(os.path.join(self._dir(step), "state.pdz"))

    def _retain(self):
        for s in self.steps()[:-self.keep]:
            shutil.rmtree(self._dir(s), ignore_errors=True)


class TrainingSupervisor:
    """Run a training fn with automatic restart-from-checkpoint.

    ``fn(start_step, state, ckpt_manager)`` should periodically
    ``ckpt.save(step, state)`` and may raise on failure; the supervisor
    reloads the latest checkpoint and re-invokes, up to ``max_restarts``.
    """

    def __init__(self, checkpoint_dir, max_restarts=3, keep=3,
                 backoff_seconds=0.0):
        self.ckpt = CheckpointManager(checkpoint_dir, keep=keep)
        self.max_restarts = max_restarts
        self.backoff_seconds = backoff_seconds
        self.restarts = 0

    def run(self, fn):
        while True:
            step, state = self.ckpt.load()
            try:
                return fn(0 if step is None else step, state, self.ckpt)
            except Exception:
                self.restarts += 1
                if self.restarts > self.max_restarts:
                    raise
                if self.backoff_seconds:
                    time.sleep(self.backoff_seconds)
