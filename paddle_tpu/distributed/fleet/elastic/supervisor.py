"""Checkpoint-restart supervision + in-run elastic shrink/regrow
(SURVEY.md §5.3; ROADMAP item 5).

Three tiers of recovery live here:

* :class:`CheckpointManager` — step-tagged checkpoints with an atomic
  completion marker (directory rename), retention, orphan-tmp sweeping,
  an **async** writer that snapshots device state on the caller's thread
  and writes off the critical path, and a **sharded** variant that rides
  ``paddle.distributed.checkpoint`` (per-shard ``.npy`` + metadata, so
  restore onto a *different* world size reuses the re-shard-on-load
  path).
* :class:`TrainingSupervisor` — single-process restart-from-checkpoint
  (the reference's kill-and-relaunch loop, in-process).
* :class:`ElasticTrainLoop` — the full elastic loop: KV-store membership
  (:class:`ElasticWorld`) with generation barriers, structured failure
  detection (``simulator.RankFailure`` surfaced by survivors the moment
  a peer dies — fed by fault injection in tests, by the flight-recorder
  watchdog / membership TTL in real runs), deterministic mesh shrink to
  the survivors, restore from the latest complete checkpoint, and regrow
  at the next checkpoint boundary.
"""
from __future__ import annotations

import os
import shutil
import threading
import time

from ....framework import io as fio


def _ckpt_telemetry():
    from ...fault import elastic_telemetry
    return elastic_telemetry()


class _AsyncSaveHandle:
    """Join handle for one in-flight checkpoint write."""

    def __init__(self, thread, errbox, path):
        self._thread = thread
        self._err = errbox
        self.path = path

    def wait(self, timeout=None):
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError(
                f"async checkpoint write to {self.path} still running")
        if self._err and self._err[0] is not None:
            raise self._err[0]
        return self.path

    result = wait

    def done(self):
        return not self._thread.is_alive()


class CheckpointManager:
    """Step-tagged checkpoints with atomic completion marker + retention.

    Completion contract: a checkpoint exists iff ``step_<N>`` (no
    ``.tmp`` suffix) exists — writers stage into ``step_<N>.tmp`` and
    ``os.replace`` on success, so readers can never observe a partial
    save. A writer killed mid-save leaves only an orphaned ``.tmp``
    directory, which :meth:`sweep_orphans` (and retention, for stale
    steps) removes.
    """

    def __init__(self, directory, keep=3):
        self.directory = directory
        self.keep = keep
        self._pending: _AsyncSaveHandle | None = None
        os.makedirs(directory, exist_ok=True)

    def _dir(self, step):
        return os.path.join(self.directory, f"step_{step}")

    # -- write paths ---------------------------------------------------------
    def save(self, step, state):
        """Synchronous save (blocks until durable)."""
        self.wait_pending()
        d = self._dir(step)
        self._write_pickle(step, fio._pack(state))
        return d

    def save_async(self, step, state):
        """Off-critical-path save: device→host snapshot happens NOW (on
        the caller's thread, so the captured state is step-consistent);
        serialization + fsync-rename run on a background thread. At most
        one write is in flight — a second save waits the first. Returns
        a handle with ``.wait()``; ``paddle_ckpt_async_seconds`` records
        each write's off-path wall time."""
        self.wait_pending()
        payload = fio._pack(state)            # snapshot before returning
        errbox = [None]

        def write():
            t0 = time.perf_counter()
            try:
                self._write_pickle(step, payload)
            except BaseException as e:  # noqa: BLE001 — re-raised at wait()
                errbox[0] = e
            finally:
                try:
                    _ckpt_telemetry()["ckpt_async"].observe(
                        time.perf_counter() - t0)
                except Exception:
                    pass

        th = threading.Thread(target=write, daemon=True,
                              name=f"paddle-ckpt-async-{step}")
        th.start()
        self._pending = _AsyncSaveHandle(th, errbox, self._dir(step))
        return self._pending

    def _complete(self, tmp, d):
        """Publish staging dir ``tmp`` as complete checkpoint ``d``.
        ``os.replace`` onto a non-empty directory fails (ENOTEMPTY),
        and a complete ``d`` legitimately exists when a run that
        restored from an earlier step re-writes later ones — move it
        aside first, then drop it once the new dir is in place. The
        aside name ends in ``.tmp`` so crash hygiene sweeps it."""
        old = None
        if os.path.isdir(d):
            old = d + ".old.tmp"
            shutil.rmtree(old, ignore_errors=True)
            os.replace(d, old)
        os.replace(tmp, d)                      # atomic completion
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)

    def _write_pickle(self, step, payload):
        import pickle
        d = self._dir(step)
        tmp = d + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp, exist_ok=True)
        with open(os.path.join(tmp, "state.pdz"), "wb") as f:
            pickle.dump(payload, f, protocol=4)
        self._complete(tmp, d)
        self._retain()
        return d

    def save_sharded(self, step, state, async_save=False, **kw):
        """Sharded save through ``paddle.distributed.checkpoint`` — each
        host writes its addressable shards; restore onto a different
        mesh/world reuses that module's re-shard-on-load. The step dir
        gains the same atomic rename marker as the pickle path. With
        ``async_save`` the device→host snapshot is taken by
        ``save_state_dict`` immediately and the rename happens when the
        background writer finishes."""
        from ... import checkpoint as dckpt
        self.wait_pending()
        d = self._dir(step)
        tmp = d + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp, exist_ok=True)
        inner = dckpt.save_state_dict(state, tmp, async_save=async_save,
                                      save_id=step, **kw)
        if not async_save:
            self._complete(tmp, d)
            self._retain()
            return d
        errbox = [None]

        def finish():
            t0 = time.perf_counter()
            try:
                inner.wait()
                self._complete(tmp, d)
                self._retain()
            except BaseException as e:  # noqa: BLE001
                errbox[0] = e
            finally:
                try:
                    _ckpt_telemetry()["ckpt_async"].observe(
                        time.perf_counter() - t0)
                except Exception:
                    pass

        th = threading.Thread(target=finish, daemon=True,
                              name=f"paddle-ckpt-sharded-{step}")
        th.start()
        self._pending = _AsyncSaveHandle(th, errbox, d)
        return self._pending

    def wait_pending(self):
        """Block until the in-flight async save (if any) is durable."""
        h, self._pending = self._pending, None
        if h is not None:
            h.wait()

    # -- read paths ----------------------------------------------------------
    def steps(self):
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("step_") and not name.endswith(".tmp"):
                try:
                    out.append(int(name.split("_", 1)[1]))
                except ValueError:
                    pass
        return sorted(out)

    def latest_step(self):
        self.wait_pending()
        s = self.steps()
        return s[-1] if s else None

    def load(self, step=None):
        self.wait_pending()
        step = step if step is not None else self.latest_step()
        if step is None:
            return None, None
        d = self._dir(step)
        pkl = os.path.join(d, "state.pdz")
        if not os.path.exists(pkl) and os.path.exists(
                os.path.join(d, "metadata.json")):
            raise ValueError(
                f"checkpoint step {step} is sharded (metadata.json); load "
                "it with load_sharded(state_template, step=...)")
        return step, fio.load(pkl)

    def load_sharded(self, state_template, step=None, **kw):
        """Fill ``state_template`` (a state dict with the target tensors
        already constructed — their CURRENT shardings decide placement)
        from a sharded checkpoint; re-shard-on-load handles a different
        save-time mesh. Returns ``(step, state_template)``."""
        from ... import checkpoint as dckpt
        self.wait_pending()
        step = step if step is not None else self.latest_step()
        if step is None:
            return None, None
        dckpt.load_state_dict(state_template, self._dir(step), **kw)
        return step, state_template

    # -- hygiene -------------------------------------------------------------
    def _retain(self):
        for s in self.steps()[:-self.keep]:
            shutil.rmtree(self._dir(s), ignore_errors=True)
        # crash hygiene: a rank killed mid-save leaves step_<N>.tmp
        # behind. Any tmp at or below the newest COMPLETE step can't
        # belong to a live writer (steps are monotonic; one write in
        # flight per manager), so sweep it here — newer tmps may be a
        # peer's in-flight save and are left for sweep_orphans().
        done = self.steps()
        newest = done[-1] if done else None
        for name in os.listdir(self.directory):
            if not (name.startswith("step_") and name.endswith(".tmp")):
                continue
            try:
                s = int(name[len("step_"):-len(".tmp")])
            except ValueError:
                continue
            if newest is not None and s <= newest:
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    def sweep_orphans(self):
        """Remove EVERY ``step_*.tmp`` staging dir. Only call when no
        writer can be mid-save (e.g. at an elastic rebuild barrier, after
        every survivor waited its own pending write — anything left was
        abandoned by a dead rank)."""
        removed = []
        for name in os.listdir(self.directory):
            if name.startswith("step_") and name.endswith(".tmp"):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)
                removed.append(name)
        return removed


class TrainingSupervisor:
    """Run a training fn with automatic restart-from-checkpoint.

    ``fn(start_step, state, ckpt_manager)`` should periodically
    ``ckpt.save(step, state)`` and may raise on failure; the supervisor
    reloads the latest checkpoint and re-invokes, up to ``max_restarts``.
    """

    def __init__(self, checkpoint_dir, max_restarts=3, keep=3,
                 backoff_seconds=0.0):
        self.ckpt = CheckpointManager(checkpoint_dir, keep=keep)
        self.max_restarts = max_restarts
        self.backoff_seconds = backoff_seconds
        self.restarts = 0

    def run(self, fn):
        while True:
            step, state = self.ckpt.load()
            try:
                return fn(0 if step is None else step, state, self.ckpt)
            except Exception:
                self.restarts += 1
                if self.restarts > self.max_restarts:
                    raise
                if self.backoff_seconds:
                    time.sleep(self.backoff_seconds)


# ---------------------------------------------------------------------------
# KV-backed membership + generation barrier
# ---------------------------------------------------------------------------


class ElasticWorld:
    """Rank membership over any elastic KV store (``MemKVStore`` in the
    thread simulator, ``TcpKVStore``/``FileKVStore`` across hosts).

    Liveness = a fresh member key (heartbeat within ``ttl``) without a
    dead marker. World changes are coordinated by integer *generations*:
    any rank may propose ``gen+1`` (failure detector, rejoiner); everyone
    then meets in :meth:`agree`, a two-phase barrier — phase A collects
    acks until they exactly cover the live membership, the leader (lowest
    live rank) runs the purge callback (rendezvous cleanup, orphan
    checkpoint sweep) and publishes the authoritative world; phase B
    releases everyone on that published world, after which each rank
    resets its simulator collective counters so tags pair deterministically
    in the new generation."""

    def __init__(self, store, job_id="elastic", rank=None, ttl=5.0,
                 heartbeat_interval=None, poll=0.005):
        from ...parallel_env import get_rank
        self.store = store
        self.job_id = job_id
        self.rank = get_rank() if rank is None else int(rank)
        self.ttl = float(ttl)
        self.poll = float(poll)
        self.heartbeat_interval = (heartbeat_interval
                                   if heartbeat_interval is not None
                                   else max(self.ttl / 4.0, 0.05))
        self._stop = threading.Event()
        self._hb = None

    def _k(self, *parts):
        return "/".join((self.job_id,) + tuple(str(p) for p in parts))

    # -- membership ----------------------------------------------------------
    def join(self):
        """(Re)register this rank: clear any dead marker left by a
        previous life, revive it in the active simulator world, and start
        heartbeating."""
        self.store.delete(self._k("dead", self.rank))
        from ... import simulator
        w = simulator.active_world()
        if w is not None:
            w.revive(self.rank)
        self.store.put(self._k("member", self.rank), self.rank)
        if self._hb is None or not self._hb.is_alive():
            self._stop.clear()

            def beat():
                while not self._stop.wait(self.heartbeat_interval):
                    self.store.put(self._k("member", self.rank), self.rank)

            self._hb = threading.Thread(target=beat, daemon=True,
                                        name=f"elastic-hb-r{self.rank}")
            self._hb.start()

    def leave(self):
        self._stop.set()
        if self._hb is not None:
            self._hb.join(timeout=2)
            self._hb = None
        self.store.delete(self._k("member", self.rank))

    def die(self):
        """This rank is going away NON-gracefully (injected kill): mark
        itself dead so survivors' membership converges immediately
        instead of waiting out the TTL."""
        self.mark_dead(self.rank)
        self.leave()

    def mark_dead(self, rank):
        self.store.put(self._k("dead", rank), True)

    def dead_ranks(self):
        out = set()
        for key in self.store.keys(self._k("dead", "")):
            try:
                out.add(int(key.rsplit("/", 1)[-1]))
            except ValueError:
                pass
        return out

    def members(self):
        """Live ranks: fresh member key, no dead marker."""
        dead = self.dead_ranks()
        out = set()
        for key in self.store.keys(self._k("member", "")):
            try:
                r = int(key.rsplit("/", 1)[-1])
            except ValueError:
                continue
            age = self.store.age(key)
            if r not in dead and age is not None and age <= self.ttl:
                out.add(r)
        return out

    def stale_members(self):
        """Ranks whose member key exists but whose heartbeat exceeded the
        TTL — the membership-TTL failure signal (used when a failure is
        detected as a bare timeout with no rank attribution)."""
        out = set()
        for key in self.store.keys(self._k("member", "")):
            try:
                r = int(key.rsplit("/", 1)[-1])
            except ValueError:
                continue
            age = self.store.age(key)
            if age is not None and age > self.ttl:
                out.add(r)
        return out

    # -- generations ---------------------------------------------------------
    def stored_gen(self) -> int:
        g = self.store.get(self._k("gen"))
        return int(g) if g is not None else 0

    def propose(self, gen: int) -> int:
        """Request a rebuild at generation >= ``gen``. Idempotent —
        concurrent proposers converge on the max."""
        g = max(int(gen), self.stored_gen())
        self.store.put(self._k("gen"), g)
        return g

    def published_world(self, gen):
        w = self.store.get(self._k("world", gen))
        return None if w is None else [int(r) for r in w]

    def publish_progress(self, step):
        self.store.put(self._k("progress", self.rank), int(step))

    def progress(self):
        out = {}
        for key in self.store.keys(self._k("progress", "")):
            try:
                out[int(key.rsplit("/", 1)[-1])] = int(self.store.get(key))
            except (TypeError, ValueError):
                pass
        return out

    def agree(self, gen, purge_cb=None, timeout=60.0, settle=3):
        """Generation barrier; returns the agreed (leader-published)
        sorted world. ``purge_cb(world)`` runs exactly once, on the
        leader, between the ack phase and the release phase — every
        member is parked inside the barrier at that point, so it is the
        only safe window for cross-rank cleanup (rendezvous purge,
        checkpoint orphan sweep)."""
        self.store.put(self._k("a", gen, self.rank), True)
        deadline = time.monotonic() + timeout
        stable = 0
        world = None
        while True:
            # a later generation supersedes this barrier (e.g. a second
            # failure while agreeing): bail out and let the caller re-agree
            g2 = self.stored_gen()
            if g2 > gen:
                raise WorldChanged(g2)
            acks = set()
            for key in self.store.keys(self._k("a", gen, "")):
                try:
                    acks.add(int(key.rsplit("/", 1)[-1]))
                except ValueError:
                    pass
            mem = self.members()
            # superset, not equality: a rank that acked and then died (or
            # acked a moment before marking itself dead) must not wedge
            # the barrier — the authoritative world is the live members
            if mem and acks >= mem:
                stable += 1
                if stable >= settle:
                    world = sorted(mem)
                    break
            else:
                stable = 0
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"elastic barrier gen {gen} timed out: acks={sorted(acks)}"
                    f" members={sorted(mem)}")
            time.sleep(self.poll)
        if self.rank == world[0]:
            if purge_cb is not None:
                purge_cb(world)
            self.store.put(self._k("world", gen), world)
        else:
            while self.published_world(gen) is None:
                g2 = self.stored_gen()
                if g2 > gen:
                    raise WorldChanged(g2)
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"elastic barrier gen {gen}: leader never published")
                time.sleep(self.poll)
            world = self.published_world(gen)
        from ... import simulator
        simulator.reset_seqs()
        return world

    def decide(self, gen, key, fn, timeout=30.0):
        """Single-writer agreement helper: the gen's world leader computes
        ``fn()`` and publishes it; everyone else polls the published
        value."""
        world = self.published_world(gen) or []
        if world and self.rank == world[0]:
            val = fn()
            self.store.put(self._k(key, gen), val)
            return val
        deadline = time.monotonic() + timeout
        while True:
            v = self.store.get(self._k(key, gen))
            if v is not None:
                return v
            if time.monotonic() > deadline:
                raise TimeoutError(f"elastic decide({key}, gen {gen}) "
                                   "timed out")
            time.sleep(self.poll)


class WorldChanged(RuntimeError):
    """A newer generation was proposed while this rank was mid-protocol."""

    def __init__(self, gen):
        self.gen = gen
        super().__init__(f"world superseded by generation {gen}")


# ---------------------------------------------------------------------------
# the elastic train loop
# ---------------------------------------------------------------------------


def _env_on(name, default="1"):
    return os.environ.get(name, default) not in ("0", "false", "False", "no")


class ElasticTrainLoop(TrainingSupervisor):
    """In-run elastic training: survive rank death by shrinking the mesh
    to the survivors, restoring from the latest complete checkpoint, and
    resuming deterministically; re-admit ranks at checkpoint boundaries.

    Contract (per rank, typically under ``dist.spawn``)::

        loop = ElasticTrainLoop(ckpt_dir, store=MemKVStore(), ...)
        result = loop.run(build_fn, data_fn, total_steps)

    * ``build_fn() -> (model, optimizer, loss_fn)`` — deterministic
      same-seed construction on every rank (replicated-params DP).
    * ``data_fn(step) -> (x, y)`` — the GLOBAL numpy batch for ``step``,
      identical on every rank; the loop row-splits it across the live
      world by *position*, so a given world size always sees the same
      shards regardless of which global ranks survived — this is what
      makes a post-shrink trajectory bit-match a fresh restart on the
      same world size.

    Failure → shrink: a dead peer surfaces as ``simulator.RankFailure``
    (structured: rank/seq/op) out of ``backward()``/``opt.step()``; the
    survivor marks it dead in the KV store, proposes the next
    generation, meets the others at the barrier (the leader purges
    rendezvous state and orphaned checkpoint tmps), rebuilds
    model/optimizer/comm on the survivor world, restores the latest
    complete checkpoint, and replays from its step. Regrow: a rejoining
    rank proposes a generation; running ranks notice at their next
    checkpoint boundary and rebuild the same way.

    Checkpoints are written by world position 0 only, asynchronously by
    default (``save_async``; ``sharded_checkpoint=True`` routes through
    ``distributed.checkpoint`` for true per-shard restore-and-reshard).
    ``PADDLE_ELASTIC=0`` disables in-run shrink (failures re-raise —
    the classic supervisor restart path); ``PADDLE_CKPT_INTERVAL_STEPS``
    sets the default checkpoint cadence.
    """

    def __init__(self, checkpoint_dir, store=None, job_id="elastic-train",
                 ckpt_interval=None, keep=3, max_restarts=8, min_ranks=1,
                 ttl=5.0, barrier_timeout=60.0, async_checkpoint=True,
                 sharded_checkpoint=False):
        super().__init__(checkpoint_dir, max_restarts=max_restarts, keep=keep)
        if store is None:
            from .tcp_kv import MemKVStore
            store = MemKVStore()
        self.store = store
        self.job_id = job_id
        if ckpt_interval is None:
            ckpt_interval = int(os.environ.get("PADDLE_CKPT_INTERVAL_STEPS",
                                               "10"))
        self.ckpt_interval = int(ckpt_interval)
        self.min_ranks = int(min_ranks)
        self.ttl = float(ttl)
        self.barrier_timeout = float(barrier_timeout)
        self.async_checkpoint = bool(async_checkpoint)
        self.sharded_checkpoint = bool(sharded_checkpoint)

    # -- internals -----------------------------------------------------------
    def _events(self):
        return _ckpt_telemetry()["events"]

    def _purge_cb(self, ew):
        def purge(world):
            from ... import simulator
            w = simulator.active_world()
            if w is not None:
                w.rendezvous.purge()
            self.ckpt.sweep_orphans()
        return purge

    def _save_checkpoint(self, step, model, opt, world, pos):
        if pos != 0:
            return
        state = {"model": model.state_dict(), "opt": opt.state_dict(),
                 "step": step, "world": list(world)}
        self._events().inc(kind="checkpoint")
        if self.sharded_checkpoint:
            self.ckpt.save_sharded(step, state,
                                   async_save=self.async_checkpoint)
        elif self.async_checkpoint:
            self.ckpt.save_async(step, state)
        else:
            self.ckpt.save(step, state)

    def _restore(self, model, opt, step):
        from ....profiler import flight_recorder as _flight
        if self.sharded_checkpoint:
            template = {"model": model.state_dict(),
                        "opt": opt.state_dict(),
                        "step": 0, "world": []}
            _, state = self.ckpt.load_sharded(template, step=step)
        else:
            _, state = self.ckpt.load(step=step)
        model.set_state_dict(state["model"])
        opt.set_state_dict(state["opt"])
        self._events().inc(kind="restore")
        _flight.record_event("elastic_restore", step=step)
        return int(state.get("step", step))

    # -- the loop ------------------------------------------------------------
    def run(self, build_fn, data_fn, total_steps, restore_step=None):
        import numpy as np

        from ....framework.core import Tensor
        from ....profiler import flight_recorder as _flight
        from ... import collective, fault as _fault, simulator
        from ...parallel import DataParallel
        from ...parallel_env import get_rank
        from ...simulator import RankFailure, SimulatedRankKill

        rank = get_rank()
        ew = ElasticWorld(self.store, self.job_id, rank=rank, ttl=self.ttl)
        ew.join()
        gen = ew.stored_gen()
        initial_gen = gen
        pub = ew.published_world(gen)
        if pub is not None and rank not in pub:
            # late join (regrow / scale-out): force a rebuild everyone
            # will meet at their next checkpoint boundary
            gen = ew.propose(gen + 1)
            self._events().inc(kind="regrow")
        losses: dict = {}
        last_step = 0
        elastic_on = _env_on("PADDLE_ELASTIC")

        while True:
            try:
                world = ew.agree(gen, purge_cb=self._purge_cb(ew),
                                 timeout=self.barrier_timeout)
            except WorldChanged as wc:
                gen = wc.gen
                continue
            if len(world) < self.min_ranks:
                ew.leave()
                raise RuntimeError(
                    f"elastic world shrank to {world} (< min_ranks="
                    f"{self.min_ranks}); giving up")
            pos = world.index(rank)
            nworld = len(world)
            group = collective.new_group(world)
            model, opt, loss_fn = build_fn()
            dp = DataParallel(model, group=group)
            target = ew.decide(
                gen, "restore",
                lambda: (restore_step
                         if (restore_step is not None
                             and gen == initial_gen)
                         else (self.ckpt.latest_step() or -1)),
                timeout=self.barrier_timeout)
            start = 0
            if target is not None and int(target) >= 0:
                start = self._restore(model, opt, int(target))
            _flight.record_event("elastic_world", world=list(world),
                                 generation=gen, start_step=start)
            rebuild = None
            try:
                s = start
                while s < total_steps:
                    _fault.check_step(s)
                    last_step = s
                    xg, yg = data_fn(s)
                    xs = np.array_split(np.asarray(xg), nworld)
                    ys = np.array_split(np.asarray(yg), nworld)
                    loss = loss_fn(dp(Tensor(xs[pos])), Tensor(ys[pos]))
                    loss.backward()
                    opt.step()
                    opt.clear_grad()
                    losses[s] = float(np.asarray(loss.numpy()))
                    _flight.heartbeat()
                    s += 1
                    if self.ckpt_interval and s % self.ckpt_interval == 0 \
                            and s < total_steps:
                        self._save_checkpoint(s, model, opt, world, pos)
                        ew.publish_progress(s)
                        g2 = ew.stored_gen()
                        if g2 > gen:
                            rebuild = g2     # regrow/admin world change
                            break
                if rebuild is None:
                    dp.shutdown()
                    self.ckpt.wait_pending()
                    ew.publish_progress(total_steps)
                    ew.leave()
                    return {"status": "done", "rank": rank,
                            "world": world, "generation": gen,
                            "losses": losses}
                # world change at a checkpoint boundary
                dp.shutdown()
                self.ckpt.wait_pending()
                gen = rebuild
                self._events().inc(kind="regrow")
                _flight.record_event("elastic_regrow", generation=gen)
                continue
            except SimulatedRankKill:
                # this rank IS the casualty: it is already marked dead in
                # the simulator (fault.py); make the KV view agree and
                # unwind without touching the world
                try:
                    dp.shutdown()
                except Exception:
                    pass
                ew.die()
                return {"status": "killed", "rank": rank,
                        "step": last_step, "losses": losses}
            except (RankFailure, TimeoutError) as e:
                try:
                    dp.shutdown()
                except Exception:
                    pass
                failed = getattr(e, "rank", None)
                if failed == rank:
                    # a kill on one of our own overlap lanes can surface
                    # as a RankFailure naming US (the lane that got the
                    # injected kill marked this rank dead; a sibling lane
                    # then saw the death first): this rank is the
                    # casualty, not a survivor
                    ew.die()
                    return {"status": "killed", "rank": rank,
                            "step": last_step, "losses": losses}
                if failed is None:
                    # bare timeout: fall back to the membership-TTL and
                    # simulator-death signals for attribution
                    w = simulator.active_world()
                    stale = (set(w.dead_ranks) if w is not None else set()) \
                        | ew.stale_members()
                    stale &= set(world)
                    stale.discard(rank)
                    if not stale or not elastic_on:
                        ew.leave()
                        raise
                    failed_set = stale
                else:
                    failed_set = {failed}
                if not elastic_on:
                    ew.leave()
                    raise
                self.restarts += 1
                if self.restarts > self.max_restarts:
                    ew.leave()
                    raise
                self._events().inc(kind="failure_detected")
                _flight.record_event(
                    "elastic_rank_failure", failed=sorted(failed_set),
                    seq=getattr(e, "seq", None), op=getattr(e, "op", None),
                    detected_by=rank)
                try:
                    self.ckpt.wait_pending()
                except Exception:
                    pass               # a torn async save never completes
                for r in failed_set:
                    ew.mark_dead(r)
                gen = ew.propose(gen + 1)
                self._events().inc(kind="shrink")
                continue
