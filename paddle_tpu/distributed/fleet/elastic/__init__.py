"""Elastic training (reference: ``python/paddle/distributed/fleet/elastic/``
— ``ElasticManager``: etcd-backed membership with np range ``min:max``,
heartbeat keys with TTL, watch → rebuild endpoints → relaunch trainers;
SURVEY.md §5.3).

TPU-native: the etcd server is replaced by a pluggable KV store — default a
shared-filesystem directory (``file://``), which is what multi-host TPU pods
have (GCS/NFS); heartbeats are timestamp files with TTL. The relaunch action
is the launcher's checkpoint-restart loop (launch/main.py --run_mode=elastic)
plus ``TrainingSupervisor`` for in-process resume.
"""
from __future__ import annotations

import json
import os
import threading
import time

from .supervisor import (  # noqa: F401
    TrainingSupervisor, CheckpointManager, ElasticTrainLoop, ElasticWorld,
    WorldChanged,
)
from .tcp_kv import MemKVStore, TcpKVStore  # noqa: F401
from ...simulator import RankFailure  # noqa: F401 (structured detection)

ELASTIC_EXIT_CODE = 101      # reference: trainers exit with this on scale event


class FileKVStore:
    """KV + TTL heartbeat store on a shared filesystem (etcd stand-in)."""

    def __init__(self, root):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key):
        return os.path.join(self.root, key.strip("/").replace("/", "__"))

    def put(self, key, value):
        with open(self._path(key), "w") as f:
            json.dump({"value": value, "ts": time.time()}, f)

    def get(self, key):
        try:
            with open(self._path(key)) as f:
                return json.load(f)["value"]
        except (OSError, ValueError):
            return None

    def delete(self, key):
        try:
            os.remove(self._path(key))
        except OSError:
            pass

    def keys(self, prefix=""):
        pfx = prefix.strip("/").replace("/", "__")
        out = []
        for name in os.listdir(self.root):
            if name.startswith(pfx):
                out.append(name.replace("__", "/"))
        return out

    def age(self, key):
        try:
            with open(self._path(key)) as f:
                return time.time() - json.load(f)["ts"]
        except (OSError, ValueError):
            return None


def _make_store(server):
    if server is None:
        server = os.environ.get("PADDLE_ELASTIC_SERVER")
    if server is None:
        raise ValueError("elastic needs a server (file:///shared/dir)")
    if server.startswith("file://"):
        return FileKVStore(server[len("file://"):])
    if server.startswith("tcp://") or server.startswith("etcd://"):
        # etcd:// accepted for reference CLI compat; served by the in-repo
        # C++ TCPStore (distributed/native/tcp_store.cpp)
        from .tcp_kv import TcpKVStore
        return TcpKVStore("tcp://" + server.split("://", 1)[1])
    raise NotImplementedError(f"elastic store scheme not supported: {server} "
                              "(TPU build: file:// shared dir or tcp:// "
                              "in-repo TCPStore)")


class ElasticManager:
    """Membership manager for one host (reference ElasticManager semantics:
    register, heartbeat with TTL, detect world change within [min_np, max_np],
    signal relaunch)."""

    def __init__(self, server=None, job_id=None, np=None, host=None,
                 heartbeat_interval=1.0, ttl=5.0):
        self.store = _make_store(server)
        self.job_id = job_id or os.environ.get("PADDLE_ELASTIC_JOB_ID",
                                               "default")
        np_spec = str(np if np is not None
                      else os.environ.get("PADDLE_ELASTIC_NP", "1"))
        if ":" in np_spec:
            lo, hi = np_spec.split(":")
            self.min_np, self.max_np = int(lo), int(hi)
        else:
            self.min_np = self.max_np = int(np_spec)
        self.host = host or os.environ.get("PADDLE_CURRENT_ENDPOINT",
                                           f"127.0.0.1:{os.getpid()}")
        self.heartbeat_interval = heartbeat_interval
        self.ttl = ttl
        self._stop = threading.Event()
        self._hb_thread = None
        self._last_world = None

    # -- membership ---------------------------------------------------------
    def _node_key(self, host=None):
        return f"{self.job_id}/nodes/{(host or self.host).replace(':', '_')}"

    def register(self):
        self.store.put(self._node_key(), self.host)
        self._last_world = self.hosts()

    def deregister(self):
        self.store.delete(self._node_key())

    def heartbeat(self):
        self.store.put(self._node_key(), self.host)

    def start(self):
        self.register()

        def beat():
            while not self._stop.wait(self.heartbeat_interval):
                self.heartbeat()

        self._hb_thread = threading.Thread(target=beat, daemon=True)
        self._hb_thread.start()

    def stop(self):
        self._stop.set()
        if self._hb_thread:
            self._hb_thread.join(timeout=2)
        self.deregister()

    def hosts(self):
        """Live hosts (heartbeat within TTL), sorted for determinism."""
        out = []
        for key in self.store.keys(f"{self.job_id}/nodes/"):
            age = self.store.age(key)
            val = self.store.get(key)
            if val is not None and age is not None and age <= self.ttl:
                out.append(val)
        return sorted(out)

    # -- scale detection ----------------------------------------------------
    def world_changed(self):
        cur = self.hosts()
        changed = cur != self._last_world
        return changed, cur

    def should_scale(self):
        """(scale_needed, healthy) — healthy iff live hosts within range."""
        cur = self.hosts()
        healthy = self.min_np <= len(cur) <= self.max_np
        changed = cur != self._last_world
        return changed and healthy, healthy

    def accept_world(self):
        """After relaunch: the current membership becomes the baseline and
        new endpoints env is produced for the launcher."""
        cur = self.hosts()
        self._last_world = cur
        return {
            "PADDLE_TRAINERS_NUM": str(len(cur)),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(cur),
        }
