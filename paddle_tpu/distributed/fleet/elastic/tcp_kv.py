"""TCP-backed elastic KV store — the native-TCPStore tier of the elastic
membership layer (reference: the etcd server behind
``python/paddle/distributed/fleet/elastic/manager.py``; here etcd's role
is played by the in-repo C++ TCPStore, ``distributed/native/tcp_store.cpp``).

Values carry a wall-clock timestamp (like FileKVStore) so the manager's
TTL heartbeat logic is store-agnostic."""
from __future__ import annotations

import json
import time


class MemKVStore:
    """In-process KV with the TcpKVStore interface — the thread-rank
    simulator tier of cross-rank aggregation (flight-recorder snapshot
    gathering in tests / single-host jobs). Values take the same JSON
    round trip as the TCP store so anything published here would also
    survive the wire."""

    def __init__(self):
        import threading
        self._lock = threading.Lock()
        self._d: dict = {}

    def put(self, key, value):
        raw = json.dumps({"value": value, "ts": time.time()})
        with self._lock:
            self._d[key] = raw

    def get(self, key):
        with self._lock:
            raw = self._d.get(key)
        if raw is None:
            return None
        try:
            return json.loads(raw)["value"]
        except ValueError:
            return None

    def delete(self, key):
        with self._lock:
            self._d.pop(key, None)

    def incr(self, key, delta=1):
        """Atomic fleet-wide counter: add ``delta`` and return the new
        value. Counters share the key space with put/get (the value is a
        plain int, readable by ``get``); the whole read-modify-write runs
        under the store lock so concurrent increments never lose."""
        with self._lock:
            raw = self._d.get(key)
            cur = 0
            if raw is not None:
                try:
                    cur = int(json.loads(raw)["value"])
                except (ValueError, TypeError):
                    cur = 0
            cur += int(delta)
            self._d[key] = json.dumps({"value": cur, "ts": time.time()})
            return cur

    def keys(self, prefix=""):
        with self._lock:
            return [k for k in self._d if k.startswith(prefix)]

    def age(self, key):
        with self._lock:
            raw = self._d.get(key)
        try:
            return time.time() - json.loads(raw)["ts"]
        except (TypeError, ValueError):
            return None

    def close(self):
        pass


class TcpKVStore:
    """FileKVStore-interface adapter over ``distributed.native.TCPStore``.

    ``spec``: ``tcp://host:port`` — the first manager to bind the port
    becomes the server (etcd stand-in); everyone else connects as client.
    """

    def __init__(self, spec):
        import socket
        from ...native import TCPStore
        hostport = spec[len("tcp://"):]
        host, _, port = hostport.partition(":")
        host = host or "127.0.0.1"
        port = int(port or 0)
        # only a node the spec actually names may serve (binding the port
        # on an unrelated machine would create a phantom empty store)
        local_names = {"127.0.0.1", "localhost", "0.0.0.0",
                       socket.gethostname()}
        try:
            local_names.add(socket.gethostbyname(socket.gethostname()))
        except OSError:
            pass
        self._store = None
        if host in local_names:
            try:
                self._store = TCPStore(host="127.0.0.1", port=port,
                                       is_master=True)
            except RuntimeError:
                pass             # port taken: a peer manager is serving
        if self._store is None:
            self._store = TCPStore(host=host, port=port, is_master=False)

    def put(self, key, value):
        self._store.set(key, json.dumps({"value": value,
                                         "ts": time.time()}))

    def get(self, key):
        try:
            raw = self._store.get(key, wait=False)
        except KeyError:
            return None
        try:
            return json.loads(raw.decode())["value"]
        except (ValueError, UnicodeDecodeError, TypeError):
            # counter keys (see incr) hold the native ADD op's raw
            # little-endian int64, not the JSON envelope
            if len(raw) == 8:
                return int.from_bytes(raw, "little", signed=True)
            return None

    def incr(self, key, delta=1):
        """Atomic fleet-wide counter via the native TCPStore ADD op —
        the server applies the add under its own lock, so increments
        from any number of clients/hosts never lose. NB: the stored
        representation is a raw int64 (``get`` reads it back as an int,
        ``age`` has no timestamp for it); don't mix ``put`` and ``incr``
        on the same key."""
        return int(self._store.add(key, int(delta)))

    def delete(self, key):
        self._store.delete_key(key)

    def keys(self, prefix=""):
        return self._store.keys(prefix)

    def age(self, key):
        try:
            raw = self._store.get(key, wait=False)
            return time.time() - json.loads(raw.decode())["ts"]
        except (KeyError, ValueError, UnicodeDecodeError, TypeError):
            return None

    def close(self):
        self._store.close()
