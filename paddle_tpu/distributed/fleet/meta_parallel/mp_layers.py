"""Megatron-style tensor-parallel layers (reference:
``python/paddle/distributed/fleet/layers/mpu/mp_layers.py`` —
``VocabParallelEmbedding``, ``ColumnParallelLinear``, ``RowParallelLinear``,
``ParallelCrossEntropy``; and ``mp_ops.py`` ``_c_identity``/``_c_split``/
``_mp_allreduce``/``_c_softmax_with_cross_entropy``; SURVEY.md §2.3 "TP/MP").

TPU-native (SURVEY.md §7.1 M4): the reference implements TP with explicit
collective ops — identity-fwd/allreduce-bwd around column layers,
allreduce-fwd/identity-bwd after row layers, a masked lookup + allreduce for
the vocab-parallel embedding, and a dedicated vocab-parallel softmax-CE
kernel. Here each layer simply *shards its weight over the mp mesh axis*
(column → P(None, 'mp'), row → P('mp', None), vocab → P('mp', None)) and
computes with plain ops: XLA's SPMD partitioner derives exactly those
collectives (partial-sum matmul → psum; sharded-vocab gather → masked
lookup + psum), fused into the surrounding program. Losses are numerically
identical to the unsharded model — the parity contract the reference tests
via ``hybrid_parallel_mp_layers.py``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....framework.core import Tensor
from ....autograd.tape import apply
from ....nn.layer import Layer
from ....nn import functional as F
from ....nn.initializer import XavierUniform, Constant
from ... import mesh as mesh_mod


def _place_param(p, spec):
    """Shard a parameter over the global mesh; records the spec for the
    train-step engine (engine.py) and checkpointing."""
    p._sharding_spec = tuple(spec)
    mesh = mesh_mod.get_mesh()
    if len(mesh.devices.flat) > 1 and not isinstance(p._data, jax.core.Tracer):
        p._data = jax.device_put(p._data, mesh_mod.sharding(*spec))
    return p


def reshard(x, *spec):
    """Differentiable resharding of a Tensor over the mesh (device_put on
    concrete arrays, with_sharding_constraint under tracing)."""
    sh = mesh_mod.sharding(*spec)

    def fn(a):
        if isinstance(a, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(a, sh)
        return jax.device_put(a, sh)

    return apply(fn, x, op_name="reshard")


def mp_degree():
    return mesh_mod.axis_size("mp")


class ColumnParallelLinear(Layer):
    """Weight [in, out] sharded on the output (column) dim over 'mp'."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.gather_output = gather_output
        self.world_size = mp_degree()
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        _place_param(self.weight, (None, "mp"))
        self.weight.is_distributed = self.world_size > 1
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            _place_param(self.bias, ("mp",))
            self.bias.is_distributed = self.world_size > 1
        else:
            self.bias = None

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output and self.world_size > 1:
            y = reshard(y, *([None] * y.ndim))
        return y


class RowParallelLinear(Layer):
    """Weight [in, out] sharded on the input (row) dim over 'mp'; the matmul's
    partial sums are combined by an XLA-inserted psum (the reference's
    explicit ``mp_allreduce``)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.world_size = mp_degree()
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        _place_param(self.weight, ("mp", None))
        self.weight.is_distributed = self.world_size > 1
        if has_bias:
            # bias applies after the reduction → replicated
            self.bias = self.create_parameter([out_features], is_bias=True)
            _place_param(self.bias, (None,))
        else:
            self.bias = None

    def forward(self, x):
        if not self.input_is_parallel and self.world_size > 1:
            # split the contraction dim over mp (reference _c_split)
            spec = [None] * x.ndim
            spec[-1] = "mp"
            x = reshard(x, *spec)
        y = F.linear(x, self.weight, None)
        if self.world_size > 1:
            y = reshard(y, *([None] * y.ndim))
        if self.bias is not None:
            y = y + self.bias
        return y


class VocabParallelEmbedding(Layer):
    """Weight [vocab, dim] sharded on the vocab dim over 'mp'. The sharded
    gather lowers to the reference's masked-lookup + psum (``c_embedding``)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.world_size = mp_degree()
        from ....nn.initializer import Normal
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=Normal(0.0, 1.0))
        _place_param(self.weight, ("mp", None))
        self.weight.is_distributed = self.world_size > 1

    def forward(self, x):
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(Layer):
    """Softmax cross-entropy over mp-sharded logits (reference
    ``c_softmax_with_cross_entropy``: avoids materialising the full logits;
    here the sharded logsumexp/gather keep the vocab dim sharded and XLA
    reduces partial max/sum over mp)."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        def fn(logits, lab):
            lse = jax.scipy.special.logsumexp(
                logits.astype(jnp.float32), axis=-1, keepdims=True)
            logp = logits.astype(jnp.float32) - lse
            lab2 = lab if lab.ndim == logp.ndim else lab[..., None]
            picked = jnp.take_along_axis(logp, lab2.astype(jnp.int32), axis=-1)
            loss = -picked
            if self.ignore_index >= 0:
                loss = jnp.where(lab2 == self.ignore_index, 0.0, loss)
            return loss

        return apply(fn, input, label, op_name="parallel_cross_entropy")


# functional mp_ops compat (reference mpu/mp_ops.py)
def _c_identity(x, group=None):
    return x


def _c_concat(x, group=None):
    return reshard(x, *([None] * x.ndim))


def _c_split(x, group=None):
    spec = [None] * x.ndim
    spec[-1] = "mp"
    return reshard(x, *spec)


def _mp_allreduce(x, op=None, group=None, use_calc_stream=True,
                  use_model_parallel=True):
    return reshard(x, *([None] * x.ndim))
