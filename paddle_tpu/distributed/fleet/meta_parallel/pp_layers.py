"""Pipeline model description (reference: ``python/paddle/distributed/fleet/
meta_parallel/parallel_layers/pp_layers.py`` — ``PipelineLayer`` partitions a
layer list into stages (uniform or by-parameter-count), ``LayerDesc`` defers
construction, ``SharedLayerDesc`` ties weights (embeddings) across stages;
SURVEY.md §2.3 "PP").

TPU-native: a single controller owns every stage, so "stage placement" is a
*sharding decision*, not process placement — the jitted engine
(distributed/engine.py) stacks homogeneous stage weights on a leading pp-
sharded axis and pipelines microbatches with ``ppermute`` (SURVEY.md §7.1
M4); eagerly, stages just run in order. Tied weights are literally the same
Parameter object — no tied-grad allreduce needed.
"""
from __future__ import annotations

import numpy as np

from ....nn.layer import Layer, Sequential
from ... import mesh as mesh_mod


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_cls, Layer):
            raise TypeError(f"{layer_cls} must be a paddle.nn.Layer subclass")

    def build_layer(self):
        return self.layer_cls(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """A layer whose weights are shared across pipeline stages (tied
    embeddings). ``shared_weight_attr`` names the tied parameter."""

    def __init__(self, key, layer_cls, *inputs, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, recompute_ctx=None,
                 num_virtual_pipeline_stages=None):
        super().__init__()
        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        self._num_stages = num_stages or mesh_mod.axis_size("pp")
        self._seg_method = seg_method
        self._vpp = int(num_virtual_pipeline_stages or 1)
        self.layers_desc = list(layers)
        self._shared_layers = {}  # key -> first-built instance
        built = []
        for d in self.layers_desc:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared_layers:
                    first = self._shared_layers[d.layer_name]
                    inst = d.build_layer()
                    # tie: point the shared parameter at the SAME object
                    shared_p = getattr(first, d.shared_weight_attr)
                    setattr(inst, d.shared_weight_attr, shared_p)
                    inst._shared_forward = d.forward_func
                    built.append(inst)
                else:
                    inst = d.build_layer()
                    inst._shared_forward = d.forward_func
                    self._shared_layers[d.layer_name] = inst
                    built.append(inst)
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            elif callable(d):
                built.append(_FnLayer(d))
            else:
                raise TypeError(f"unsupported pipeline entry {d!r}")
        self.run_function = built
        for i, l in enumerate(built):
            self.add_sublayer(str(i), l)
        self.segment_parts = self._segment(len(built), self._num_stages)

    # -- stage partition -----------------------------------------------------
    def _segment(self, n_layers, n_stages):
        if self._seg_method == "uniform" or not self._seg_method.startswith("layer:"):
            # balanced contiguous split (reference: uniform / by-params)
            base = n_layers // n_stages
            rem = n_layers % n_stages
            parts = [0]
            for s in range(n_stages):
                parts.append(parts[-1] + base + (1 if s < rem else 0))
            return parts
        # "layer:ClassName" — cut before each layer of the named class
        cls_name = self._seg_method.split(":", 1)[1]
        marks = [i for i, l in enumerate(self.run_function)
                 if type(l).__name__ == cls_name]
        if len(marks) < n_stages:
            raise ValueError(f"only {len(marks)} {cls_name} layers for "
                             f"{n_stages} stages")
        chunks = np.array_split(marks, n_stages)
        parts = [0] + [int(c[0]) for c in chunks[1:]] + [n_layers]
        return parts

    def homogeneous_run(self):
        """(lo, hi) bounds of the longest contiguous run of same-class,
        same-param-signature layers — the pipelineable block region for the
        jitted SPMD engine; layers before/after become the pre/post
        segments (reference: embedding/head stages in ``pp_layers.py``)."""
        def sig(l):
            return tuple((tuple(p.shape), str(p.dtype))
                         for p in l.parameters())

        best = (0, 0)
        i, n = 0, len(self.run_function)
        while i < n:
            j = i + 1
            cls, s0 = type(self.run_function[i]), sig(self.run_function[i])
            while j < n and type(self.run_function[j]) is cls \
                    and sig(self.run_function[j]) == s0:
                j += 1
            if j - i > best[1] - best[0]:
                best = (i, j)
            i = j
        return best

    def get_stage_layers(self, stage_id):
        lo, hi = self.segment_parts[stage_id], self.segment_parts[stage_id + 1]
        return self.run_function[lo:hi]

    def stage_of_layer(self, idx):
        for s in range(self._num_stages):
            if self.segment_parts[s] <= idx < self.segment_parts[s + 1]:
                return s
        return self._num_stages - 1

    # -- forward (runs every stage; the pipelined schedule lives in
    #    PipelineParallel.train_batch / the jitted engine) -------------------
    def forward(self, x, chunk_id=None):
        for l in self.run_function:
            fwd = getattr(l, "_shared_forward", None)
            x = fwd(l, x) if fwd is not None else l(x)
        return x


class _FnLayer(Layer):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, x):
        return self._fn(x)
