"""RNG state tracking across model-parallel regions (reference:
``python/paddle/distributed/fleet/layers/mpu/random.py`` —
``RNGStatesTracker`` keeps named per-group RNG states so dropout inside the
mp region differs per rank while dropout outside is identical; SURVEY.md
§2.3 "TP/MP").

TPU-native: JAX keys are explicit, so a "state" is a (root_key, counter)
pair in the hidden default generator (framework/random.py). ``rng_state``
swaps in a named state derived by folding the axis index into the seed —
in mesh mode the fold happens automatically when dropout's key feeds a
sharded op, so the tracker mainly preserves the reference's determinism
contract: same name → same key sequence.
"""
from __future__ import annotations

import contextlib

import jax

from ....framework import random as prandom

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.seeds_.add(seed)
        self.states_[name] = (jax.random.key(seed), 0)

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = dict(states)

    @contextlib.contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        gen = prandom.default_generator()
        orig = (gen._root, gen._counter)
        gen._root, gen._counter = self.states_[name]
        try:
            yield
        finally:
            self.states_[name] = (gen._root, gen._counter)
            gen._root, gen._counter = orig


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed=None):
    import random as pyrandom
    seed = seed if seed is not None else pyrandom.randint(0, 2 ** 31 - 1)
    global_seed = seed
    local_seed = seed + 1024  # offset per reference convention (mp-rank fold
    # is implicit in mesh mode — sharded dropout masks differ per shard)
    _RNG_STATE_TRACKER.reset()
    prandom.seed(global_seed)
    _RNG_STATE_TRACKER.add(MODEL_PARALLEL_RNG, local_seed)
