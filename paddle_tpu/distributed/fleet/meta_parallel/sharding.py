"""ZeRO-style sharded training (reference: stage-1
``dygraph_sharding_optimizer.py``; stage-2 ``group_sharded_stage2.py`` +
``group_sharded_optimizer_stage2.py``; stage-3 ``group_sharded_stage3.py``;
SURVEY.md §2.3).

TPU-native (SURVEY.md §7.1 M4): ZeRO's manual machinery — per-rank param
ownership tables, reduce-scatter hooks in backward, pre-forward allgather +
post-use release — is exactly what XLA's SPMD partitioner derives from a
*sharding annotation on the state*:

* stage 1/2: optimizer slots (and grads, inside the jitted step) carry a
  sharding over the 'sharding' axis → XLA emits reduce-scatter for grads and
  keeps moment math local to the owner shard.
* stage 3: the parameters themselves are sharded at rest; every use inside
  a step triggers an allgather XLA schedules (and frees) itself.

Eagerly this module places arrays with those shardings (correctness +
memory at rest); the jitted engine threads the same specs through
``jit`` in/out shardings for the perf path.
"""
from __future__ import annotations

import contextlib

import jax

from ....framework.core import Parameter
from ... import mesh as mesh_mod


def shard_spec_for(shape, axis="sharding", existing=None):
    """Shard the largest dim divisible by the axis size; else replicate.

    ``existing``: a PartitionSpec-like tuple already on the tensor (e.g.
    the mp placement of a Column/RowParallelLinear or vocab-parallel
    embedding weight). Dims it occupies are excluded and its entries are
    PRESERVED in the returned spec — ZeRO-3 must compose with, never
    clobber, the tensor-parallel layout."""
    n = mesh_mod.axis_size(axis)
    if n <= 1:
        return None
    taken = list(existing) + [None] * (len(shape) - len(existing)) \
        if existing is not None else [None] * len(shape)
    flat_taken = [a for t in taken if t is not None
                  for a in (t if isinstance(t, tuple) else (t,))]
    if axis in flat_taken:
        return tuple(taken)      # already sharded over this axis — keep
    dims = sorted(range(len(shape)), key=lambda i: -shape[i])
    for d in dims:
        if taken[d] is None and shape[d] % n == 0 and shape[d] >= n:
            spec = list(taken)
            spec[d] = axis
            return tuple(spec)
    return None


def _existing_spec(arr):
    """The PartitionSpec already placed on ``arr`` (None if uncommitted,
    single-device, or fully replicated)."""
    sh = getattr(arr, "sharding", None)
    spec = getattr(sh, "spec", None)
    if spec is None or all(s is None for s in spec):
        return None
    return tuple(spec)


def _place(arr, spec):
    if spec is None or isinstance(arr, jax.core.Tracer):
        return arr
    return jax.device_put(arr, mesh_mod.sharding(*spec))


class DygraphShardingOptimizer:
    """Stage 1: optimizer-state sharding. Wraps an inner Optimizer; slots are
    placed sharded over the 'sharding' axis after creation (reference: each
    rank updates its shard then broadcasts — here the broadcast is XLA's)."""

    def __init__(self, optimizer, hcg=None, comm_config=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._sharded = set()
        # gradient-communication policy for the per-rank tiers (bucketed /
        # quantized exchange); None → read the fleet strategy lazily
        self._comm_config = comm_config
        self._comm_bucketer = None

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def _shard_new_slots(self):
        for p in self._inner_opt._parameter_list:
            key = id(p)
            slots = self._inner_opt._slots.get(key)
            if slots is None or key in self._sharded:
                continue
            for name, arr in slots.items():
                spec = shard_spec_for(arr.shape,
                                      existing=_existing_spec(arr))
                slots[name] = _place(arr, spec)
            self._sharded.add(key)

    def step(self):
        self._inner_opt.step()
        self._shard_new_slots()

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()
        self._inner_opt.clear_grad()
        return None, None


class GroupShardedOptimizerStage2(DygraphShardingOptimizer):
    """Stage 2 = stage 1 + grad sharding. Eagerly grads live transiently; the
    reduce-scatter happens inside the jitted step (engine.py threads grad
    shardings); the eager wrapper additionally places grads sharded before
    the update to bound peak memory.

    In per-rank execution (thread simulator / one process per host) the
    ZeRO-2 wire pattern runs explicitly through ``distributed.comm``: a
    bucketed (optionally quantized) reduce-scatter — each rank reduces its
    shard — followed by an all-gather of the shards, so the eager update
    below still sees the full reduced gradient. With
    ``DistributedStrategy.comm_overlap`` (the default) each bucket's
    reduce-scatter dispatches the moment its last gradient lands in
    backward (tape grad-ready hooks); ``step()`` waits only on the
    in-flight handles."""

    _overlap_sched = None
    _overlap_cb = None

    def __init__(self, optimizer, hcg=None, comm_config=None):
        super().__init__(optimizer, hcg, comm_config)
        self._maybe_install_overlap()

    def _per_rank_tier(self):
        import jax
        from ... import simulator
        from ...parallel_env import get_world_size
        if simulator.active_world() is None and jax.process_count() <= 1:
            return False
        return get_world_size() > 1

    def _build_bucketer(self, params):
        from ...comm import GradientBucketer, comm_config_from_strategy
        cfg = self._comm_config
        if cfg is None:
            from .. import get_strategy
            cfg = comm_config_from_strategy(get_strategy())
        return GradientBucketer(params, **cfg)

    def _maybe_install_overlap(self):
        """Called once from step-0 OR lazily at the first grad-ready event:
        the stage-2 wrapper is built inside the rank context, so the hook
        registers on the right thread."""
        if self._overlap_cb is not None:
            return
        from .. import get_strategy
        if not getattr(get_strategy(), "comm_overlap", True):
            self._overlap_cb = False
            return
        if not self._per_rank_tier():
            self._overlap_cb = False
            return
        import weakref
        from ....autograd import tape
        ref = weakref.ref(self)

        def _ready(t):
            opt = ref()
            if opt is None:
                tape.unregister_grad_ready_callback(_ready)
                return
            opt._on_grad_ready(t)

        self._overlap_cb = tape.register_grad_ready_callback(_ready)

    def _on_grad_ready(self, t):
        sched = self._overlap_sched
        if sched is None:
            params = [p for p in self._inner_opt._parameter_list
                      if p is not None]
            if not params:
                return
            from ...collective import ReduceOp
            from ...comm import ReadyBucketScheduler
            sched = self._overlap_sched = ReadyBucketScheduler(
                self._build_bucketer(params), name="sharding2",
                op=ReduceOp.AVG, use_reduce_scatter=True)
        sched.mark_ready(t)

    def _maybe_exchange_grads(self):
        if not self._per_rank_tier():
            return
        params = [p for p in self._inner_opt._parameter_list
                  if p is not None]
        if not any(getattr(p, "grad", None) is not None for p in params):
            return
        sched = self._overlap_sched
        if sched is not None:
            if sched.matches(params):
                sched.finish()
                return
            sched.close()
            self._overlap_sched = None      # layout changed — rebuild
        from ...collective import ReduceOp
        b = self._comm_bucketer
        if b is None or [id(p) for p in b._params] != [id(p) for p in params]:
            b = self._comm_bucketer = self._build_bucketer(params)
        b.sync_grads(op=ReduceOp.AVG, use_reduce_scatter=True)

    def step(self):
        self._maybe_exchange_grads()
        for p in self._inner_opt._parameter_list:
            if p.grad is not None:
                spec = shard_spec_for(p.grad._data.shape,
                                      existing=_existing_spec(p.grad._data))
                p.grad._data = _place(p.grad._data, spec)
        super().step()


class GroupShardedStage2:
    """Model wrapper for stage 2 (API parity with ``GroupShardedStage2``).

    Forward delegates; opt-state sharding is the optimizer wrapper's job.
    The ZeRO-2 memory contract — grads sharded AS they are produced, not
    at step() — is enforced with per-parameter grad hooks: each cotangent
    is placed on its 'sharding'-axis layout the moment the tape
    accumulates it (the reference's backward reduce-scatter hook,
    ``group_sharded_stage2.py``'s _grad_storage path)."""

    def __init__(self, layer, optimizer, group=None, sync_buffers=False,
                 buffer_max_size=2 ** 23, auto_refresh_trainable=True,
                 device="tpu", dp_group=None):
        self._layer = layer
        self._optimizer = optimizer
        self._hooks = []
        for p in layer.parameters():
            if p is None:
                continue
            spec = shard_spec_for(p._data.shape,
                                  existing=_existing_spec(p._data))
            if spec is not None:
                self._hooks.append(p.register_hook(
                    lambda g, _spec=spec: _place_tensor(g, _spec)))

    def __call__(self, *a, **k):
        return self._layer(*a, **k)

    def __getattr__(self, item):
        return getattr(self._layer, item)


def _place_tensor(g, spec):
    data = g._data if hasattr(g, "_data") else g
    placed = _place(data, spec)
    if hasattr(g, "_data"):
        g._data = placed
        return g
    return placed


class GroupShardedStage3:
    """Stage 3 / FSDP: parameters sharded at rest over the 'sharding' axis.
    Every eager/jitted use allgathers on demand (XLA inserts + frees);
    ``state_dict`` gathers transparently via device_get."""

    def __init__(self, layer, optimizer=None, group=None, sync_buffers=False,
                 device="tpu", segment_size=2 ** 20, pertrain_sync_models=True,
                 offload=False, sync_comm=False, dp_group=None,
                 exclude_layer=None):
        self._layer = layer
        self._optimizer = optimizer
        self._offload = False
        self._offload_params = []
        for p in layer.parameters():
            if p is None:
                continue
            spec = shard_spec_for(p._data.shape,
                                  existing=_existing_spec(p._data))
            if spec is not None:
                p._sharding_spec = spec
                p._data = _place(p._data, spec)
                p.is_distributed = True
        if offload:
            # Host-resident shards (reference ``offload=True``: params
            # live in CPU memory between uses, streamed in per step).
            # TPU-native: KEEP the sharded layout, move the residence to
            # host memory via the sharding's memory kind; every __call__
            # fetches device-resident copies for the step and re-homes
            # afterwards. The host sharding recorded here stays the
            # authority — values written elsewhere (an external
            # optimizer.step) go home at the next forward.
            staged = []
            try:
                for p in layer.parameters():
                    if p is None or getattr(p, "_data", None) is None:
                        continue
                    if getattr(p, "_sharding_spec", None) is not None:
                        sh = p._data.sharding
                    else:
                        # replicate small/undivisible params over the SAME
                        # mesh — a committed single-device residence would
                        # clash with mesh-sharded operands in one op
                        sh = mesh_mod.replicated()
                    host = sh.with_memory_kind("pinned_host")
                    staged.append((p, jax.device_put(p._data, host),
                                   sh.with_memory_kind("device"), host))
            except Exception as e:
                # nothing was mutated yet — the layer stays fully usable
                raise NotImplementedError(
                    "sharding stage-3 offload needs host memory-kind "
                    f"support in the backend (got: {e!r}); rerun with "
                    "offload=False") from e
            for p, host_arr, dev_sh, host_sh in staged:
                p._data = host_arr
                self._offload_params.append((p, dev_sh, host_sh))
            self._offload = True
            if optimizer is not None:
                # eagerly re-home after each step so the host copy is
                # fresh the moment checkpointing/state_dict reads it
                orig_step = optimizer.step

                def step_and_rehome(*a, **k):
                    out = orig_step(*a, **k)
                    self._rehome()
                    return out

                optimizer.step = step_and_rehome

    def _rehome(self):
        """Move current param values to their recorded host residence."""
        for p, _, host_sh in self._offload_params:
            if p._data.sharding != host_sh:
                p._data = jax.device_put(p._data, host_sh)

    @contextlib.contextmanager
    def _fetched(self):
        """Context: device-resident copies of offloaded params for one
        step; the recorded host shardings stay authoritative and current
        values are re-homed after."""
        if not self._offload:
            yield
            return
        self._rehome()   # external updates since the last step go home
        for p, dev_sh, _ in self._offload_params:
            p._data = jax.device_put(p._data, dev_sh)
        try:
            yield
        finally:
            self._rehome()

    def __call__(self, *a, **k):
        with self._fetched():
            return self._layer(*a, **k)

    def forward(self, *a, **k):
        return self.__call__(*a, **k)

    def __getattr__(self, item):
        return getattr(self._layer, item)

    def get_all_parameters(self, convert2cpu=False):
        return list(self._layer.parameters())
