"""Pipeline-parallel training driver (reference: ``python/paddle/distributed/
fleet/meta_parallel/pipeline_parallel.py`` — ``PipelineParallel.train_batch``
runs the 1F1B schedule: warmup forwards, steady 1F1B, cooldown, with p2p
activation exchange per micro-batch via ``batch_isend_irecv``; SURVEY.md
§3.4).

TPU-native: a single controller holds all stages, so the p2p exchange
degenerates to a local hand-off and the schedule's *numerics* reduce to
micro-batch gradient accumulation — which this class implements exactly
(same losses as the reference schedule, the parity contract of
``hybrid_parallel_pp_*`` tests). The *overlap* the 1F1B schedule exists for
is recovered on TPU by the jitted shard_map+ppermute pipeline in
``paddle_tpu/distributed/engine.py`` (SURVEY.md §7.1 M4, §7.3 item 2) — XLA
schedules compute/ICI-transfer overlap there; no hand-written warmup/
cooldown bookkeeping is needed in the runtime.
"""
from __future__ import annotations

from ....framework.core import Tensor
from ....nn.layer import Layer
from ....autograd.tape import no_grad
from .pp_layers import PipelineLayer


def _split_micro(data, n):
    """Split a (possibly nested) batch into n micro-batches along dim 0."""
    if isinstance(data, (list, tuple)):
        parts = [_split_micro(d, n) for d in data]
        return [type(data)(p[i] for p in parts) for i in range(n)]
    if isinstance(data, Tensor):
        b = data.shape[0]
        if b % n != 0:
            raise ValueError(f"batch size {b} not divisible by accumulate_steps {n}")
        mb = b // n
        return [data[i * mb:(i + 1) * mb] for i in range(n)]
    return [data] * n


class PipelineParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        pp_cfg = (strategy.hybrid_configs.get("pp_configs", {})
                  if strategy is not None else {})
        self.accumulate_steps = int(pp_cfg.get("accumulate_steps", 1))
        self.schedule_mode = pp_cfg.get("schedule_mode", "1F1B")
        self.num_stages = layers._num_stages
        self._loss_fn = layers._loss_fn

    def forward(self, x):
        return self._layers(x)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """One full pipelined step: micro-batch accumulation + optimizer step.
        ``data`` = [inputs, labels] (reference contract)."""
        inputs, labels = data
        n = self.accumulate_steps
        micro_in = _split_micro(inputs, n)
        micro_lb = _split_micro(labels, n)

        total_loss = None
        for x, y in zip(micro_in, micro_lb):
            out = self._layers(x)
            loss = self._loss_fn(out, y) if self._loss_fn is not None else out
            scaled = loss / n
            if scaler is not None:
                scaled = scaler.scale(scaled)
            scaled.backward()
            with no_grad():
                total_loss = loss if total_loss is None else total_loss + loss

        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        with no_grad():
            return total_loss / n

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        with no_grad():
            out = self._layers(inputs)
            if compute_loss and self._loss_fn is not None:
                return self._loss_fn(out, labels)
            return out
