"""Pipeline-parallel training driver (reference: ``python/paddle/distributed/
fleet/meta_parallel/pipeline_parallel.py`` — ``PipelineParallel.train_batch``
runs the 1F1B schedule: warmup forwards, steady 1F1B, cooldown, with p2p
activation exchange per micro-batch via ``batch_isend_irecv``; SURVEY.md
§3.4).

TPU-native: a single controller holds all stages, so the p2p exchange
degenerates to a local hand-off and the schedule's *numerics* reduce to
micro-batch gradient accumulation — which this class implements exactly
(same losses as the reference schedule, the parity contract of
``hybrid_parallel_pp_*`` tests). The *overlap* the 1F1B schedule exists for
is recovered on TPU by the jitted shard_map+ppermute pipeline in
``paddle_tpu/distributed/engine.py`` (SURVEY.md §7.1 M4, §7.3 item 2) — XLA
schedules compute/ICI-transfer overlap there; no hand-written warmup/
cooldown bookkeeping is needed in the runtime.
"""
from __future__ import annotations

from ....framework.core import Tensor
from ....nn.layer import Layer
from ....autograd.tape import no_grad
from .pp_layers import PipelineLayer


def _split_micro(data, n):
    """Split a (possibly nested) batch into n micro-batches along dim 0."""
    if isinstance(data, (list, tuple)):
        parts = [_split_micro(d, n) for d in data]
        return [type(data)(p[i] for p in parts) for i in range(n)]
    if isinstance(data, Tensor):
        b = data.shape[0]
        if b % n != 0:
            raise ValueError(f"batch size {b} not divisible by accumulate_steps {n}")
        mb = b // n
        return [data[i * mb:(i + 1) * mb] for i in range(n)]
    return [data] * n


class PipelineParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        pp_cfg = (strategy.hybrid_configs.get("pp_configs", {})
                  if strategy is not None else {})
        self.accumulate_steps = int(pp_cfg.get("accumulate_steps", 1))
        self.schedule_mode = pp_cfg.get("schedule_mode", "1F1B")
        self.num_stages = layers._num_stages
        self._loss_fn = layers._loss_fn
        self._spmd = None        # None = undecided, False = eager fallback
        self._spmd_step = None

    def forward(self, x):
        return self._layers(x)

    # -- jitted SPMD engine dispatch ----------------------------------------
    def _spmd_module(self):
        """Build (once) the PipelinedModule when a pp mesh axis is active
        and the model qualifies (deterministic homogeneous blocks, Layer
        loss_fn, single-tensor inputs). Returns None → eager fallback."""
        if self._spmd is not None:
            return self._spmd or None
        from ... import mesh as mesh_mod
        if not (mesh_mod.has_mesh() and mesh_mod.axis_size("pp") > 1
                and isinstance(self._loss_fn, Layer)):
            return None      # undecided — a pp mesh may be installed later
        try:
            # dropout is supported: the engine threads deterministic
            # per-(microbatch, chunk) keys through the scan — remember
            # to pass a fresh base key every step
            self._needs_key = any(
                "Dropout" in type(sub).__name__ and getattr(sub, "p", 0) > 0
                for sub in self._layers.sublayers(include_self=True))
            from ....distributed.engine import PipelinedModule
            # strategy schedule_mode → engine backward schedule; VPP
            # models keep the default backward (the custom-vjp schedules
            # support vpp_degree == 1 only — rejecting at call time would
            # break interleaved models that trained fine under FThenB)
            sched = {"FThenB": "fthenb", "1F1B": "1f1b",
                     "ZBH1": "zb"}.get(str(self.schedule_mode), "fthenb")
            if getattr(self._layers, "_vpp", 1) > 1 and sched != "fthenb":
                import sys
                print(f"PipelineParallel: schedule_mode="
                      f"{self.schedule_mode} with interleaved VPP keeps "
                      "the default backward (fthenb)", file=sys.stderr)
                sched = "fthenb"
            pm = PipelinedModule(self._layers, schedule=sched)
        except ValueError as e:
            import sys
            print(f"PipelineParallel: eager fallback ({e})", file=sys.stderr)
            self._spmd = False
            return None
        self._spmd = pm
        return pm

    def _train_batch_spmd(self, pm, inputs, labels, optimizer, lr_scheduler,
                          scaler):
        """One pipelined step through the jitted ppermute engine: grads for
        every stage computed in ONE jitted SPMD program (the TPU answer to
        the reference's 1F1B send/recv loop), written back to ``.grad``,
        then the eager optimizer/scaler step off the shared tape path."""
        import jax
        import jax.numpy as jnp

        n = self.accumulate_steps
        x, y = inputs._data, labels._data
        if x.shape[0] % n != 0:
            raise ValueError(f"batch size {x.shape[0]} not divisible by "
                             f"accumulate_steps {n}")
        mb = x.shape[0] // n
        micro_x = x.reshape((n, mb) + tuple(x.shape[1:]))
        micro_y = y.reshape((n, mb) + tuple(y.shape[1:]))
        scaling = (scaler is not None and getattr(scaler, "_enable", True))
        scale = jnp.asarray(scaler._scale if scaling else 1.0, jnp.float32)

        if self._spmd_step is None:
            from ....framework.functional import FunctionalModule
            loss_fm = FunctionalModule(self._loss_fn)
            key = jax.random.PRNGKey(0)

            def step(edge, stacked, mx, my, scale, rkey):
                def scaled_loss(e, s):
                    out = pm(e, s, mx, rng_key=rkey)
                    per = jax.vmap(
                        lambda o, l: loss_fm([], [], key, o, l)[0])(out, my)
                    loss = per.mean()
                    return loss * scale.astype(loss.dtype), loss

                (_, loss), (ge, gs) = jax.value_and_grad(
                    scaled_loss, argnums=(0, 1), has_aux=True)(edge, stacked)
                return loss, ge, gs

            self._spmd_step = jax.jit(step)

        # stochastic models draw a fresh base key per step (the engine
        # derives schedule-invariant per-micro×chunk keys from it);
        # deterministic models keep a fixed key for reproducibility
        if getattr(self, "_needs_key", False):
            from ....framework import random as prandom
            rkey = prandom.next_key()
        else:
            rkey = jax.random.PRNGKey(0)
        loss, ge, gs = self._spmd_step(pm.edge_arrays(), pm.stacked_arrays(),
                                       micro_x, micro_y, scale, rkey)
        for p, g in zip(pm.edge_params, ge):
            p.grad = Tensor(g)
        for blk, gl in zip(pm.blocks, pm.unstack_grads(gs)):
            for p, g in zip(blk.parameters(), gl):
                p.grad = Tensor(g)
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return Tensor(loss)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """One full pipelined step: micro-batch accumulation + optimizer step.
        ``data`` = [inputs, labels] (reference contract). With a pp mesh
        axis active, runs the jitted SPMD ppermute schedule; otherwise the
        eager accumulation shim (numerics-identical)."""
        inputs, labels = data
        if isinstance(inputs, Tensor) and isinstance(labels, Tensor):
            pm = self._spmd_module()
            if pm is not None:
                return self._train_batch_spmd(pm, inputs, labels, optimizer,
                                              lr_scheduler, scaler)
        n = self.accumulate_steps
        micro_in = _split_micro(inputs, n)
        micro_lb = _split_micro(labels, n)

        total_loss = None
        for x, y in zip(micro_in, micro_lb):
            out = self._layers(x)
            loss = self._loss_fn(out, y) if self._loss_fn is not None else out
            scaled = loss / n
            if scaler is not None:
                scaled = scaler.scale(scaled)
            scaled.backward()
            with no_grad():
                total_loss = loss if total_loss is None else total_loss + loss

        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        with no_grad():
            return total_loss / n

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        with no_grad():
            out = self._layers(inputs)
            if compute_loss and self._loss_fn is not None:
                return self._loss_fn(out, labels)
            return out
