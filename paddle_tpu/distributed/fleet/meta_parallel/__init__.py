"""fleet.meta_parallel (reference: ``python/paddle/distributed/fleet/
meta_parallel/__init__.py``): hybrid-parallel model wrappers + mp/pp layers."""
from __future__ import annotations

from ....nn.layer import Layer
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy,
)
from .pp_layers import PipelineLayer, LayerDesc, SharedLayerDesc  # noqa: F401
from .pipeline_parallel import PipelineParallel  # noqa: F401
from .sharding import (  # noqa: F401
    DygraphShardingOptimizer, GroupShardedOptimizerStage2,
    GroupShardedStage2, GroupShardedStage3,
)
from .random import get_rng_state_tracker, RNGStatesTracker, model_parallel_random_seed  # noqa: F401
from ...parallel import DataParallel  # noqa: F401


class _MetaParallelBase(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, state, *a, **k):
        return self._layers.set_state_dict(state, *a, **k)


class TensorParallel(_MetaParallelBase):
    """mp wrapper: in the reference this broadcasts mp params within the mp
    group; in mesh mode mp params already carry their shardings — nothing to
    sync (single source of truth)."""


class ShardingParallel(_MetaParallelBase):
    """sharding-group wrapper (reference: syncs params in the sharding group)."""
