"""Megatron-style sequence parallelism (reference: ``python/paddle/
distributed/fleet/utils/sequence_parallel_utils.py`` — ``ScatterOp``/
``GatherOp``/``AllGatherOp``/``ReduceScatterOp`` on the seq dim,
``ColumnSequenceParallelLinear``/``RowSequenceParallelLinear``,
``mark_as_sequence_parallel_parameter`` + grad-allreduce hooks for
seq-parallel params (LayerNorm); SURVEY.md §5.7 mechanism 1).

TPU-native (SURVEY.md §5.7 "TPU-native plan"): SP ≡ sharding the sequence
axis of activations over the 'mp' mesh axis. The reference's four explicit
collectives (AG before column-linear, RS after row-linear, scatter/gather at
region boundaries) are the lowering XLA derives from resharding between
``P('mp', ...)`` (seq sharded) and contraction with mp-sharded weights — so
each Op here is a differentiable reshard, and the LN-param grad-allreduce
hook is unnecessary (grads of replicated params are psum'd by GSPMD).

Convention: activations are [s, b, h] inside the SP region (reference
convention), seq dim = 0.
"""
from __future__ import annotations

from ..meta_parallel.mp_layers import (
    reshard, ColumnParallelLinear, RowParallelLinear, mp_degree,
)


def _seq_spec(x, axis):
    spec = [None] * x.ndim
    spec[0] = axis
    return spec


class ScatterOp:
    """Split the seq dim over mp (fwd scatter / bwd gather)."""

    @staticmethod
    def apply(x):
        if mp_degree() <= 1:
            return x
        return reshard(x, *_seq_spec(x, "mp"))


class GatherOp:
    """Gather the seq dim (fwd allgather / bwd scatter)."""

    @staticmethod
    def apply(x):
        if mp_degree() <= 1:
            return x
        return reshard(x, *([None] * x.ndim))


class AllGatherOp(GatherOp):
    """AG before a column-parallel matmul (bwd reduce-scatter)."""


class ReduceScatterOp:
    """RS after a row-parallel matmul (bwd allgather)."""

    @staticmethod
    def apply(x):
        if mp_degree() <= 1:
            return x
        return reshard(x, *_seq_spec(x, "mp"))


def scatter(x):
    return ScatterOp.apply(x)


def all_gather(x):
    return AllGatherOp.apply(x)


def reduce_scatter(x):
    return ReduceScatterOp.apply(x)


def mark_as_sequence_parallel_parameter(parameter):
    parameter.sequence_parallel = True


def is_sequence_parallel_parameter(parameter):
    return getattr(parameter, "sequence_parallel", False)


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_sequence_parallel_allreduce=False):
    """No-op in mesh mode: grads of replicated (seq-parallel) params are
    already globally reduced by the SPMD partitioner. Kept for API parity."""
    return


class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """Column-parallel linear fed by seq-sharded activations: AG(seq) then
    matmul against the column-sharded weight (XLA derives the AG from the
    reshard)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__(in_features, out_features, weight_attr=weight_attr,
                         has_bias=has_bias, gather_output=gather_output,
                         mp_group=mp_group, name=name)

    def forward(self, x):
        x = AllGatherOp.apply(x)
        return super().forward(x)


class RowSequenceParallelLinear(RowParallelLinear):
    """Row-parallel linear whose output is reduce-scattered onto the seq dim."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__(in_features, out_features, weight_attr=weight_attr,
                         has_bias=has_bias, input_is_parallel=input_is_parallel,
                         mp_group=mp_group, name=name)

    def forward(self, x):
        y = super().forward(x)
        return ReduceScatterOp.apply(y)
