"""Ulysses (all-to-all) sequence-parallel attention — CP mechanism 2.

Reference analogue: the DeepSpeed-Ulysses-style sep parallelism the
reference ecosystem wires over its sep comm group + ``alltoall`` p2p
(SURVEY.md §5.7 mechanism 2: "all-to-all head/seq swap"), complementing
the ring rotation (mechanism 3, ``ring_attention``).

TPU-native design: inside ``shard_map`` over the 'sep' mesh axis, one
``lax.all_to_all`` re-partitions the activation from sequence-sharded
[b, s/n, h, d] to head-sharded [b, s, h/n, d] — on TPU this lowers to a
single ICI all-to-all, after which every device runs a plain full-
sequence flash attention over its head slice (exact causal masking, no
per-step rotation), and a second all-to-all swaps back. Versus the ring:

* communication is 2 all-to-alls of the activation instead of n-1
  ppermutes of K/V — cheaper when n is large or KV is wide (GQA makes
  ring cheaper: it only rotates the narrow KV heads);
* no causal load skew (the ring's late ranks do more masked work);
* requires ``heads % n == 0`` (head capacity bounds sep, the classic
  Ulysses limit), while the ring scales regardless of head count.

Gradients: ``all_to_all`` is its own transpose, so ``jax.grad`` derives
the backward swaps automatically.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ... import mesh as mesh_mod
from ....autograd.tape import apply
from ....framework.core import Tensor


def _ulysses_shard(q, k, v, *, axis_name, n, causal, interpret, use_kernel):
    """Per-device body ([b, s_local, h, d] in, same out)."""
    # seq-sharded -> head-sharded: split heads n-ways, gather full seq
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name,
                            split_axis=2, concat_axis=1, tiled=True)
    qh, kh, vh = a2a(q), a2a(k), a2a(v)      # [b, s_global, h/n, d]
    if use_kernel:
        from ....ops.pallas.flash_attention import flash_attention
        out = flash_attention(qh, kh, vh, causal=causal, interpret=interpret)
    else:
        from ....ops.pallas.flash_attention import mha_reference
        out = jnp.swapaxes(
            mha_reference(jnp.swapaxes(qh, 1, 2), jnp.swapaxes(kh, 1, 2),
                          jnp.swapaxes(vh, 1, 2), causal=causal), 1, 2)
    # head-sharded -> seq-sharded
    return jax.lax.all_to_all(out, axis_name=axis_name, split_axis=1,
                              concat_axis=2, tiled=True)


def ulysses_attention(q, k, v, causal=True, seq_axis="sep", mesh=None,
                      interpret=None, use_kernel=True):
    """All-to-all sequence-parallel attention over the mesh's ``seq_axis``.

    q/k/v: jax arrays (or Tensors), paddle layout [b, s, h, d], seq dim
    sharded over ``seq_axis``. Requires ``num_heads % axis_size == 0``
    (and ``kv_heads % axis_size == 0`` under GQA). Drop-in alternative
    to :func:`ring_attention` — same signature, same numerics.
    """
    mesh = mesh or mesh_mod.get_mesh()
    n = int(mesh.shape[seq_axis]) if seq_axis in mesh.shape else 1

    def jfn(qa, ka, va):
        if n == 1:
            from .ring_attention import ring_attention as _ring
            return _ring(qa, ka, va, causal=causal, seq_axis=seq_axis,
                         mesh=mesh, interpret=interpret,
                         use_kernel=use_kernel)
        hq, hk = qa.shape[2], ka.shape[2]
        if hq % n or hk % n:
            raise ValueError(
                f"ulysses_attention needs heads divisible by the "
                f"'{seq_axis}' size {n}; got q heads {hq}, kv heads {hk} "
                f"(use ring_attention for head-limited models)")
        spec = P(None, seq_axis, None, None)
        inner = functools.partial(
            _ulysses_shard, axis_name=seq_axis, n=n, causal=causal,
            interpret=interpret, use_kernel=use_kernel)
        mapped = jax.shard_map(
            inner, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            axis_names={seq_axis}, check_vma=False)
        # partial-manual shard_map (other mesh axes stay auto) is only
        # supported under jit; nested jit inlines into callers' traces
        return jax.jit(mapped)(qa, ka, va)

    if isinstance(q, Tensor):
        return apply(jfn, q, k, v, op_name="ulysses_attention")
    return jfn(q, k, v)


class UlyssesAttention:
    """Facade mirroring ``RingFlashAttention``: ``UlyssesAttention.apply``."""

    @staticmethod
    def apply(q, k, v, causal=True, seq_axis="sep", **kw):
        return ulysses_attention(q, k, v, causal=causal, seq_axis=seq_axis,
                                 **kw)
