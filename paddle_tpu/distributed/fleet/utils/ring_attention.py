"""Context-parallel (ring) attention — user-facing layer.

Reference analogue: PaddleNLP ``RingFlashAttention`` over the sep/cp comm
group (SURVEY.md §2.3 "CP / ring attention"); core Paddle contributes the
group + p2p + FA2 softmax_lse. Here the core contribution is
``paddle_tpu.ops.pallas.ring_flash_attention`` (Pallas FA kernel + ppermute KV
rotation), and this module binds it to the global hybrid mesh's 'sep' axis so
it drops into a GSPMD-jitted train step: every other mesh axis stays in
"auto" sharding mode — only 'sep' is manual inside the shard_map region.
"""
from __future__ import annotations

import functools

import jax
from jax.sharding import PartitionSpec as P

from ... import mesh as mesh_mod
from ....ops.pallas.ring_attention import ring_flash_attention
from ....autograd.tape import apply
from ....framework.core import Tensor


def ring_attention(q, k, v, causal=True, seq_axis="sep", mesh=None,
                   interpret=None, use_kernel=True):
    """Ring flash attention over the mesh's ``seq_axis``.

    q/k/v: jax arrays (or Tensors), paddle layout [b, s, h, d], with the seq
    dim sharded over ``seq_axis``. Works eagerly and under jit: the shard_map
    region binds only ``seq_axis``; remaining mesh axes are auto-sharded by
    GSPMD around it.
    """
    mesh = mesh or mesh_mod.get_mesh()
    n = int(mesh.shape[seq_axis]) if seq_axis in mesh.shape else 1

    def jfn(qa, ka, va):
        if n == 1:
            from ....ops.pallas.flash_attention import (
                flash_attention, mha_reference)
            import jax.numpy as jnp
            if use_kernel:
                return flash_attention(qa, ka, va, causal=causal,
                                       interpret=interpret)
            out = mha_reference(jnp.swapaxes(qa, 1, 2), jnp.swapaxes(ka, 1, 2),
                                jnp.swapaxes(va, 1, 2), causal=causal)
            return jnp.swapaxes(out, 1, 2)
        spec = P(None, seq_axis, None, None)
        inner = functools.partial(
            ring_flash_attention, axis_name=seq_axis, causal=causal,
            axis_size=n, interpret=interpret, use_kernel=use_kernel)
        return jax.shard_map(
            inner, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            axis_names={seq_axis}, check_vma=False)(qa, ka, va)

    if isinstance(q, Tensor):
        return apply(jfn, q, k, v, op_name="ring_attention")
    return jfn(q, k, v)


class RingFlashAttention:
    """PaddleNLP-compatible facade: ``RingFlashAttention.apply(q, k, v)``."""

    @staticmethod
    def apply(q, k, v, causal=True, seq_axis="sep", **kw):
        return ring_attention(q, k, v, causal=causal, seq_axis=seq_axis, **kw)
