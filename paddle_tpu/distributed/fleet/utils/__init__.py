"""fleet.utils — recompute + sequence parallel helpers (reference:
``python/paddle/distributed/fleet/utils/__init__.py``)."""
from __future__ import annotations

import functools

import jax

from ....framework.core import Tensor
from ....autograd.tape import apply, no_grad
from . import sequence_parallel_utils  # noqa: F401
from .ring_attention import ring_attention, RingFlashAttention  # noqa: F401
from .ulysses import ulysses_attention, UlyssesAttention  # noqa: F401


def _is_tensor(x):
    return isinstance(x, Tensor)


def recompute(function, *args, **kwargs):
    """Activation recompute (reference: ``paddle.distributed.fleet.utils.
    recompute`` → re-forward in backward; SURVEY.md §7.1 M4 "recompute ≡
    jax.checkpoint").

    Under a jit trace (to_static / the distributed engine) this wraps the
    call in ``jax.checkpoint`` — residuals are dropped and re-computed in
    backward, with params correctly differentiated through the closure
    tracers. In pure eager mode it runs normally (eager JAX holds vjp
    residuals per-op; the memory win belongs to the compiled path, which is
    also where the reference uses recompute for real training).
    """
    kwargs.pop("use_reentrant", None)
    kwargs.pop("preserve_rng_state", None)
    policy_name = kwargs.pop("policy", None)
    leaves, treedef = jax.tree.flatten(list(args), is_leaf=_is_tensor)
    tracing = any(isinstance(l._data if isinstance(l, Tensor) else l,
                             jax.core.Tracer) for l in leaves)
    if not tracing:
        return function(*args, **kwargs)

    tensor_slots = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]
    static_leaves = [None if isinstance(l, Tensor) else l for l in leaves]
    sg_flags = [leaves[i].stop_gradient for i in tensor_slots]

    from ....flags import flag as _flag
    policy_name = policy_name or _flag("FLAGS_recompute_policy", "full")
    try:
        policy = {
            "full": None,   # jax.checkpoint default: nothing saveable
            "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            "dots_batch": jax.checkpoint_policies.dots_saveable,
            "everything": jax.checkpoint_policies.everything_saveable,
        }[policy_name]
    except KeyError:
        raise ValueError(
            f"unknown recompute policy {policy_name!r}; expected one of "
            "full/dots/dots_batch/everything") from None

    @functools.partial(jax.checkpoint, policy=policy)
    def pure(*arrs):
        new_leaves = list(static_leaves)
        for slot, a, sg in zip(tensor_slots, arrs, sg_flags):
            t = Tensor(a)
            t.stop_gradient = sg
            new_leaves[slot] = t
        new_args = jax.tree.unflatten(treedef, new_leaves)
        with no_grad():
            out = function(*new_args, **kwargs)
        return jax.tree.map(lambda t: t._data if isinstance(t, Tensor) else t,
                            out, is_leaf=_is_tensor)

    arrs = [leaves[i]._data for i in tensor_slots]
    out = pure(*arrs)
    return jax.tree.map(lambda a: Tensor(a) if isinstance(
        a, (jax.Array, jax.core.Tracer)) else a, out)


class HybridParallelInferenceHelper:
    def __init__(self, *a, **k):
        raise NotImplementedError("static-mode hybrid inference helper is not "
                                  "in the TPU build; use jit + AOT lowering")
