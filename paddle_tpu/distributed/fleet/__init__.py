"""paddle.distributed.fleet — the hybrid-parallel facade (reference:
``python/paddle/distributed/fleet/fleet.py`` — ``fleet.init(is_collective,
strategy)``, ``distributed_model()`` wrapping the model per strategy,
``distributed_optimizer()``; SURVEY.md §2.3 "Fleet facade", §3.4).

TPU-native: ``init`` builds the global device mesh from the strategy's
hybrid degrees (mesh axes [dp, pp, sharding, sep, mp]) — the reference's
per-axis NCCL group creation becomes mesh construction; everything else is
sharding annotations the wrapped layers/optimizers already carry.
"""
from __future__ import annotations

from .distributed_strategy import DistributedStrategy
from .topology import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup,
    get_hybrid_communicate_group, set_hybrid_communicate_group,
)
from .hybrid_parallel_optimizer import HybridParallelOptimizer
from . import meta_parallel  # noqa: F401
from .meta_parallel import (  # noqa: F401
    PipelineLayer, LayerDesc, SharedLayerDesc, PipelineParallel,
    TensorParallel, ShardingParallel, ColumnParallelLinear, RowParallelLinear,
    VocabParallelEmbedding, ParallelCrossEntropy, get_rng_state_tracker,
)
from . import utils  # noqa: F401
from .utils import recompute  # noqa: F401
from . import elastic  # noqa: F401  (ElasticManager + TrainingSupervisor)
from .. import mesh as mesh_mod
from ..parallel import DataParallel
from ..parallel_env import init_parallel_env, get_rank, get_world_size

# module-level fleet state (the reference Fleet singleton)
_strategy: DistributedStrategy | None = None
_initialized = [False]


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    global _strategy
    _strategy = strategy or DistributedStrategy()
    init_parallel_env()
    if getattr(_strategy, "auto_search", False):
        _apply_auto_search(_strategy)
    degrees = _strategy.degrees()
    mesh_mod.init_mesh(degrees)
    set_hybrid_communicate_group(None)
    set_hybrid_communicate_group(HybridCommunicateGroup())
    _initialized[0] = True
    return


def _apply_auto_search(strategy):
    """strategy.auto_search: pick hybrid degrees with the cost-model
    Tuner (reference: the rule-based auto-parallel tuner steering
    strategy.auto) and install them as this job's hybrid_configs.
    Explicitly-set degrees win — the tuner only fills an untouched
    (all-1) hybrid config."""
    import sys
    import jax
    if any(v > 1 for v in strategy.degrees().values()):
        return                     # user already chose a layout
    cfg = dict(strategy.auto_search_configs or {})
    model = cfg.pop("model", None)
    if model is None:
        print("fleet.init: auto_search needs auto_search_configs['model'] "
              "(a model config or ModelSpec); keeping dp-only", file=sys.stderr)
        return
    from ..auto_parallel.cost_model import ModelSpec, Tuner
    n = len(jax.devices())
    chip = cfg.pop("chip", None)
    if chip is None:
        plat = jax.devices()[0].device_kind.lower()
        chip = next((k for k in ("v6e", "v5p", "v5e", "v4")
                     if k in plat), "v5e")
    seq_len = cfg.pop("seq_len", None)
    global_batch = cfg.pop("global_batch", None)
    if isinstance(model, ModelSpec):
        import dataclasses
        overrides = {}
        if seq_len is not None:
            overrides["seq_len"] = int(seq_len)
        if global_batch is not None:
            overrides["global_batch"] = int(global_batch)
        spec = dataclasses.replace(model, **overrides) if overrides \
            else model
    else:
        spec = ModelSpec.from_config(model, seq_len=seq_len,
                                     global_batch=global_batch or n)
    try:
        plan = Tuner(chip=chip).tune(spec, n, top_k=1)[0]
    except ValueError as e:
        print(f"fleet.init: auto_search found no valid plan ({e}); "
              f"keeping dp-only", file=sys.stderr)
        return
    # update ONLY the degree keys in place — the user's pp_configs /
    # sharding settings etc. must survive the tuner
    for k, v in plan.degrees.items():
        strategy._hybrid_configs[f"{k}_degree"] = int(v)
    print(f"fleet.init: auto_search chose {plan.degrees} "
          f"(est {plan.step_time_s * 1e3:.2f} ms/step, "
          f"{plan.mem_per_chip / 2**30:.2f} GiB/chip)", file=sys.stderr)


def is_initialized():
    return _initialized[0]


def get_strategy() -> DistributedStrategy:
    return _strategy or DistributedStrategy()


def distributed_model(model):
    """Wrap per strategy: PipelineLayer → PipelineParallel; mp-only →
    TensorParallel; dp → DataParallel (mesh input sharding). Reference
    precedence: pp > sharding > mp > dp."""
    strategy = get_strategy()
    hcg = get_hybrid_communicate_group()
    d = strategy.degrees()
    if isinstance(model, PipelineLayer) or (
            hasattr(model, "_layers") and isinstance(getattr(model, "_layers", None),
                                                     PipelineLayer)):
        return PipelineParallel(model, hcg, strategy)
    if d["pp"] > 1:
        raise TypeError("pp_degree > 1 requires the model to be a PipelineLayer")
    if d["mp"] > 1 and d["dp"] == 1:
        return TensorParallel(model, hcg, strategy)
    if d["dp"] > 1:
        return DataParallel(model)
    return model


def distributed_optimizer(optimizer, strategy=None):
    return HybridParallelOptimizer(optimizer, get_hybrid_communicate_group(),
                                   strategy or get_strategy())


# -- worker topology helpers (reference Fleet API) ---------------------------
def worker_index():
    return get_rank()


def worker_num():
    return get_world_size()


def is_first_worker():
    return get_rank() == 0


def worker_endpoints(to_string=False):
    from ..parallel_env import ParallelEnv
    eps = ParallelEnv().trainer_endpoints
    return ",".join(eps) if to_string else eps


def barrier_worker():
    from ..collective import barrier
    barrier()


# -- parameter-server mode: explicitly out of TPU scope (SURVEY.md §7.4) -----
def _ps_stub(name):
    def fn(*a, **k):
        raise NotImplementedError(
            f"fleet.{name} belongs to parameter-server mode, which is not in "
            "the TPU build (SURVEY.md §7.4); use collective mode")
    return fn


init_worker = _ps_stub("init_worker")
init_server = _ps_stub("init_server")
run_server = _ps_stub("run_server")
stop_worker = _ps_stub("stop_worker")


class UserDefinedRoleMaker:
    def __init__(self, *a, **k):
        pass


class PaddleCloudRoleMaker:
    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective
