"""paddle.distributed.fleet — the hybrid-parallel facade (reference:
``python/paddle/distributed/fleet/fleet.py`` — ``fleet.init(is_collective,
strategy)``, ``distributed_model()`` wrapping the model per strategy,
``distributed_optimizer()``; SURVEY.md §2.3 "Fleet facade", §3.4).

TPU-native: ``init`` builds the global device mesh from the strategy's
hybrid degrees (mesh axes [dp, pp, sharding, sep, mp]) — the reference's
per-axis NCCL group creation becomes mesh construction; everything else is
sharding annotations the wrapped layers/optimizers already carry.
"""
from __future__ import annotations

from .distributed_strategy import DistributedStrategy
from .topology import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup,
    get_hybrid_communicate_group, set_hybrid_communicate_group,
)
from .hybrid_parallel_optimizer import HybridParallelOptimizer
from . import meta_parallel  # noqa: F401
from .meta_parallel import (  # noqa: F401
    PipelineLayer, LayerDesc, SharedLayerDesc, PipelineParallel,
    TensorParallel, ShardingParallel, ColumnParallelLinear, RowParallelLinear,
    VocabParallelEmbedding, ParallelCrossEntropy, get_rng_state_tracker,
)
from . import utils  # noqa: F401
from .utils import recompute  # noqa: F401
from . import elastic  # noqa: F401  (ElasticManager + TrainingSupervisor)
from .. import mesh as mesh_mod
from ..parallel import DataParallel
from ..parallel_env import init_parallel_env, get_rank, get_world_size

# module-level fleet state (the reference Fleet singleton)
_strategy: DistributedStrategy | None = None
_initialized = [False]


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    global _strategy
    _strategy = strategy or DistributedStrategy()
    if not is_collective or (role_maker is not None
                             and not getattr(role_maker, "_is_collective",
                                             True)):
        # parameter-server mode (reference fleet.init(is_collective=False)):
        # no device mesh — role/endpoint bookkeeping only, servers and
        # workers rendezvous over the PS RPC tier instead of collectives.
        old_client, old_server = _ps_state.get("client"), _ps_state.get(
            "server")
        if old_client is not None:
            old_client.close()
        # keep a still-serving server (same-process server+trainer jobs,
        # simulators); discard a shut-down one so a NEW job can't silently
        # reuse its closed socket
        if old_server is not None and old_server._shutdown.is_set():
            old_server.stop()
            old_server = None
        _ps_state.update(role_maker=role_maker or PaddleCloudRoleMaker(
            is_collective=False), mode="ps", server=old_server, client=None)
        _initialized[0] = True
        return
    init_parallel_env()
    if getattr(_strategy, "auto_search", False):
        _apply_auto_search(_strategy)
    degrees = _strategy.degrees()
    mesh_mod.init_mesh(degrees)
    set_hybrid_communicate_group(None)
    set_hybrid_communicate_group(HybridCommunicateGroup())
    _initialized[0] = True
    return


def _apply_auto_search(strategy):
    """strategy.auto_search: pick hybrid degrees with the cost-model
    Tuner (reference: the rule-based auto-parallel tuner steering
    strategy.auto) and install them as this job's hybrid_configs.
    Explicitly-set degrees win — the tuner only fills an untouched
    (all-1) hybrid config."""
    import sys
    import jax
    if any(v > 1 for v in strategy.degrees().values()):
        return                     # user already chose a layout
    cfg = dict(strategy.auto_search_configs or {})
    model = cfg.pop("model", None)
    if model is None:
        print("fleet.init: auto_search needs auto_search_configs['model'] "
              "(a model config or ModelSpec); keeping dp-only", file=sys.stderr)
        return
    from ..auto_parallel.cost_model import ModelSpec, Tuner
    n = len(jax.devices())
    chip = cfg.pop("chip", None)
    if chip is None:
        plat = jax.devices()[0].device_kind.lower()
        chip = next((k for k in ("v6e", "v5p", "v5e", "v4")
                     if k in plat), "v5e")
    seq_len = cfg.pop("seq_len", None)
    global_batch = cfg.pop("global_batch", None)
    if isinstance(model, ModelSpec):
        import dataclasses
        overrides = {}
        if seq_len is not None:
            overrides["seq_len"] = int(seq_len)
        if global_batch is not None:
            overrides["global_batch"] = int(global_batch)
        spec = dataclasses.replace(model, **overrides) if overrides \
            else model
    else:
        spec = ModelSpec.from_config(model, seq_len=seq_len,
                                     global_batch=global_batch or n)
    try:
        from ..mesh import _slice_major
        n_slices = _slice_major(jax.devices())[1]
        plan = Tuner(chip=chip, n_slices=n_slices).tune(spec, n, top_k=1)[0]
    except ValueError as e:
        print(f"fleet.init: auto_search found no valid plan ({e}); "
              f"keeping dp-only", file=sys.stderr)
        return
    # update ONLY the degree keys in place — the user's pp_configs /
    # sharding settings etc. must survive the tuner
    for k, v in plan.degrees.items():
        strategy._hybrid_configs[f"{k}_degree"] = int(v)
    print(f"fleet.init: auto_search chose {plan.degrees} "
          f"(est {plan.step_time_s * 1e3:.2f} ms/step, "
          f"{plan.mem_per_chip / 2**30:.2f} GiB/chip)", file=sys.stderr)


def is_initialized():
    return _initialized[0]


def get_strategy() -> DistributedStrategy:
    return _strategy or DistributedStrategy()


def distributed_model(model):
    """Wrap per strategy: PipelineLayer → PipelineParallel; mp-only →
    TensorParallel; dp → DataParallel (mesh input sharding). Reference
    precedence: pp > sharding > mp > dp."""
    strategy = get_strategy()
    hcg = get_hybrid_communicate_group()
    d = strategy.degrees()
    if isinstance(model, PipelineLayer) or (
            hasattr(model, "_layers") and isinstance(getattr(model, "_layers", None),
                                                     PipelineLayer)):
        return PipelineParallel(model, hcg, strategy)
    if d["pp"] > 1:
        raise TypeError("pp_degree > 1 requires the model to be a PipelineLayer")
    if d["mp"] > 1 and d["dp"] == 1:
        return TensorParallel(model, hcg, strategy)
    if d["dp"] > 1:
        return DataParallel(model)
    return model


def distributed_optimizer(optimizer, strategy=None):
    strategy = strategy or get_strategy()
    # dp-axis meta-optimizers wrap first (reference meta-optimizer
    # resolution: dgc/localsgd apply to the data-parallel exchange)
    if getattr(strategy, "dgc", False):
        from ...optimizer import Momentum, SGD
        # reference contract: the DGC meta-optimizer engages only for
        # Momentum/SGD inner optimizers (its update rule IS momentum
        # SGD); anything else keeps its own math rather than being
        # silently replaced
        lr = getattr(optimizer, "_learning_rate", 0.001)
        if isinstance(optimizer, (Momentum, SGD)) and not callable(lr):
            from .meta_optimizers import DGCMomentumOptimizer
            cfg = dict(getattr(strategy, "dgc_configs", {}) or {})
            optimizer = DGCMomentumOptimizer(
                learning_rate=float(lr),
                momentum=getattr(optimizer, "_momentum", 0.9),
                parameters=optimizer._parameter_list,
                rampup_begin_step=cfg.get("rampup_begin_step", 0),
                rampup_step=cfg.get("rampup_step", 1),
                sparsity=cfg.get("sparsity", [0.999]),
                grad_clip=getattr(optimizer, "_grad_clip", None),
                fuse_grad_size_in_MB=getattr(strategy,
                                             "fuse_grad_size_in_MB", 32),
                comm_quantization=getattr(strategy, "comm_quantization",
                                          None),
                comm_configs=getattr(strategy, "comm_configs", None))
        else:
            import sys
            print("fleet: strategy.dgc=True ignored — DGC applies to "
                  "Momentum/SGD with a static learning rate; the inner "
                  f"optimizer is {type(optimizer).__name__}",
                  file=sys.stderr)
    if getattr(strategy, "localsgd", False):
        from .meta_optimizers import LocalSGDOptimizer
        cfg = dict(getattr(strategy, "localsgd_configs", {}) or {})
        optimizer = LocalSGDOptimizer(
            optimizer, k_steps=cfg.get("k_steps", 1),
            begin_step=cfg.get("begin_step", 1),
            fuse_grad_size_in_MB=getattr(strategy, "fuse_grad_size_in_MB",
                                         32),
            comm_quantization=getattr(strategy, "comm_quantization", None),
            comm_configs=getattr(strategy, "comm_configs", None))
    return HybridParallelOptimizer(optimizer, get_hybrid_communicate_group(),
                                   strategy)


# -- worker topology helpers (reference Fleet API) ---------------------------
def worker_index():
    return get_rank()


def worker_num():
    return get_world_size()


def is_first_worker():
    return get_rank() == 0


def worker_endpoints(to_string=False):
    from ..parallel_env import ParallelEnv
    eps = ParallelEnv().trainer_endpoints
    return ",".join(eps) if to_string else eps


def barrier_worker():
    from ..collective import barrier
    barrier()


# -- parameter-server mode (reference: fleet PS path + the_one_ps.py;
# SURVEY.md §2.3 "PS mode"). SURVEY §7.4 scoped this note-only; the
# working TPU-native re-design lives in paddle_tpu.distributed.ps and
# this is its role/lifecycle facade. ------------------------------------
_ps_state: dict = {"mode": None, "role_maker": None, "server": None,
                   "client": None}


def _ps_role():
    rm = _ps_state.get("role_maker")
    if rm is None or _ps_state.get("mode") != "ps":
        raise RuntimeError(
            "fleet is not in parameter-server mode; call "
            "fleet.init(PaddleCloudRoleMaker(is_collective=False)) or "
            "fleet.init(is_collective=False) first")
    return rm


def is_server():
    return _ps_role().is_server()


def is_worker():
    return _ps_role().is_worker()


def init_server(*model_dirs, **kwargs):
    """Bind this process's PSServer on its endpoint from the role maker.
    A still-serving server kept across fleet.init() is reused — binding a
    second socket on the same endpoint would EADDRINUSE."""
    from ..ps import PSServer
    srv = _ps_state.get("server")
    if srv is not None and not srv._shutdown.is_set():
        return srv
    rm = _ps_role()
    host, port = rm.server_endpoint().rsplit(":", 1)
    _ps_state["server"] = PSServer(host=host, port=int(port))
    return _ps_state["server"]


def run_server():
    """Blocking serve loop (reference fleet.run_server); returns after a
    worker calls stop_worker() → SHUTDOWN."""
    srv = _ps_state.get("server") or init_server()
    srv.run()


def init_worker():
    """Create the trainer-side PSClient over all server endpoints."""
    from ..ps import PSClient
    rm = _ps_role()
    _ps_state["client"] = PSClient(rm.server_endpoints(),
                                   async_push=getattr(_strategy, "a_sync",
                                                      False))
    return _ps_state["client"]


def ps_client():
    c = _ps_state.get("client")
    if c is None:
        raise RuntimeError("call fleet.init_worker() first")
    return c


def stop_worker():
    c = _ps_state.get("client")
    if c is not None:
        try:
            c.flush()                    # surfaces dropped async pushes
        finally:
            # even a failed flush must not leave pservers serving forever;
            # and a failed role lookup must not mask the flush error or
            # skip close() — cleanup is unconditional
            try:
                rm = _ps_state.get("role_maker")
                if rm is None or rm.worker_index() == 0:
                    c.shutdown_servers()
            finally:
                c.close()
                _ps_state["client"] = None


class UserDefinedRoleMaker:
    def __init__(self, *a, **k):
        pass


class PaddleCloudRoleMaker:
    """Parses the reference's PaddleCloud environment contract
    (``TRAINING_ROLE``, ``PADDLE_PSERVERS_IP_PORT_LIST``,
    ``PADDLE_TRAINERS_NUM``, ``POD_IP``/``PADDLE_PORT``) so PS jobs
    launched by the reference's cluster scripts resolve roles unchanged."""

    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective
        import os
        self._role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._server_eps = [e for e in eps.replace(";", ",").split(",") if e]
        self._trainers = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self._trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._pod_ip = os.environ.get("POD_IP", "127.0.0.1")
        self._port = os.environ.get("PADDLE_PORT", "")

    def is_server(self):
        return self._role == "PSERVER"

    def is_worker(self):
        return self._role == "TRAINER"

    def server_endpoints(self):
        return list(self._server_eps)

    def server_endpoint(self):
        """This PSERVER's own bind endpoint: POD_IP:PADDLE_PORT when it
        matches the server list; else the list entry with this PADDLE_PORT
        (POD_IP unset on some clusters); else list[0]; else the local
        pair."""
        me = f"{self._pod_ip}:{self._port}"
        if me in self._server_eps:
            return me
        if self._port:
            for ep in self._server_eps:
                if ep.rsplit(":", 1)[-1] == self._port:
                    return ep
        if self._server_eps:
            return self._server_eps[0]
        return me if self._port else "127.0.0.1:0"

    def worker_index(self):
        return self._trainer_id

    def worker_num(self):
        return self._trainers
