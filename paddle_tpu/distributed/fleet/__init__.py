"""paddle.distributed.fleet — the hybrid-parallel facade (reference:
``python/paddle/distributed/fleet/fleet.py`` — ``fleet.init(is_collective,
strategy)``, ``distributed_model()`` wrapping the model per strategy,
``distributed_optimizer()``; SURVEY.md §2.3 "Fleet facade", §3.4).

TPU-native: ``init`` builds the global device mesh from the strategy's
hybrid degrees (mesh axes [dp, pp, sharding, sep, mp]) — the reference's
per-axis NCCL group creation becomes mesh construction; everything else is
sharding annotations the wrapped layers/optimizers already carry.
"""
from __future__ import annotations

from .distributed_strategy import DistributedStrategy
from .topology import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup,
    get_hybrid_communicate_group, set_hybrid_communicate_group,
)
from .hybrid_parallel_optimizer import HybridParallelOptimizer
from . import meta_parallel  # noqa: F401
from .meta_parallel import (  # noqa: F401
    PipelineLayer, LayerDesc, SharedLayerDesc, PipelineParallel,
    TensorParallel, ShardingParallel, ColumnParallelLinear, RowParallelLinear,
    VocabParallelEmbedding, ParallelCrossEntropy, get_rng_state_tracker,
)
from . import utils  # noqa: F401
from .utils import recompute  # noqa: F401
from . import elastic  # noqa: F401  (ElasticManager + TrainingSupervisor)
from .. import mesh as mesh_mod
from ..parallel import DataParallel
from ..parallel_env import init_parallel_env, get_rank, get_world_size

# module-level fleet state (the reference Fleet singleton)
_strategy: DistributedStrategy | None = None
_initialized = [False]


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    global _strategy
    _strategy = strategy or DistributedStrategy()
    init_parallel_env()
    degrees = _strategy.degrees()
    mesh_mod.init_mesh(degrees)
    set_hybrid_communicate_group(None)
    set_hybrid_communicate_group(HybridCommunicateGroup())
    _initialized[0] = True
    return


def is_initialized():
    return _initialized[0]


def get_strategy() -> DistributedStrategy:
    return _strategy or DistributedStrategy()


def distributed_model(model):
    """Wrap per strategy: PipelineLayer → PipelineParallel; mp-only →
    TensorParallel; dp → DataParallel (mesh input sharding). Reference
    precedence: pp > sharding > mp > dp."""
    strategy = get_strategy()
    hcg = get_hybrid_communicate_group()
    d = strategy.degrees()
    if isinstance(model, PipelineLayer) or (
            hasattr(model, "_layers") and isinstance(getattr(model, "_layers", None),
                                                     PipelineLayer)):
        return PipelineParallel(model, hcg, strategy)
    if d["pp"] > 1:
        raise TypeError("pp_degree > 1 requires the model to be a PipelineLayer")
    if d["mp"] > 1 and d["dp"] == 1:
        return TensorParallel(model, hcg, strategy)
    if d["dp"] > 1:
        return DataParallel(model)
    return model


def distributed_optimizer(optimizer, strategy=None):
    return HybridParallelOptimizer(optimizer, get_hybrid_communicate_group(),
                                   strategy or get_strategy())


# -- worker topology helpers (reference Fleet API) ---------------------------
def worker_index():
    return get_rank()


def worker_num():
    return get_world_size()


def is_first_worker():
    return get_rank() == 0


def worker_endpoints(to_string=False):
    from ..parallel_env import ParallelEnv
    eps = ParallelEnv().trainer_endpoints
    return ",".join(eps) if to_string else eps


def barrier_worker():
    from ..collective import barrier
    barrier()


# -- parameter-server mode: explicitly out of TPU scope (SURVEY.md §7.4) -----
def _ps_stub(name):
    def fn(*a, **k):
        raise NotImplementedError(
            f"fleet.{name} belongs to parameter-server mode, which is not in "
            "the TPU build (SURVEY.md §7.4); use collective mode")
    return fn


init_worker = _ps_stub("init_worker")
init_server = _ps_stub("init_server")
run_server = _ps_stub("run_server")
stop_worker = _ps_stub("stop_worker")


class UserDefinedRoleMaker:
    def __init__(self, *a, **k):
        pass


class PaddleCloudRoleMaker:
    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective
