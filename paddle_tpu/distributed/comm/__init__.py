"""paddle_tpu.distributed.comm — bucketed + quantized gradient
communication (EQuARX-style blockwise int8 collectives, arXiv:2506.17615;
policy-programmable comm in the spirit of Piper, arXiv:2606.11169).

Three layers:

* :class:`GradientBucketer` — flattens per-parameter gradients into
  fixed-size dtype-homogeneous fusion buckets (``fuse_grad_size_in_MB``)
  with a rank-deterministic layout, so one collective covers many
  tensors;
* quantized collectives — :func:`all_reduce_quantized` /
  :func:`reduce_scatter_quantized` with blockwise-int8 or bf16 wire
  formats, fp32 passthrough, and optional error feedback;
* :class:`CommStats` — calls / logical vs wire bytes / compression ratio
  / max quantization error, queryable from ``paddle_tpu.profiler
  .comm_stats()`` and emitted by ``bench.py`` (BENCH_MODEL=comm).

Policy wiring: ``DistributedStrategy.comm_quantization`` +
``fuse_grad_size_in_MB`` + ``comm_configs`` route ``DataParallel``,
``HybridParallelOptimizer``, the DGC/LocalSGD meta-optimizers and the
stage-2 sharding optimizer through this subsystem instead of per-tensor
fp32 calls.
"""
from __future__ import annotations

from .stats import CommStats, get_comm_stats, reset_comm_stats  # noqa: F401
from .quantization import (  # noqa: F401
    DEFAULT_BLOCK_SIZE, quantize_blockwise, dequantize_blockwise,
    quantize_blockwise_jax, dequantize_blockwise_jax, SCHEMES,
)
from .collectives import (  # noqa: F401
    all_reduce_quantized, reduce_scatter_quantized, allreduce_array,
    reduce_scatter_array, PASSTHROUGH,
)
from .bucketer import GradientBucketer, ReadyBucketScheduler  # noqa: F401


def comm_config_from_strategy(strategy) -> dict:
    """Kwargs for :class:`GradientBucketer` from a DistributedStrategy
    (tolerates None / strategies predating the comm knobs)."""
    cfg = dict(getattr(strategy, "comm_configs", {}) or {})
    return {
        "fuse_grad_size_in_MB": getattr(strategy, "fuse_grad_size_in_MB", 32),
        "quantization": getattr(strategy, "comm_quantization", None),
        "block_size": cfg.get("block_size", DEFAULT_BLOCK_SIZE),
        "error_feedback": cfg.get("error_feedback", False),
    }
