"""GradientBucketer — fixed-size fusion buckets for gradient exchange.

The reference amortizes NCCL launch overhead with the C++ reducer's
grad buckets (``reducer.cc``, ``fuse_grad_size_in_MB``); here the same
fusion amortizes the per-collective rendezvous/host round trip of the
imperative tier AND gives the quantized wire codec long contiguous
vectors to blockwise-compress.

Layout contract: buckets are built from the parameter list's *order,
shapes and dtypes only* — never from gradient values or presence — so
every rank derives the identical layout and the per-bucket collectives
pair correctly (``signature()`` is the testable witness). Buckets are
dtype-homogeneous; a bucket closes when adding the next same-dtype
parameter would exceed ``fuse_grad_size_in_MB`` (0 → one bucket per
parameter, the legacy per-tensor wire pattern). Each parameter owns a
``[offset, offset+numel)`` view into its bucket's flat buffer.
"""
from __future__ import annotations

import numpy as np

from .. import collective as _collective
from ...framework.core import Tensor
from .collectives import PASSTHROUGH, allreduce_array, reduce_scatter_array
from .quantization import DEFAULT_BLOCK_SIZE


class _Bucket:
    __slots__ = ("dtype", "items", "numel")

    def __init__(self, dtype):
        self.dtype = dtype
        self.items = []   # (param_index, offset, numel, shape)
        self.numel = 0

    @property
    def nbytes(self):
        return self.numel * self.dtype.itemsize


class GradientBucketer:
    def __init__(self, parameters, fuse_grad_size_in_MB=32, quantization=None,
                 block_size: int = DEFAULT_BLOCK_SIZE, error_feedback=False):
        self._params = [p for p in parameters if p is not None]
        self._fuse_bytes = max(0.0, float(fuse_grad_size_in_MB)) * 2 ** 20
        self.quantization = (None if quantization in PASSTHROUGH
                             else quantization)
        self.block_size = int(block_size)
        self.error_feedback = bool(error_feedback)
        self._residuals = {}    # bucket index -> fp32 residual (error feedback)
        self._buckets = self._build()

    @classmethod
    def from_strategy(cls, parameters, strategy):
        """Build with the ``DistributedStrategy`` comm knobs."""
        cfg = dict(getattr(strategy, "comm_configs", {}) or {})
        return cls(parameters,
                   fuse_grad_size_in_MB=getattr(strategy,
                                                "fuse_grad_size_in_MB", 32),
                   quantization=getattr(strategy, "comm_quantization", None),
                   block_size=cfg.get("block_size", DEFAULT_BLOCK_SIZE),
                   error_feedback=cfg.get("error_feedback", False))

    # -- layout --------------------------------------------------------------
    def _build(self):
        # With int8 quantization each parameter is aligned to a block
        # boundary so no quantization block spans two parameters — a small
        # tensor must never inherit the scale of a large-gradient neighbor
        # (per-block scales are EQuARX's accuracy lever; crossing tensor
        # boundaries would defeat it). Alignment padding is zeros on the
        # wire and depends only on shapes/dtypes, so layout determinism
        # across ranks is preserved.
        align = self.block_size if self.quantization == "int8" else 1
        buckets: list[_Bucket] = []
        open_by_dtype: dict = {}
        for i, p in enumerate(self._params):
            arr = getattr(p, "_data", p)
            dt = np.dtype(arr.dtype)
            numel = int(np.prod(arr.shape)) if arr.shape else 1
            b = open_by_dtype.get(dt)
            if (b is None or
                    (b.numel and (b.numel + numel) * dt.itemsize
                     > self._fuse_bytes)):
                b = _Bucket(dt)
                buckets.append(b)
                open_by_dtype[dt] = b
            b.items.append((i, b.numel, numel, tuple(arr.shape)))
            b.numel += -(-numel // align) * align
        return buckets

    @property
    def buckets(self):
        return list(self._buckets)

    @property
    def num_buckets(self):
        return len(self._buckets)

    def signature(self):
        """Hashable layout descriptor — identical across ranks by
        construction; tested as such."""
        return tuple((str(b.dtype),
                      tuple((it[0], it[1], it[2]) for it in b.items))
                     for b in self._buckets)

    # -- exchange ------------------------------------------------------------
    def _flatten(self, bucket, arrays):
        flat = np.zeros(bucket.numel, bucket.dtype)
        for (i, off, numel, _shape) in bucket.items:
            a = arrays[i]
            if a is not None:
                flat[off:off + numel] = np.asarray(a, bucket.dtype).ravel()
        return flat

    def _quantizable(self, bucket):
        return (self.quantization is not None
                and np.issubdtype(bucket.dtype, np.floating))

    def _residual(self, key, numel):
        if not self.error_feedback:
            return None
        r = self._residuals.get(key)
        if r is None or r.size != numel:
            r = self._residuals[key] = np.zeros(numel, np.float32)
        return r

    def sync_arrays(self, arrays, group=None, op=None,
                    use_reduce_scatter=False):
        """Reduce ``arrays`` (aligned with the parameter list; ``None``
        entries contribute zeros) across ``group`` — one collective per
        bucket — and return the reduced list (``None`` preserved).

        ``use_reduce_scatter=True`` runs the stage-2 wire pattern:
        reduce-scatter (each rank reduces its shard) followed by an
        all-gather of the shards, so the wire carries 2/n of the
        all-reduce gather-tier volume per direction while every rank
        still ends with the full reduced vector.
        """
        group = group or _collective._get_default_group()
        op = op if op is not None else _collective.ReduceOp.AVG
        out = [None] * len(self._params)
        for bi, bucket in enumerate(self._buckets):
            flat = self._flatten(bucket, arrays)
            if self._quantizable(bucket):
                red = self._sync_flat_quantized(bi, bucket, flat, group, op,
                                                use_reduce_scatter)
            else:
                red = self._sync_flat_plain(bucket, flat, group, op,
                                            use_reduce_scatter)
            red = np.asarray(red).ravel()
            for (i, off, numel, shape) in bucket.items:
                if arrays[i] is not None:
                    out[i] = red[off:off + numel].reshape(shape).astype(
                        bucket.dtype, copy=False)
        return out

    def _sync_flat_quantized(self, bi, bucket, flat, group, op, use_rs):
        residual = self._residual(bi, flat.size)
        if not use_rs or group.nranks == 1:
            return allreduce_array(flat.astype(np.float32, copy=False),
                                   group=group, op=op,
                                   scheme=self.quantization,
                                   block_size=self.block_size,
                                   residual=residual)
        n = group.nranks
        shard_len = -(-flat.size // n)
        padded = np.zeros(n * shard_len, np.float32)
        padded[:flat.size] = flat
        if residual is not None and residual.size != padded.size:
            residual = self._residuals[bi] = np.zeros(padded.size, np.float32)
        shard = reduce_scatter_array(padded.reshape(n, shard_len),
                                     group=group, op=op,
                                     scheme=self.quantization,
                                     block_size=self.block_size,
                                     residual=residual)
        return self._gather_shards(shard, group)[:flat.size]

    def _sync_flat_plain(self, bucket, flat, group, op, use_rs):
        if not use_rs or group.nranks == 1:
            t = Tensor(flat)
            _collective.all_reduce(t, op=op, group=group)
            return t.numpy()
        n = group.nranks
        shard_len = -(-flat.size // n)
        padded = np.zeros(n * shard_len, flat.dtype)
        padded[:flat.size] = flat
        stacked = padded.reshape(n, shard_len)
        out = Tensor(np.zeros(shard_len, flat.dtype))
        _collective.reduce_scatter(out, [Tensor(stacked[i]) for i in range(n)],
                                   op=op, group=group)
        return self._gather_shards(out.numpy(), group)[:flat.size]

    @staticmethod
    def _gather_shards(shard, group):
        outs: list = []
        _collective.all_gather(outs, Tensor(np.asarray(shard)), group=group)
        return np.concatenate([np.asarray(t.numpy()).ravel() for t in outs])

    # -- parameter/gradient conveniences -------------------------------------
    def sync_grads(self, group=None, op=None, use_reduce_scatter=False):
        """Exchange the wrapped parameters' gradients in place (the
        bucketed replacement for per-tensor ``all_reduce(p.grad)``)."""
        import jax.numpy as jnp
        arrays = [p.grad._data if getattr(p, "grad", None) is not None
                  else None for p in self._params]
        red = self.sync_arrays(arrays, group=group, op=op,
                               use_reduce_scatter=use_reduce_scatter)
        for p, r in zip(self._params, red):
            if r is not None:
                p.grad._data = jnp.asarray(r, dtype=p.grad._data.dtype)
        return self

    def sync_params(self, group=None, op=None):
        """Average/reduce the parameter *values* (LocalSGD's averaging)."""
        import jax.numpy as jnp
        arrays = [p._data for p in self._params]
        red = self.sync_arrays(arrays, group=group, op=op)
        for p, r in zip(self._params, red):
            if r is not None:
                p._data = jnp.asarray(r, dtype=p._data.dtype)
        return self
