"""GradientBucketer — fixed-size fusion buckets for gradient exchange.

The reference amortizes NCCL launch overhead with the C++ reducer's
grad buckets (``reducer.cc``, ``fuse_grad_size_in_MB``); here the same
fusion amortizes the per-collective rendezvous/host round trip of the
imperative tier AND gives the quantized wire codec long contiguous
vectors to blockwise-compress.

Layout contract: buckets are built from the parameter list's *order,
shapes and dtypes only* — never from gradient values or presence — so
every rank derives the identical layout and the per-bucket collectives
pair correctly (``signature()`` is the testable witness). Buckets are
dtype-homogeneous; a bucket closes when adding the next same-dtype
parameter would exceed ``fuse_grad_size_in_MB`` (0 → one bucket per
parameter, the legacy per-tensor wire pattern). Each parameter owns a
``[offset, offset+numel)`` view into its bucket's flat buffer.
"""
from __future__ import annotations

import os
import threading
import time
import zlib

import numpy as np

from .. import collective as _collective
from .. import simulator
from ...framework.core import Tensor
from .collectives import PASSTHROUGH, allreduce_array, reduce_scatter_array
from .quantization import DEFAULT_BLOCK_SIZE

_OVERLAP_TELEMETRY = None


def _overlap_telemetry():
    """Lazily bound registry families for the comm/compute overlap path."""
    global _OVERLAP_TELEMETRY
    if _OVERLAP_TELEMETRY is None:
        from ...profiler.telemetry import get_registry
        r = get_registry()
        _OVERLAP_TELEMETRY = {
            "buckets": r.counter(
                "paddle_comm_overlap_buckets_total",
                "gradient buckets dispatched by the ready-bucket scheduler",
                labels=("where",)),
            "wait": r.histogram(
                "paddle_comm_overlap_wait_seconds",
                "seconds blocked on in-flight gradient collectives at the "
                "step boundary"),
        }
    return _OVERLAP_TELEMETRY


class _Bucket:
    __slots__ = ("dtype", "items", "numel")

    def __init__(self, dtype):
        self.dtype = dtype
        self.items = []   # (param_index, offset, numel, shape)
        self.numel = 0

    @property
    def nbytes(self):
        return self.numel * self.dtype.itemsize


class GradientBucketer:
    def __init__(self, parameters, fuse_grad_size_in_MB=32, quantization=None,
                 block_size: int = DEFAULT_BLOCK_SIZE, error_feedback=False):
        self._params = [p for p in parameters if p is not None]
        self._fuse_bytes = max(0.0, float(fuse_grad_size_in_MB)) * 2 ** 20
        self.quantization = (None if quantization in PASSTHROUGH
                             else quantization)
        self.block_size = int(block_size)
        self.error_feedback = bool(error_feedback)
        self._residuals = {}    # bucket index -> fp32 residual (error feedback)
        self._buckets = self._build()

    @classmethod
    def from_strategy(cls, parameters, strategy):
        """Build with the ``DistributedStrategy`` comm knobs."""
        cfg = dict(getattr(strategy, "comm_configs", {}) or {})
        return cls(parameters,
                   fuse_grad_size_in_MB=getattr(strategy,
                                                "fuse_grad_size_in_MB", 32),
                   quantization=getattr(strategy, "comm_quantization", None),
                   block_size=cfg.get("block_size", DEFAULT_BLOCK_SIZE),
                   error_feedback=cfg.get("error_feedback", False))

    # -- layout --------------------------------------------------------------
    def _build(self):
        # With int8 quantization each parameter is aligned to a block
        # boundary so no quantization block spans two parameters — a small
        # tensor must never inherit the scale of a large-gradient neighbor
        # (per-block scales are EQuARX's accuracy lever; crossing tensor
        # boundaries would defeat it). Alignment padding is zeros on the
        # wire and depends only on shapes/dtypes, so layout determinism
        # across ranks is preserved.
        align = self.block_size if self.quantization == "int8" else 1
        buckets: list[_Bucket] = []
        open_by_dtype: dict = {}
        for i, p in enumerate(self._params):
            arr = getattr(p, "_data", p)
            dt = np.dtype(arr.dtype)
            numel = int(np.prod(arr.shape)) if arr.shape else 1
            b = open_by_dtype.get(dt)
            if (b is None or
                    (b.numel and (b.numel + numel) * dt.itemsize
                     > self._fuse_bytes)):
                b = _Bucket(dt)
                buckets.append(b)
                open_by_dtype[dt] = b
            b.items.append((i, b.numel, numel, tuple(arr.shape)))
            b.numel += -(-numel // align) * align
        return buckets

    @property
    def buckets(self):
        return list(self._buckets)

    @property
    def num_buckets(self):
        return len(self._buckets)

    def signature(self):
        """Hashable layout descriptor — identical across ranks by
        construction; tested as such."""
        return tuple((str(b.dtype),
                      tuple((it[0], it[1], it[2]) for it in b.items))
                     for b in self._buckets)

    # -- exchange ------------------------------------------------------------
    def _flatten(self, bucket, arrays):
        # single-tensor buckets (fuse 0, or one large embedding grad that
        # fills a bucket alone) skip the assembly buffer: no zero-fill and
        # no copy-in — the device->host transfer already yields a fresh
        # flat vector with the identical layout (offset 0, no alignment
        # padding possible when the bucket holds exactly its one tensor)
        if len(bucket.items) == 1:
            (i, _off, numel, _shape) = bucket.items[0]
            a = arrays[i]
            if a is not None and numel == bucket.numel:
                return np.asarray(a, bucket.dtype).reshape(-1)
        flat = np.zeros(bucket.numel, bucket.dtype)
        for (i, off, numel, _shape) in bucket.items:
            a = arrays[i]
            if a is not None:
                flat[off:off + numel] = np.asarray(a, bucket.dtype).ravel()
        return flat

    def _quantizable(self, bucket):
        return (self.quantization is not None
                and np.issubdtype(bucket.dtype, np.floating))

    def _residual(self, key, numel):
        if not self.error_feedback:
            return None
        r = self._residuals.get(key)
        if r is None or r.size != numel:
            r = self._residuals[key] = np.zeros(numel, np.float32)
        return r

    def sync_arrays(self, arrays, group=None, op=None,
                    use_reduce_scatter=False):
        """Reduce ``arrays`` (aligned with the parameter list; ``None``
        entries contribute zeros) across ``group`` — one collective per
        bucket — and return the reduced list (``None`` preserved).

        ``use_reduce_scatter=True`` runs the stage-2 wire pattern:
        reduce-scatter (each rank reduces its shard) followed by an
        all-gather of the shards, so the wire carries 2/n of the
        all-reduce gather-tier volume per direction while every rank
        still ends with the full reduced vector.
        """
        out = [None] * len(self._params)
        for bi in range(len(self._buckets)):
            red = self.exchange_bucket(bi, arrays, group=group, op=op,
                                       use_reduce_scatter=use_reduce_scatter)
            self._scatter_bucket(bi, red, arrays, out)
        return out

    def exchange_bucket(self, bi, arrays, group=None, op=None,
                        use_reduce_scatter=False):
        """Run ONE bucket's collective and return the reduced flat vector.

        This is the unit the ready-bucket scheduler dispatches
        asynchronously; ``sync_arrays`` is the barrier composition of it
        over every bucket."""
        group = group or _collective._get_default_group()
        op = op if op is not None else _collective.ReduceOp.AVG
        bucket = self._buckets[bi]
        flat = self._flatten(bucket, arrays)
        if self._quantizable(bucket):
            red = self._sync_flat_quantized(bi, bucket, flat, group, op,
                                            use_reduce_scatter)
        else:
            red = self._sync_flat_plain(bucket, flat, group, op,
                                        use_reduce_scatter)
        return np.asarray(red).ravel()

    def _scatter_bucket(self, bi, red, arrays, out):
        bucket = self._buckets[bi]
        for (i, off, numel, shape) in bucket.items:
            if arrays[i] is not None:
                out[i] = red[off:off + numel].reshape(shape).astype(
                    bucket.dtype, copy=False)

    def _sync_flat_quantized(self, bi, bucket, flat, group, op, use_rs):
        residual = self._residual(bi, flat.size)
        if not use_rs or group.nranks == 1:
            return allreduce_array(flat.astype(np.float32, copy=False),
                                   group=group, op=op,
                                   scheme=self.quantization,
                                   block_size=self.block_size,
                                   residual=residual)
        n = group.nranks
        shard_len = -(-flat.size // n)
        padded = np.zeros(n * shard_len, np.float32)
        padded[:flat.size] = flat
        if residual is not None and residual.size != padded.size:
            residual = self._residuals[bi] = np.zeros(padded.size, np.float32)
        shard = reduce_scatter_array(padded.reshape(n, shard_len),
                                     group=group, op=op,
                                     scheme=self.quantization,
                                     block_size=self.block_size,
                                     residual=residual)
        return self._gather_shards(shard, group)[:flat.size]

    def _sync_flat_plain(self, bucket, flat, group, op, use_rs):
        if not use_rs or group.nranks == 1:
            t = Tensor(flat)
            _collective.all_reduce(t, op=op, group=group)
            return t.numpy()
        n = group.nranks
        shard_len = -(-flat.size // n)
        padded = np.zeros(n * shard_len, flat.dtype)
        padded[:flat.size] = flat
        stacked = padded.reshape(n, shard_len)
        out = Tensor(np.zeros(shard_len, flat.dtype))
        _collective.reduce_scatter(out, [Tensor(stacked[i]) for i in range(n)],
                                   op=op, group=group)
        return self._gather_shards(out.numpy(), group)[:flat.size]

    @staticmethod
    def _gather_shards(shard, group):
        outs: list = []
        _collective.all_gather(outs, Tensor(np.asarray(shard)), group=group)
        return np.concatenate([np.asarray(t.numpy()).ravel() for t in outs])

    # -- parameter/gradient conveniences -------------------------------------
    def sync_grads(self, group=None, op=None, use_reduce_scatter=False):
        """Exchange the wrapped parameters' gradients in place (the
        bucketed replacement for per-tensor ``all_reduce(p.grad)``)."""
        import jax.numpy as jnp
        from ...profiler import step_phase as _step_phase
        t0 = time.perf_counter()
        arrays = [p.grad._data if getattr(p, "grad", None) is not None
                  else None for p in self._params]
        red = self.sync_arrays(arrays, group=group, op=op,
                               use_reduce_scatter=use_reduce_scatter)
        for p, r in zip(self._params, red):
            if r is not None:
                p.grad._data = jnp.asarray(r, dtype=p.grad._data.dtype)
        # barrier-path gradient exchange = un-overlapped comm time
        _step_phase.record_phase("comm_wait", time.perf_counter() - t0)
        return self

    def sync_params(self, group=None, op=None):
        """Average/reduce the parameter *values* (LocalSGD's averaging)."""
        import jax.numpy as jnp
        arrays = [p._data for p in self._params]
        red = self.sync_arrays(arrays, group=group, op=op)
        for p, r in zip(self._params, red):
            if r is not None:
                p._data = jnp.asarray(r, dtype=p._data.dtype)
        return self


# ---------------------------------------------------------------------------
# ready-bucket overlap scheduling
# ---------------------------------------------------------------------------


class _AsyncBucketWork:
    """Handle for one in-flight bucket collective queued on a scheduler's
    persistent rank worker — the thread-rank simulator's analogue of an
    async collective handle."""

    __slots__ = ("_done", "_result", "_error", "name")

    def __init__(self, name):
        self._done = threading.Event()
        self._result = None
        self._error = None
        self.name = name

    def _finish(self, result, error):
        self._result = result
        self._error = error
        self._done.set()

    def wait(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"in-flight gradient collective '{self.name}' did not "
                f"complete within {timeout}s — a peer rank likely skipped "
                f"this step (its bucket was never dispatched); disable "
                f"overlap (DistributedStrategy.comm_overlap=False / "
                f"PADDLE_COMM_OVERLAP=0) for uneven-step workloads")
        if self._error is not None:
            raise self._error
        return self._result


def _inflight_limit():
    """Concurrent in-flight bucket collectives per scheduler. One lane
    serializes the whole wire pipeline behind a single blocking exchange
    (overlap then hides at most one bucket's latency); real async
    collectives keep several transfers in flight, so the sim tier does
    too. Bounded — a thread per bucket starves the GIL-heavy backward."""
    return max(1, int(os.environ.get("PADDLE_COMM_OVERLAP_INFLIGHT", "4")))


class _RankWorker:
    """A small persistent dispatch pool per scheduler (persistent — thread
    churn measurably starves the GIL-heavy backward; per-scheduler — tags
    are namespaced per (scheduler, bucket, round), so lanes of different
    schedulers never pair). Buckets leave the queue in ready order but may
    complete out of order across lanes: each bucket's collective
    rendezvouses on its own namespaced tag, so cross-rank pairing is
    order-independent and the pipelined exchange cannot deadlock — every
    dispatched bucket eventually gets a lane, and a genuinely skipped
    rank surfaces as the handle's wait timeout."""

    def __init__(self, rank, name, nthreads=None):
        import queue
        self._q = queue.Queue()
        self._rank = rank
        self._threads = []
        for i in range(nthreads or _inflight_limit()):
            t = threading.Thread(target=self._run, name=f"{name}.{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def submit(self, fn, handle):
        self._q.put((fn, handle))

    def close(self, join_timeout=2.0):
        """Retire the lanes. Best-effort join so a failure-path shrink
        (RankFailure/TimeoutError) doesn't leak `_RankWorker` threads
        into the next world generation — lanes blocked in a dead-rank
        exchange have already been woken by ``mark_dead``."""
        for _ in self._threads:
            self._q.put(None)
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=join_timeout)
        self._threads = []

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, handle = item
            try:
                handle._finish(fn(), None)
            except BaseException as e:  # noqa: BLE001 — re-raised at wait()
                handle._finish(None, e)


class _DoneWork:
    """Handle for a bucket exchanged inline (non-simulator tiers: the
    device dispatch itself is async under jax)."""

    __slots__ = ("_result",)

    def __init__(self, result):
        self._result = result

    def wait(self, timeout=None):
        return self._result


class ReadyBucketScheduler:
    """Ready-bucket overlap driver over a :class:`GradientBucketer`.

    Fed by the tape's grad-ready hooks
    (``autograd.tape.register_grad_ready_callback``): the moment the last
    gradient of a bucket lands during backward, the bucket's (optionally
    quantized) collective is dispatched asynchronously — a worker thread
    in the thread-rank simulator tier, inline (jax async dispatch) on the
    device tiers — and :meth:`finish` at the step boundary waits only on
    the outstanding handles, dispatches any partial leftovers, and writes
    the reduced gradients back. Numerics are bit-identical to the barrier
    path: the same ``exchange_bucket`` runs per bucket, only earlier.

    ``name`` must be unique per concurrently-active scheduler (e.g. a
    ``DataParallel`` reducer and a ``HybridParallelOptimizer`` exchange on
    the same rank): it namespaces the simulator collective tags.
    """

    def __init__(self, bucketer, name="dp", group=None, op=None,
                 use_reduce_scatter=False, wait_timeout=None):
        self._b = bucketer
        self._name = name
        self._group = group
        self._op = op
        self._use_rs = bool(use_reduce_scatter)
        if wait_timeout is None:
            wait_timeout = float(
                os.environ.get("PADDLE_COMM_OVERLAP_TIMEOUT_S", "120"))
        self._wait_timeout = wait_timeout
        self._param_slot = {id(p): i for i, p in enumerate(bucketer._params)}
        self._bucket_of = {}
        for bi, bucket in enumerate(bucketer._buckets):
            for it in bucket.items:
                self._bucket_of[it[0]] = bi
        # tag namespace base: deterministic across ranks (name + bucket +
        # round), disjoint from main-thread seq counters (negative)
        self._ns = (zlib.crc32(name.encode()) & 0x3FF) + 1
        self._round = 0
        self._worker = None
        self._reset_round()

    def close(self):
        """Stop the persistent dispatch thread (called when a consumer
        replaces a stale scheduler)."""
        if self._worker is not None:
            self._worker.close()
            self._worker = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- introspection -------------------------------------------------------
    @property
    def bucketer(self):
        return self._b

    def matches(self, params):
        """True when ``params`` is exactly the layout this scheduler was
        built over (the rebuild test the consumers run per step)."""
        return [id(p) for p in self._b._params] == [id(p) for p in params]

    def _reset_round(self):
        self._pending = {bi: {it[0] for it in b.items}
                         for bi, b in enumerate(self._b._buckets)}
        self._arrays = [None] * len(self._b._params)
        self._inflight = {}
        self._dispatched = set()

    # -- in-backward path ----------------------------------------------------
    def mark_ready(self, t):
        """Grad-ready hook target. Ignores tensors outside the parameter
        set; dispatches a bucket the moment its last parameter reports."""
        i = self._param_slot.get(id(t))
        if i is None:
            return
        bi = self._bucket_of[i]
        if bi in self._dispatched:
            # a second backward before the step boundary (grad
            # accumulation without no_sync): the in-flight round is stale.
            # Every rank hits this deterministically on its first re-fired
            # param, so all drop the round together and the accumulated
            # gradients are re-exchanged fresh — step-boundary semantics
            # are preserved, only the wasted round's overlap is lost.
            self.discard()
        pend = self._pending[bi]
        pend.discard(i)
        if t.grad is not None:
            self._arrays[i] = t.grad._data
        if not pend:
            self._dispatch(bi, where="in_backward")

    def _dispatch(self, bi, where):
        group = self._group or _collective._get_default_group()
        op = self._op
        arrays = self._arrays
        _overlap_telemetry()["buckets"].inc(where=where)
        world = simulator.active_world()
        rank = simulator.current_rank()
        if world is not None:
            # ≤4 collectives per bucket exchange (rs + gather tiers); 32
            # slots of headroom per (bucket, round) namespace
            base = -(((self._ns << 34)
                      + (self._round * self._b.num_buckets + bi + 1)) << 5)

            class _SeqNamespace(dict):
                def get(self, key, default=0):
                    return dict.get(self, key, base)

            def work():
                simulator.adopt_rank(rank, _SeqNamespace())
                return self._b.exchange_bucket(
                    bi, arrays, group=group, op=op,
                    use_reduce_scatter=self._use_rs)

            if self._worker is None:
                self._worker = _RankWorker(
                    rank, name=f"comm-overlap:{self._name}:r{rank}")
            handle = _AsyncBucketWork(f"{self._name}:b{bi}")
            self._inflight[bi] = handle
            self._worker.submit(work, handle)
        else:
            self._inflight[bi] = _DoneWork(self._b.exchange_bucket(
                bi, arrays, group=group, op=op,
                use_reduce_scatter=self._use_rs))
        self._dispatched.add(bi)

    # -- step boundary -------------------------------------------------------
    def finish(self):
        """Wait on in-flight buckets, dispatch partial leftovers at the
        barrier, write reduced gradients back onto ``p.grad``. Returns
        True when any bucket was exchanged this round."""
        b = self._b
        for bi, bucket in enumerate(b._buckets):
            if bi in self._dispatched:
                continue
            # leftovers (params whose ready hook never fired — unused this
            # step, or grads carried from an earlier backward): read grads
            # straight off the parameters, barrier-style
            got = False
            for it in bucket.items:
                i = it[0]
                if self._arrays[i] is None:
                    g = getattr(b._params[i], "grad", None)
                    if g is not None:
                        self._arrays[i] = g._data
                if self._arrays[i] is not None:
                    got = True
            if got:
                self._dispatch(bi, where="at_barrier")
        t0 = time.perf_counter()
        exchanged = False
        try:
            for bi in sorted(self._inflight):
                red = self._inflight[bi].wait(self._wait_timeout)
                self._apply_bucket(bi, red)
                exchanged = True
        except TimeoutError as e:
            # release the worker lanes so the process can shrink/retry
            # without leaking _RankWorker threads, and attach the flight
            # recorder's desync view (which rank never entered which seq)
            # so the timeout is diagnosable instead of a bare hang report
            self.close()
            raise TimeoutError(f"{e}{self._desync_diagnosis()}") from None
        except BaseException:
            # structured failures (simulator.RankFailure, an injected
            # kill) propagate as-is — but never with lanes still parked
            self.close()
            raise
        finally:
            dt = time.perf_counter() - t0
            _overlap_telemetry()["wait"].observe(dt)
            # the step-boundary wait IS the comm time overlap failed to
            # hide — the "comm_wait" slice of the step-phase breakdown
            from ...profiler import step_phase as _step_phase
            _step_phase.record_phase("comm_wait", dt)
            self._round += 1
            self._reset_round()
        return exchanged

    def _desync_diagnosis(self) -> str:
        """Flight-recorder desync summary for timeout messages (empty
        when the recorder is disabled or has no cross-rank view)."""
        try:
            from ...profiler import flight_recorder as _flight
            if not _flight.is_enabled():
                return ("\n(enable PADDLE_FLIGHT_RECORDER=1 for a per-rank "
                        "desync report)")
            fr = _flight.get_flight_recorder()
            group = self._group or _collective._get_default_group()
            rep = _flight.desync_report(fr.collective_events(by_rank=True),
                                        world=group.ranks)
            lines = [f"rank {s['rank']} last entered seq {s['last_seq']}, "
                     f"never entered seq {s['missing_seq']} "
                     f"(op {s['op']!r}, entered by {s['entered_by']})"
                     for s in rep.get("stalled", [])]
            if not lines:
                return ("\nflight recorder desync report: no stalled rank "
                        f"(frontier seq {rep.get('frontier_seq')})")
            return "\nflight recorder desync report:\n  " + \
                "\n  ".join(lines)
        except Exception:
            return ""                # diagnosis must never mask the timeout

    def discard(self):
        """Drop the current round without applying results (stale grads —
        cleared, or superseded by a second backward). Waits out in-flight
        work so the rendezvous stays aligned across ranks."""
        for work in self._inflight.values():
            try:
                work.wait(self._wait_timeout)
            except Exception:
                pass
        self._round += 1
        self._reset_round()

    def _apply_bucket(self, bi, red):
        import jax.numpy as jnp
        bucket = self._b._buckets[bi]
        red = np.asarray(red).ravel()
        for (i, off, numel, shape) in bucket.items:
            p = self._b._params[i]
            if getattr(p, "grad", None) is not None:
                seg = red[off:off + numel].reshape(shape).astype(
                    bucket.dtype, copy=False)
                p.grad._data = jnp.asarray(seg, dtype=p.grad._data.dtype)
