"""Communication instrumentation: one global byte/call counter.

Every imperative collective (plain or quantized) records one entry per
*issuing rank* — ``logical_bytes`` is what the exchange would cost in the
tensor's native dtype, ``wire_bytes`` what actually crossed the wire
(int8 payload + per-block scales for the quantized path). The counter is
process-global and thread-safe so the thread-rank simulator's N ranks
aggregate into one record, queryable from ``paddle_tpu.profiler
.comm_stats()`` and emitted by ``bench.py`` (BENCH_MODEL=comm).
"""
from __future__ import annotations

import threading
from collections import defaultdict


class CommStats:
    """Counters for collective communication volume and compression."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        with getattr(self, "_lock", threading.Lock()):
            self.calls = 0
            self.logical_bytes = 0
            self.wire_bytes = 0
            self.quant_max_error = 0.0
            self.by_kind = defaultdict(lambda: {"calls": 0, "logical_bytes": 0,
                                                "wire_bytes": 0})

    def record(self, kind: str, logical_bytes: int, wire_bytes: int,
               max_error: float = 0.0):
        with self._lock:
            self.calls += 1
            self.logical_bytes += int(logical_bytes)
            self.wire_bytes += int(wire_bytes)
            if max_error > self.quant_max_error:
                self.quant_max_error = float(max_error)
            k = self.by_kind[kind]
            k["calls"] += 1
            k["logical_bytes"] += int(logical_bytes)
            k["wire_bytes"] += int(wire_bytes)

    @property
    def compression_ratio(self) -> float:
        """logical/wire — >1 means the wire was cheaper than fp32."""
        return self.logical_bytes / self.wire_bytes if self.wire_bytes else 1.0

    def as_dict(self):
        with self._lock:
            return {
                "calls": self.calls,
                "logical_bytes": self.logical_bytes,
                "wire_bytes": self.wire_bytes,
                "compression_ratio": round(self.compression_ratio, 4),
                "quant_max_error": self.quant_max_error,
                "by_kind": {k: dict(v) for k, v in self.by_kind.items()},
            }


_GLOBAL = CommStats()


def get_comm_stats() -> CommStats:
    return _GLOBAL


def reset_comm_stats():
    _GLOBAL.reset()
