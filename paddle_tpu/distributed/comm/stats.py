"""Communication instrumentation: one global byte/call counter.

Every imperative collective (plain or quantized) records one entry per
*issuing rank* — ``logical_bytes`` is what the exchange would cost in the
tensor's native dtype, ``wire_bytes`` what actually crossed the wire
(int8 payload + per-block scales for the quantized path). The counter is
process-global and thread-safe so the thread-rank simulator's N ranks
aggregate into one record, queryable from ``paddle_tpu.profiler
.comm_stats()`` and emitted by ``bench.py`` (BENCH_MODEL=comm).
"""
from __future__ import annotations

import threading
from collections import defaultdict

_TELEMETRY = None      # lazily bound registry families


def _telemetry():
    """Bridge into the unified metrics registry (profiler.telemetry):
    every CommStats.record also lands in Prometheus-exposable counters,
    so comm volume shows up next to step time / serving latency in one
    ``paddle.profiler.metrics()`` snapshot."""
    global _TELEMETRY
    if _TELEMETRY is None:
        from ...profiler.telemetry import get_registry
        r = get_registry()
        _TELEMETRY = {
            "calls": r.counter("paddle_comm_collectives_total",
                               "collective calls issued (per issuing rank)",
                               labels=("kind",)),
            "logical": r.counter("paddle_comm_logical_bytes_total",
                                 "bytes the exchange would cost in the "
                                 "tensor's native dtype", labels=("kind",)),
            "wire": r.counter("paddle_comm_wire_bytes_total",
                              "bytes that actually crossed the wire",
                              labels=("kind",)),
            "qerr": r.gauge("paddle_comm_quant_max_error",
                            "max quantization error seen since reset"),
        }
    return _TELEMETRY


class CommStats:
    """Counters for collective communication volume and compression."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        with getattr(self, "_lock", threading.Lock()):
            self.calls = 0
            self.logical_bytes = 0
            self.wire_bytes = 0
            self.quant_max_error = 0.0
            self.by_kind = defaultdict(lambda: {"calls": 0, "logical_bytes": 0,
                                                "wire_bytes": 0})

    def record(self, kind: str, logical_bytes: int, wire_bytes: int,
               max_error: float = 0.0):
        with self._lock:
            self.calls += 1
            self.logical_bytes += int(logical_bytes)
            self.wire_bytes += int(wire_bytes)
            if max_error > self.quant_max_error:
                self.quant_max_error = float(max_error)
            k = self.by_kind[kind]
            k["calls"] += 1
            k["logical_bytes"] += int(logical_bytes)
            k["wire_bytes"] += int(wire_bytes)
        tele = _telemetry()
        tele["calls"].inc(kind=kind)
        tele["logical"].inc(int(logical_bytes), kind=kind)
        tele["wire"].inc(int(wire_bytes), kind=kind)
        if max_error:
            tele["qerr"].set_max(float(max_error))

    @property
    def compression_ratio(self) -> float:
        """logical/wire — >1 means the wire was cheaper than fp32."""
        return self.logical_bytes / self.wire_bytes if self.wire_bytes else 1.0

    def as_dict(self):
        with self._lock:
            return {
                "calls": self.calls,
                "logical_bytes": self.logical_bytes,
                "wire_bytes": self.wire_bytes,
                "compression_ratio": round(self.compression_ratio, 4),
                "quant_max_error": self.quant_max_error,
                "by_kind": {k: dict(v) for k, v in self.by_kind.items()},
            }


_GLOBAL = CommStats()


def get_comm_stats() -> CommStats:
    return _GLOBAL


def reset_comm_stats():
    _GLOBAL.reset()
