"""Quantized collectives (EQuARX-style blockwise-int8 AllReduce /
ReduceScatter) over the same execution tiers as ``collective.py``:

* **thread simulator / multi-host eager** — each rank encodes its
  contribution (int8 + per-block scales, or bf16), peers exchange the
  compressed payloads through ``collective._exchange``, and every rank
  dequantizes + reduces locally. Wire volume is the compressed payload.
* **jitted device path** (no simulator, single process) — the
  quantize/dequantize round trip runs as a jitted kernel so the wire
  format's numerics apply on-device; with world size 1 the "reduction"
  is the rank's own dequantized contribution, matching the multi-rank
  per-contribution semantics.

Error feedback (the residual trick): pass ``residual`` (a fp32 numpy
array, updated in place) and the compression error of each round is
carried into the next round's input instead of being lost — the standard
EF-SGD convergence fix for biased compressors.
"""
from __future__ import annotations

import numpy as np

from ...profiler import flight_recorder as _flight
from .. import simulator
from .. import collective as _collective
from .quantization import (DEFAULT_BLOCK_SIZE, decode_wire, encode_wire,
                           dequantize_blockwise_jax, quantize_blockwise_jax)
from .stats import get_comm_stats

PASSTHROUGH = (None, "", "none", "fp32")


def _postreduce(vals, op, n):
    op = _collective._normalize_op(op)
    if op == _collective.ReduceOp.AVG:
        return np.sum(vals, axis=0) / n
    return _collective._reduce_fn(op)(vals)


def allreduce_array(flat: np.ndarray, group=None, op=None, scheme="int8",
                    block_size: int = DEFAULT_BLOCK_SIZE, residual=None,
                    kind="all_reduce_q") -> np.ndarray:
    """All-reduce a 1-D fp32 array with a compressed wire format.

    Returns the reduced fp32 array. ``residual`` (optional, in-place)
    enables error feedback.
    """
    group = group or _collective._get_default_group()
    op = op if op is not None else _collective.ReduceOp.SUM
    n = group.nranks
    flat = np.asarray(flat, np.float32).ravel()
    send = flat if residual is None else flat + residual

    in_sim = simulator.active_world() is not None
    import jax
    payload = None
    if not in_sim and jax.process_count() <= 1 and scheme == "int8":
        # device tier: the q/dq round trip is a jitted kernel
        q, scales = quantize_blockwise_jax(send, block_size)
        decoded = np.asarray(dequantize_blockwise_jax(q, scales, send.size,
                                                      block_size))
        wire = q.size * q.dtype.itemsize + scales.size * scales.dtype.itemsize
    else:
        payload, wire = encode_wire(send, scheme, block_size)
        decoded = decode_wire(payload, send.size, block_size)
    err = float(np.max(np.abs(send - decoded))) if send.size else 0.0
    if residual is not None:
        residual[:] = send - decoded
    get_comm_stats().record(kind, logical_bytes=flat.nbytes, wire_bytes=wire,
                            max_error=err)
    if n == 1:
        return _postreduce([decoded], op, 1)
    if payload is None:   # device-tier branch reached with a >1 group
        payload, _ = encode_wire(send, scheme, block_size)
    ev = _flight.collective_begin(kind, wire, group.ranks)
    try:
        got = _collective._exchange(kind, payload, group)
    finally:
        _flight.collective_end(ev)
    vals = [decode_wire(got[i], flat.size, block_size) for i in range(n)]
    return _postreduce(vals, op, n)


def reduce_scatter_array(stacked: np.ndarray, group=None, op=None,
                         scheme="int8", block_size: int = DEFAULT_BLOCK_SIZE,
                         residual=None, kind="reduce_scatter_q") -> np.ndarray:
    """Reduce-scatter with a compressed wire format.

    ``stacked``: this rank's ``[nranks, ...]`` contributions (slot *i* is
    destined for group rank *i*). Returns this rank's reduced slice.
    """
    group = group or _collective._get_default_group()
    op = op if op is not None else _collective.ReduceOp.SUM
    n = group.nranks
    stacked = np.asarray(stacked, np.float32)
    send = stacked if residual is None else stacked + residual.reshape(
        stacked.shape)
    flat = send.ravel()
    payload, wire = encode_wire(flat, scheme, block_size)
    decoded = decode_wire(payload, flat.size, block_size)
    err = float(np.max(np.abs(flat - decoded))) if flat.size else 0.0
    if residual is not None:
        residual[:] = flat - decoded
    get_comm_stats().record(kind, logical_bytes=stacked.nbytes,
                            wire_bytes=wire, max_error=err)
    if n == 1:
        return _postreduce([decoded.reshape(stacked.shape)[0]], op, 1)
    mine = group.rank
    ev = _flight.collective_begin(kind, wire, group.ranks)
    try:
        got = _collective._exchange(kind, payload, group)
    finally:
        _flight.collective_end(ev)
    slices = [decode_wire(got[i], flat.size, block_size)
              .reshape(stacked.shape)[mine] for i in range(n)]
    return _postreduce(slices, op, n)


# ---------------------------------------------------------------------------
# Tensor-level API (paddle semantics: mutate in place, return a task)
# ---------------------------------------------------------------------------


def all_reduce_quantized(tensor, op=None, group=None, scheme="int8",
                         block_size: int = DEFAULT_BLOCK_SIZE, residual=None,
                         sync_op=True):
    """``dist.all_reduce`` with a blockwise-quantized wire format.

    ``scheme``: ``"int8"`` (blockwise, per-block scale), ``"bf16"``
    (cast passthrough), or None/"fp32" → delegates to the plain dense
    all-reduce. ``residual`` (fp32 numpy array of the flattened tensor's
    size, updated in place) enables error feedback.
    """
    if scheme in PASSTHROUGH:
        return _collective.all_reduce(tensor, op=op if op is not None
                                      else _collective.ReduceOp.SUM,
                                      group=group)
    arr = _collective._np(tensor)
    red = allreduce_array(arr.ravel(), group=group, op=op, scheme=scheme,
                          block_size=block_size, residual=residual)
    _collective._write_back(tensor, red.reshape(arr.shape))
    return _collective._Task()


def reduce_scatter_quantized(tensor, tensor_list, op=None, group=None,
                             scheme="int8",
                             block_size: int = DEFAULT_BLOCK_SIZE,
                             residual=None, sync_op=True):
    """``dist.reduce_scatter`` with a blockwise-quantized wire format."""
    if scheme in PASSTHROUGH:
        return _collective.reduce_scatter(tensor, tensor_list,
                                          op=op if op is not None
                                          else _collective.ReduceOp.SUM,
                                          group=group)
    stacked = np.stack([_collective._np(t) for t in tensor_list])
    shard = reduce_scatter_array(stacked, group=group, op=op, scheme=scheme,
                                 block_size=block_size, residual=residual)
    _collective._write_back(tensor, shard)
    return _collective._Task()
