"""Blockwise symmetric int8 wire codec (EQuARX-style, arXiv:2506.17615).

The gradient vector is split into fixed-size blocks; each block carries one
fp32 scale = max|block| / 127 and its values as int8 ``round(x / scale)``.
Round-trip error is bounded per block by ``scale / 2 = max|block| / 254``.
Wire cost: 1 byte/element + 4 bytes/block (≈25.4% of fp32 at block 256).

Two implementations with identical numerics (both round half-to-even):

* numpy — the thread-rank simulator / host ``_exchange`` path;
* jitted jax — the device path (quantize/dequantize compile into the
  step so wire-format parity holds without leaving the device).

``bf16`` is the cheap passthrough tier: cast to bfloat16 on the wire
(50% of fp32), no scales.
"""
from __future__ import annotations

import functools

import numpy as np

DEFAULT_BLOCK_SIZE = 256

#: quantization schemes understood by the comm layer; None/"" is fp32
SCHEMES = ("int8", "bf16")


def _padded(x: np.ndarray, block_size: int) -> np.ndarray:
    pad = (-x.size) % block_size
    if pad:
        x = np.concatenate([x, np.zeros(pad, x.dtype)])
    return x


def quantize_blockwise(arr, block_size: int = DEFAULT_BLOCK_SIZE):
    """fp32 array -> (int8 values incl. zero padding, fp32 per-block scales)."""
    x = _padded(np.asarray(arr, np.float32).ravel(), block_size)
    blocks = x.reshape(-1, block_size)
    maxabs = np.max(np.abs(blocks), axis=1)
    # guard the COMPUTED scale: maxabs/127 of a denormal-tiny block can
    # underflow to 0 in fp32 even when maxabs > 0 (error-feedback
    # residuals get that small) — a zero scale would divide-by-zero
    scales = (maxabs / np.float32(127.0)).astype(np.float32)
    scales = np.where(scales > 0, scales, np.float32(1.0))
    q = np.clip(np.rint(blocks / scales[:, None]), -127, 127).astype(np.int8)
    return q.reshape(-1), scales


def dequantize_blockwise(q, scales, numel: int,
                         block_size: int = DEFAULT_BLOCK_SIZE) -> np.ndarray:
    """Inverse of :func:`quantize_blockwise`; returns fp32 of ``numel``."""
    deq = (np.asarray(q).reshape(-1, block_size).astype(np.float32)
           * np.asarray(scales, np.float32)[:, None])
    return deq.reshape(-1)[:numel]


@functools.lru_cache(maxsize=32)
def _quantize_jit(block_size: int):
    import jax
    import jax.numpy as jnp

    def f(x):
        blocks = x.reshape(-1, block_size)
        maxabs = jnp.max(jnp.abs(blocks), axis=1)
        s = maxabs / 127.0   # see numpy codec: guard the computed scale
        scales = jnp.where(s > 0, s, 1.0)
        q = jnp.clip(jnp.rint(blocks / scales[:, None]),
                     -127, 127).astype(jnp.int8)
        return q.reshape(-1), scales

    return jax.jit(f)


@functools.lru_cache(maxsize=32)
def _dequantize_jit(block_size: int):
    import jax
    import jax.numpy as jnp

    def f(q, scales):
        return (q.reshape(-1, block_size).astype(jnp.float32)
                * scales[:, None]).reshape(-1)

    return jax.jit(f)


def quantize_blockwise_jax(arr, block_size: int = DEFAULT_BLOCK_SIZE):
    """Device-path quantizer: jitted, same numerics as the numpy codec."""
    import jax.numpy as jnp
    x = jnp.asarray(arr, jnp.float32).ravel()
    pad = (-x.size) % block_size
    if pad:
        x = jnp.concatenate([x, jnp.zeros(pad, jnp.float32)])
    return _quantize_jit(block_size)(x)


def dequantize_blockwise_jax(q, scales, numel: int,
                             block_size: int = DEFAULT_BLOCK_SIZE):
    return _dequantize_jit(block_size)(q, scales)[:numel]


def _bf16_dtype():
    import ml_dtypes
    return ml_dtypes.bfloat16


def encode_wire(arr: np.ndarray, scheme, block_size: int):
    """Encode one rank's contribution for the wire.

    Returns ``(payload, wire_bytes)`` — payload is what peers receive
    (pytree of numpy arrays, so both the rendezvous simulator and
    ``multihost_utils.process_allgather`` can carry it).
    """
    if scheme == "int8":
        q, scales = quantize_blockwise(arr, block_size)
        return ("int8", q, scales), q.nbytes + scales.nbytes
    if scheme == "bf16":
        b = np.asarray(arr, _bf16_dtype())
        return ("bf16", b), b.nbytes
    raise ValueError(f"unknown comm quantization scheme {scheme!r} "
                     f"(expected one of {SCHEMES})")


def decode_wire(payload, numel: int, block_size: int) -> np.ndarray:
    tag = payload[0]
    if tag == "int8":
        return dequantize_blockwise(payload[1], payload[2], numel, block_size)
    if tag == "bf16":
        return np.asarray(payload[1], np.float32).ravel()[:numel]
    raise ValueError(f"unknown wire payload tag {tag!r}")
