"""Parameter-server RPC tier: length-prefixed binary protocol over TCP.

Reference: ``paddle/fluid/distributed/ps/service/`` (brpc handlers for
pull_sparse/push_sparse, server registry — SURVEY.md §2.1). The brpc
stack is GPU-cluster plumbing; here the wire is a ~60-byte fixed header
plus raw little-endian numpy buffers, so a pull of 100k×64 rows is one
25 MB read straight into an ndarray — no serialization layer to feed
the host CPUs that should be feeding the TPU.

Frame: ``[u32 len][u8 op][u32 table][u32 n][u32 dim]`` then ``n`` int64
keys then (push ops) ``n*dim`` float32 payload. CONFIG/SAVE/LOAD carry a
JSON body instead. Responses: ``[u32 len][u8 status]`` + payload.
"""
from __future__ import annotations

import json
import os
import socket
import socketserver
import struct
import threading

import numpy as np

from .table import SparseTable

OP_CONFIG, OP_PULL, OP_PUSH_GRAD, OP_PUSH_DELTA = 0, 1, 2, 3
OP_SAVE, OP_LOAD, OP_STATS, OP_SHUTDOWN = 4, 5, 6, 7
_HDR = struct.Struct("<BIII")


def _read_exact(sock, n):
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed")
        got += r
    return bytes(buf)


def _send_frame(sock, *parts):
    body = b"".join(parts)
    sock.sendall(struct.pack("<I", len(body)) + body)


def _recv_frame(sock):
    (n,) = struct.unpack("<I", _read_exact(sock, 4))
    return _read_exact(sock, n)


class PSServer:
    """One parameter-server process/thread: hosts this shard's tables and
    answers pull/push RPCs until SHUTDOWN."""

    def __init__(self, host="127.0.0.1", port=0):
        self._tables: dict[int, SparseTable] = {}
        self._tlock = threading.Lock()
        self._shutdown = threading.Event()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        frame = _recv_frame(self.request)
                        resp = outer._dispatch(frame)
                        _send_frame(self.request, resp)
                        if frame[0] == OP_SHUTDOWN:
                            return
                except (ConnectionError, OSError):
                    return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = Server((host, port), Handler)
        self.host, self.port = self._srv.server_address[:2]
        self._thread = None

    @property
    def endpoint(self):
        return f"{self.host}:{self.port}"

    def start(self):
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def run(self):
        """Blocking serve (fleet.run_server)."""
        self.start()
        self._shutdown.wait()
        self._srv.shutdown()

    def stop(self):
        self._shutdown.set()
        # shutdown() blocks on an event only serve_forever() sets — calling
        # it on a never-started server would wait forever
        if self._thread is not None and self._thread.is_alive():
            self._srv.shutdown()
        self._srv.server_close()

    # -- dispatch -----------------------------------------------------------
    def _table(self, tid):
        with self._tlock:
            t = self._tables.get(tid)
        if t is None:
            raise KeyError(f"table {tid} not configured")
        return t

    def _dispatch(self, frame):
        op, tid, n, dim = _HDR.unpack_from(frame)
        body = frame[_HDR.size:]
        try:
            if op == OP_CONFIG:
                cfg = json.loads(body.decode())
                with self._tlock:
                    t = self._tables.get(tid)
                    if t is None:
                        self._tables[tid] = SparseTable(**cfg)
                    else:
                        # a second trainer must see the LIVE config or an
                        # error — never silently train under different
                        # optimizer/lr than it asked for
                        want = {"dim": int(cfg.get("dim", t.dim)),
                                "optimizer": cfg.get("optimizer",
                                                     t.optimizer),
                                "lr": float(cfg.get("lr", t.lr)),
                                "initializer": cfg.get("initializer",
                                                       t.initializer)}
                        have = {"dim": t.dim, "optimizer": t.optimizer,
                                "lr": t.lr, "initializer": t.initializer}
                        if want != have:
                            return (b"\x01" + f"table {tid} already exists "
                                    f"with {have}, requested {want}"
                                    .encode())
                return b"\x00"
            if op == OP_PULL:
                keys = np.frombuffer(body, "<i8", n)
                rows = self._table(tid).pull(keys)
                return b"\x00" + rows.astype("<f4", copy=False).tobytes()
            if op in (OP_PUSH_GRAD, OP_PUSH_DELTA):
                keys = np.frombuffer(body, "<i8", n)
                vals = np.frombuffer(body, "<f4", n * dim,
                                     offset=n * 8).reshape(n, dim)
                t = self._table(tid)
                (t.push_grad if op == OP_PUSH_GRAD else t.push_delta)(
                    keys, vals)
                return b"\x00"
            if op == OP_SAVE:
                path = json.loads(body.decode())["path"]
                st = self._table(tid).state()
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                np.savez(path, **st)
                return b"\x00"
            if op == OP_LOAD:
                path = json.loads(body.decode())["path"]
                with np.load(path) as z:
                    self._table(tid).load_state(
                        {k: z[k] for k in ("keys", "rows", "acc")})
                return b"\x00"
            if op == OP_STATS:
                with self._tlock:
                    stats = {str(k): t.size() for k, t in self._tables.items()}
                return b"\x00" + json.dumps(stats).encode()
            if op == OP_SHUTDOWN:
                self._shutdown.set()
                threading.Thread(target=self._srv.shutdown,
                                 daemon=True).start()
                return b"\x00"
            return b"\x01unknown op"
        except Exception as e:            # noqa: BLE001 — report to client
            return b"\x01" + repr(e).encode()[:500]


class PSClient:
    """Trainer-side stub: shards keys over servers by ``key % n_servers``
    (the reference's sparse-shard rule), issues per-server RPCs, and
    reassembles rows in request order. ``async_push=True`` queues pushes
    to a background thread — the reference's async-SGD trainer loop."""

    def __init__(self, endpoints, async_push=False):
        if isinstance(endpoints, str):
            endpoints = [e for e in endpoints.replace(";", ",").split(",")
                         if e]
        self.endpoints = list(endpoints)
        self._created: set[int] = set()
        self._socks = [None] * len(self.endpoints)
        self._locks = [threading.Lock() for _ in self.endpoints]
        self._async = bool(async_push)
        self.push_errors = 0
        self._last_push_error = None
        # eager: lazy creation would race between the drain thread and the
        # main thread (ThreadPoolExecutor spawns workers on demand, so an
        # unused pool costs nothing)
        from concurrent.futures import ThreadPoolExecutor
        self._pool = (ThreadPoolExecutor(max_workers=len(self.endpoints),
                                         thread_name_prefix="ps-client")
                      if len(self.endpoints) > 1 else None)
        self._closed = False
        self._q = None
        self._pusher = None
        if self._async:
            import queue
            self._q = queue.Queue(maxsize=256)
            self._pusher = threading.Thread(target=self._drain, daemon=True)
            self._pusher.start()

    def _sock(self, i):
        if self._socks[i] is None:
            host, port = self.endpoints[i].rsplit(":", 1)
            s = socket.create_connection((host, int(port)), timeout=60)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks[i] = s
        return self._socks[i]

    def _call(self, i, op, tid, n, dim, body):
        with self._locks[i]:
            try:
                s = self._sock(i)
                _send_frame(s, _HDR.pack(op, tid, n, dim), body)
                resp = _recv_frame(s)
            except (OSError, ConnectionError):
                # a dead or mid-frame socket must not be reused — drop it
                # so the next call reconnects cleanly
                if self._socks[i] is not None:
                    try:
                        self._socks[i].close()
                    except OSError:
                        pass
                    self._socks[i] = None
                raise
        if resp[:1] != b"\x00":
            raise RuntimeError(f"PS error from {self.endpoints[i]}: "
                               f"{resp[1:].decode(errors='replace')}")
        return resp[1:]

    def _shard(self, keys):
        keys = np.asarray(keys, np.int64).ravel()
        sid = keys % len(self.endpoints)
        return keys, sid

    # -- API ----------------------------------------------------------------
    def create_table(self, table_id, **cfg):
        body = json.dumps(cfg).encode()
        for i in range(len(self.endpoints)):
            self._call(i, OP_CONFIG, table_id, 0, 0, body)
        self._created.add(int(table_id))

    def next_auto_table_id(self):
        """Smallest id this client hasn't configured — lets layers
        auto-assign tables without colliding with user-created ids."""
        return max(self._created, default=-1) + 1

    def _fanout(self, shard_calls):
        """Run one RPC per involved shard CONCURRENTLY — per-batch latency
        on the embedding hot path must not scale with shard count."""
        if len(shard_calls) == 1 or self._pool is None:
            return [fn() for fn in shard_calls]
        return [f.result() for f in
                [self._pool.submit(fn) for fn in shard_calls]]

    def pull(self, table_id, keys):
        keys, sid = self._shard(keys)
        masks = [(i, sid == i) for i in range(len(self.endpoints))]
        masks = [(i, m) for i, m in masks if m.any()]

        def one(i, mask):
            sub = keys[mask]
            raw = self._call(i, OP_PULL, table_id, len(sub), 0,
                             sub.astype("<i8").tobytes())
            return mask, np.frombuffer(raw, "<f4").reshape(len(sub), -1)

        results = self._fanout([(lambda i=i, m=m: one(i, m))
                                for i, m in masks])
        out = None
        for mask, rows in results:
            if out is None:
                out = np.empty((len(keys), rows.shape[1]), np.float32)
            out[mask] = rows
        return out if out is not None else np.empty((0, 0), np.float32)

    def _push(self, op, table_id, keys, vals):
        keys, sid = self._shard(keys)
        vals = np.asarray(vals, np.float32).reshape(len(keys), -1)
        dim = vals.shape[1]

        def one(i, mask):
            sub, sv = keys[mask], vals[mask]
            self._call(i, op, table_id, len(sub), dim,
                       sub.astype("<i8").tobytes()
                       + sv.astype("<f4", copy=False).tobytes())

        masks = [(i, sid == i) for i in range(len(self.endpoints))]
        self._fanout([(lambda i=i, m=m: one(i, m))
                      for i, m in masks if m.any()])

    def push_grad(self, table_id, keys, grads):
        if self._async:
            if self._closed:
                raise RuntimeError("PSClient is closed")
            self._q.put((OP_PUSH_GRAD, table_id,
                         np.array(keys, np.int64, copy=True),
                         np.array(grads, np.float32, copy=True)))
        else:
            self._push(OP_PUSH_GRAD, table_id, keys, grads)

    def push_delta(self, table_id, keys, deltas):
        self._push(OP_PUSH_DELTA, table_id, keys, deltas)

    def _drain(self):
        import warnings
        while True:
            item = self._q.get()
            if item is None:              # close() sentinel — exit thread
                self._q.task_done()
                return
            op, tid, keys, vals = item
            try:
                self._push(op, tid, keys, vals)
            except Exception as e:        # noqa: BLE001 — record, don't die
                self.push_errors += 1
                self._last_push_error = e
                if self.push_errors == 1:
                    warnings.warn(f"PS async push failed (further failures "
                                  f"counted silently): {e!r}",
                                  RuntimeWarning)
            finally:
                self._q.task_done()

    def flush(self, raise_on_error=True):
        """Wait for queued pushes; by default surface any drops — an async
        job must not run to completion with a shard silently frozen."""
        if self._q is not None:
            self._q.join()
        if raise_on_error and self.push_errors:
            n, err = self.push_errors, self._last_push_error
            self.push_errors, self._last_push_error = 0, None
            raise RuntimeError(
                f"{n} async sparse push(es) were dropped; last error: "
                f"{err!r}")

    def save(self, table_id, path_prefix):
        for i in range(len(self.endpoints)):
            body = json.dumps(
                {"path": f"{path_prefix}.shard{i}.npz"}).encode()
            self._call(i, OP_SAVE, table_id, 0, 0, body)

    def load(self, table_id, path_prefix):
        for i in range(len(self.endpoints)):
            body = json.dumps(
                {"path": f"{path_prefix}.shard{i}.npz"}).encode()
            self._call(i, OP_LOAD, table_id, 0, 0, body)

    def stats(self, shard=0):
        return json.loads(self._call(shard, OP_STATS, 0, 0, 0, b"").decode())

    def shutdown_servers(self):
        for i in range(len(self.endpoints)):
            try:
                self._call(i, OP_SHUTDOWN, 0, 0, 0, b"")
            except (RuntimeError, OSError, ConnectionError):
                pass

    def close(self):
        self._closed = True
        self.flush(raise_on_error=False)
        if self._q is not None and self._pusher is not None \
                and self._pusher.is_alive():
            self._q.put(None)             # sentinel: stop the drain thread
            self._pusher.join(timeout=10)
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        for s in self._socks:
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._socks = [None] * len(self.endpoints)
