"""Parameter-server mode (reference: ``paddle/fluid/distributed/ps/`` +
``python/paddle/distributed/ps/the_one_ps.py`` — SURVEY.md §2.1/§2.3).

SURVEY §7.4 scoped this to note-only for the TPU build; this module
closes the row with a working TPU-native re-design rather than a brpc
port: host-resident sharded :class:`SparseTable`s behind a raw-numpy
socket RPC (:class:`PSServer`/:class:`PSClient`), and a
:class:`DistributedEmbedding` layer whose backward pushes sparse grads
through the autograd tape's accumulation hook. The TPU device only ever
sees dense pulled rows — the jit'd dense step is unchanged.

Role wiring (``fleet.init(role_maker, is_collective=False)`` +
``fleet.run_server()`` / ``init_worker()``) lives in
``paddle_tpu.distributed.fleet``.
"""
from .table import SparseTable
from .service import PSClient, PSServer
from .layers import DistributedEmbedding

__all__ = ["SparseTable", "PSClient", "PSServer", "DistributedEmbedding"]
