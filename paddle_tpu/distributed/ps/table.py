"""Host-side sparse embedding tables for parameter-server mode.

Reference: ``paddle/fluid/distributed/ps/table/`` (memory_sparse_table,
ctr accessors — SURVEY.md §2.1 "Parameter server"): unbounded-id
embedding rows created on first touch, with the optimizer applied ON THE
SERVER so trainers exchange only (keys, grads) — never the full table.

TPU-native rethink: the table is host-resident numpy (embedding tables
at recsys scale never fit HBM); the device sees only the dense pulled
rows, so the TPU step stays a pure dense jit program. Rows live in one
growable 2-D arena + a key->slot dict so pull/push are vectorized
fancy-indexing over the arena, not per-key Python."""
from __future__ import annotations

import threading

import numpy as np


class SparseTable:
    """One shard of a distributed embedding table.

    ``optimizer``: applied server-side on ``push_grad`` —
      * ``"sgd"``:      row -= lr * g
      * ``"adagrad"``:  acc += g²; row -= lr * g / (sqrt(acc) + eps)
        (the reference's default sparse accessor family).
    ``push_delta`` merges trainer-local deltas (geo-SGD mode) without
    touching optimizer state.
    """

    def __init__(self, dim, optimizer="adagrad", lr=0.05, eps=1e-8,
                 initializer="uniform", init_range=0.01, seed=0):
        self.dim = int(dim)
        self.optimizer = optimizer
        self.lr = float(lr)
        self.eps = float(eps)
        self.initializer = initializer
        self.init_range = float(init_range)
        self._seed = int(seed)
        self._slots: dict[int, int] = {}
        self._cap = 0
        self._n = 0
        self._rows = np.empty((0, self.dim), np.float32)
        self._acc = np.empty((0, self.dim), np.float32)
        self._lock = threading.Lock()

    # -- storage ------------------------------------------------------------
    def _grow(self, need):
        cap = max(64, self._cap)
        while cap < need:
            cap *= 2
        pad = cap - self._cap
        self._rows = np.concatenate(
            [self._rows, np.zeros((pad, self.dim), np.float32)])
        self._acc = np.concatenate(
            [self._acc, np.zeros((pad, self.dim), np.float32)])
        self._cap = cap

    def _init_rows(self, keys):
        """Deterministic per-key init: the same key hashes to the same row
        on every shard/restart, so sync-parity tests and elastic restarts
        see identical tables. Vectorized counter-based hash (splitmix64
        finalizer over key x column) — a cold 100k-key pull must not run
        per-key Python under the table lock."""
        if self.initializer == "zeros":
            return np.zeros((len(keys), self.dim), np.float32)
        k = (np.asarray(keys, np.int64).astype(np.uint64)[:, None]
             * np.uint64(1000003) + np.uint64(self._seed))
        z = k + np.arange(self.dim, dtype=np.uint64)[None, :] \
            * np.uint64(0x9E3779B97F4A7C15)
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        z ^= z >> np.uint64(31)
        unit = (z >> np.uint64(11)).astype(np.float64) * (2.0 ** -53)
        return ((2.0 * unit - 1.0) * self.init_range).astype(np.float32)

    def _index(self, keys, create):
        idx = np.empty(len(keys), np.int64)
        missing = []
        for i, k in enumerate(keys):
            slot = self._slots.get(int(k), -1)
            if slot < 0 and create:
                missing.append((i, int(k)))
            idx[i] = slot
        if missing:
            need = self._n + len(missing)
            if need > self._cap:
                self._grow(need)
            new_keys = [k for _, k in missing]
            self._rows[self._n:need] = self._init_rows(new_keys)
            for j, (i, k) in enumerate(missing):
                slot = self._n + j
                self._slots[k] = slot
                idx[i] = slot
            self._n = need
        return idx

    # -- RPC surface --------------------------------------------------------
    def pull(self, keys: np.ndarray) -> np.ndarray:
        with self._lock:
            idx = self._index(keys, create=True)
            return self._rows[idx].copy()

    def push_grad(self, keys: np.ndarray, grads: np.ndarray) -> None:
        grads = np.asarray(grads, np.float32).reshape(len(keys), self.dim)
        uniq, inv = np.unique(np.asarray(keys, np.int64), return_inverse=True)
        summed = np.zeros((len(uniq), self.dim), np.float32)
        np.add.at(summed, inv, grads)
        with self._lock:
            idx = self._index(uniq, create=True)
            if self.optimizer == "adagrad":
                self._acc[idx] += summed * summed
                self._rows[idx] -= (self.lr * summed
                                    / (np.sqrt(self._acc[idx]) + self.eps))
            else:
                self._rows[idx] -= self.lr * summed

    def push_delta(self, keys: np.ndarray, deltas: np.ndarray) -> None:
        deltas = np.asarray(deltas, np.float32).reshape(len(keys), self.dim)
        with self._lock:
            idx = self._index(np.asarray(keys, np.int64), create=True)
            np.add.at(self._rows, idx, deltas)

    # -- checkpoint ---------------------------------------------------------
    def state(self):
        with self._lock:
            keys = np.fromiter(self._slots.keys(), np.int64,
                               len(self._slots))
            idx = np.fromiter(self._slots.values(), np.int64,
                              len(self._slots))
            return {"keys": keys, "rows": self._rows[idx],
                    "acc": self._acc[idx]}

    def clear(self):
        with self._lock:
            self._slots.clear()
            self._cap = self._n = 0
            self._rows = np.empty((0, self.dim), np.float32)
            self._acc = np.empty((0, self.dim), np.float32)

    def load_state(self, st):
        """Full restore: the table becomes exactly the checkpoint (keys
        created since the save are dropped, matching a real restart)."""
        self.clear()
        keys, rows, acc = st["keys"], st["rows"], st["acc"]
        with self._lock:
            idx = self._index(keys, create=True)
            self._rows[idx] = rows
            self._acc[idx] = acc

    def size(self):
        with self._lock:
            return self._n
