"""Trainer-side sparse layers for parameter-server mode.

Reference: ``python/paddle/distributed/ps/the_one_ps.py`` +
``paddle.static.nn.sparse_embedding`` (SURVEY.md §2.3 "PS mode"): an
embedding whose weight lives on the parameter servers; forward pulls
only the rows this batch touches, backward pushes only their gradients.

TPU-native shape: the pull happens on the host (eager, per batch), the
pulled rows become a dense [unique, dim] device tensor, and everything
downstream — gather, dense net, loss, backward — is ordinary tape
autograd on device. The tape's gradient hook on the pulled-rows leaf is
the push: sparse grads leave for the server the moment they are
accumulated, which IS async-SGD when the client queues pushes.

Modes (reference ``DistributedStrategy`` a_sync/geo):
* ``"sync"``  — push blocks until the server applied the update.
* ``"async"`` — pushes drain on a background thread (a_sync=True).
* ``"geo"``   — trainer-local SGD on a cached copy; accumulated deltas
  are merged into the server every ``geo_k`` steps and the cache is
  refreshed (geo-SGD).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...framework.core import Tensor
from ...nn.layer import Layer
from ...ops.manipulation import gather as _gather
from ...ops.manipulation import reshape as _reshape


class DistributedEmbedding(Layer):
    """Embedding backed by a :class:`~..ps.service.PSClient` table."""

    def __init__(self, embedding_dim, client, table_id=None, mode="async",
                 optimizer="adagrad", learning_rate=0.05,
                 initializer="uniform", init_range=0.01, geo_k=8,
                 name=None):
        super().__init__(name_scope=name)
        if mode not in ("sync", "async", "geo"):
            raise ValueError(f"mode must be sync/async/geo, got {mode!r}")
        self.embedding_dim = int(embedding_dim)
        self.client = client
        if table_id is None:
            table_id = client.next_auto_table_id()
        self.table_id = int(table_id)
        self.mode = mode
        self.geo_k = int(geo_k)
        self._geo_lr = float(learning_rate)
        # geo: key -> [row (local), delta (pending merge)]
        self._geo_cache: dict[int, list] = {}
        self._geo_step = 0
        client.create_table(
            self.table_id, dim=self.embedding_dim,
            # geo trainers own the optimizer locally; the server only merges
            optimizer="sgd" if mode == "geo" else optimizer,
            lr=learning_rate, initializer=initializer,
            init_range=init_range)

    # -- geo-SGD cache ------------------------------------------------------
    def _geo_rows(self, uniq):
        missing = [k for k in uniq if int(k) not in self._geo_cache]
        if missing:
            pulled = self.client.pull(self.table_id,
                                      np.asarray(missing, np.int64))
            for k, r in zip(missing, pulled):
                self._geo_cache[int(k)] = [r.copy(),
                                           np.zeros_like(r)]
        return np.stack([self._geo_cache[int(k)][0] for k in uniq])

    def _geo_apply(self, uniq, grad):
        for k, g in zip(uniq, grad):
            ent = self._geo_cache[int(k)]
            upd = self._geo_lr * g
            ent[0] -= upd
            ent[1] -= upd
        self._geo_step += 1
        if self._geo_step % self.geo_k == 0:
            keys = np.fromiter(self._geo_cache.keys(), np.int64,
                               len(self._geo_cache))
            deltas = np.stack([self._geo_cache[int(k)][1] for k in keys])
            touched = np.abs(deltas).sum(axis=1) > 0
            if touched.any():
                self.client.push_delta(self.table_id, keys[touched],
                                       deltas[touched])
            fresh = self.client.pull(self.table_id, keys)
            for k, r in zip(keys, fresh):
                self._geo_cache[int(k)] = [r.copy(), np.zeros_like(r)]

    # -- forward ------------------------------------------------------------
    def forward(self, ids):
        ids_np = np.asarray(ids._data if isinstance(ids, Tensor)
                            else ids).astype(np.int64)
        uniq, inv = np.unique(ids_np, return_inverse=True)
        if self.mode == "geo":
            rows_np = self._geo_rows(uniq)
        else:
            rows_np = self.client.pull(self.table_id, uniq)
        rows = Tensor(jnp.asarray(rows_np), stop_gradient=False)

        def _push(grad):
            g = np.asarray(grad._data if isinstance(grad, Tensor)
                           else grad, np.float32)
            if self.mode == "geo":
                self._geo_apply(uniq, g)
            else:
                self.client.push_grad(self.table_id, uniq, g)
            return grad

        if self.training:
            rows.register_hook(_push)
        out = _gather(rows, Tensor(jnp.asarray(inv, jnp.int32)), axis=0)
        return _reshape(out, tuple(ids_np.shape) + (self.embedding_dim,))

    def extra_repr(self):
        return (f"dim={self.embedding_dim}, table={self.table_id}, "
                f"mode={self.mode}, servers={len(self.client.endpoints)}")
