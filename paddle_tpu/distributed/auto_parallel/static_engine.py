"""Auto-parallel Engine (reference: ``python/paddle/distributed/
auto_parallel/static/engine.py`` — ``Engine(model, loss, optimizer,
strategy).fit/evaluate/predict/prepare``: completion propagates dist attrs,
the partitioner emits per-rank programs; SURVEY.md §2.3 "Auto-parallel").

TPU-native: "completion + partitioner" is the XLA SPMD partitioner. Engine
builds ONE jitted sharded train step from the model's parameter placements
(or its ``sharding_rules()``) over the global mesh, with donated buffers —
the per-rank program emission happens inside XLA at compile time.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...framework.core import Tensor
from ...framework.functional import FunctionalModule
from .. import mesh as mesh_mod


class Engine:
    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 strategy=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.strategy = strategy
        self._step_fn = None
        self._state = None

    # -- build the sharded step --------------------------------------------
    def prepare(self, inputs_spec=None, labels_spec=None, mode="train"):
        mesh = mesh_mod.get_mesh()
        fm = FunctionalModule(self.model, training=(mode == "train"))
        rules = getattr(type(self.model), "sharding_rules", None)
        if rules is not None:
            specs = fm.param_specs(rules())
        else:
            specs = [P() for _ in fm.params]
        p_sh = [NamedSharding(mesh, s) for s in specs]
        lr = 0.001
        if self.optimizer is not None:
            lr_attr = getattr(self.optimizer, "_learning_rate", 0.001)
            lr = float(lr_attr() if callable(lr_attr) else lr_attr)
        loss_layer = self.loss

        p_arrs = [jax.device_put(a, s)
                  for a, s in zip(fm.param_arrays(), p_sh)]
        if mode == "train":
            m_arrs = [jax.device_put(jnp.zeros_like(a), s)
                      for a, s in zip(p_arrs, p_sh)]
            v_arrs = [jax.device_put(jnp.zeros_like(a), s)
                      for a, s in zip(p_arrs, p_sh)]
        else:
            # eval-only prepare: no optimizer state, no train step —
            # 3x less device memory for inference use
            m_arrs, v_arrs = [], []
        b_arrs = fm.buffer_arrays()      # frozen for the engine's step
        self._state = {"fm": fm, "p": p_arrs, "m": m_arrs, "v": v_arrs,
                       "t": 0, "mesh": mesh, "p_sh": p_sh, "b": b_arrs,
                       "mode": mode}
        if mode != "train":
            self._step_fn = None
            return self
        b1, b2, eps = 0.9, 0.999, 1e-8

        def step(p_arrs, m_arrs, v_arrs, t, key, x, y):
            def loss_fn(ps):
                out, _ = fm(ps, b_arrs, key, x)
                if loss_layer is not None:
                    lo = loss_layer(Tensor(out) if not isinstance(out, Tensor)
                                    else out, Tensor(y))
                    return lo._data if isinstance(lo, Tensor) else lo
                return out.mean()

            loss, grads = jax.value_and_grad(loss_fn)(p_arrs)
            t = t + 1
            new_p, new_m, new_v = [], [], []
            for pa, g, mm, vv in zip(p_arrs, grads, m_arrs, v_arrs):
                mm = b1 * mm + (1 - b1) * g
                vv = b2 * vv + (1 - b2) * g * g
                mhat = mm / (1 - b1 ** t)
                vhat = vv / (1 - b2 ** t)
                new_p.append(pa - lr * mhat / (jnp.sqrt(vhat) + eps))
                new_m.append(mm)
                new_v.append(vv)
            return loss, new_p, new_m, new_v, t

        self._step_fn = jax.jit(step, donate_argnums=(0, 1, 2))
        return self

    def _data_axes(self):
        mesh = self._state["mesh"]
        return tuple(a for a in ("dp", "sharding") if a in mesh.shape
                     and mesh.shape[a] > 1)

    def _data_sharding(self):
        """Shard batch dim over the mesh's data axes when present (the
        completion pass's input annotation in the reference)."""
        axes = self._data_axes()
        return NamedSharding(self._state["mesh"], P(axes if axes else None))

    def _put_batch(self, x, y):
        mesh = self._state["mesh"]
        axes = self._data_axes()
        div = int(np.prod([mesh.shape[a] for a in axes])) if axes else 1
        sh = self._data_sharding()
        xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        ya = y._data if isinstance(y, Tensor) else jnp.asarray(y)
        # both operands must divide the data axes; a ragged one falls
        # back to replicated rather than crashing mid-epoch
        if xa.shape[0] % div == 0 and ya.shape[0] % div == 0:
            xa = jax.device_put(xa, sh)
            ya = jax.device_put(ya, sh)
        return xa, ya

    def fit(self, train_data, epochs=1, batch_size=None, steps_per_epoch=None,
            valid_data=None, log_freq=10, verbose=0):
        from ...io import DataLoader
        if self._step_fn is None or self._state.get("mode") != "train":
            # a step compiled by evaluate() ran with training=False
            # (dropout/BN off) — training must rebuild it
            self.prepare(mode="train")
        st = self._state
        loader = train_data if isinstance(train_data, DataLoader) \
            else DataLoader(train_data, batch_size=batch_size or 8)
        history = []
        for epoch in range(epochs):
            for i, batch in enumerate(loader):
                xa, ya = self._put_batch(batch[0], batch[1])
                key = st["fm"].next_key()
                loss, st["p"], st["m"], st["v"], st["t"] = self._step_fn(
                    st["p"], st["m"], st["v"], st["t"], key, xa, ya)
                history.append(float(loss))
                if verbose and i % log_freq == 0:
                    print(f"epoch {epoch} step {i} loss {history[-1]:.4f}")
                if steps_per_epoch and i + 1 >= steps_per_epoch:
                    break
            if valid_data is not None:
                ev = self.evaluate(valid_data, batch_size=batch_size)
                if verbose:
                    print(f"epoch {epoch} eval_loss {ev['loss']:.4f}")
        # write trained params back into the eager model
        self._sync_back()
        return history

    def evaluate(self, eval_data, batch_size=None, steps=None):
        """Mean loss over ``eval_data`` with the current sharded params
        (reference ``Engine.evaluate``)."""
        from ...io import DataLoader
        if self._state is None:
            self.prepare(mode="eval")
        st = self._state

        if "eval_fn" not in st:
            fm_eval = FunctionalModule(self.model, training=False)
            loss_layer = self.loss
            b_arrs = st["b"]

            def eval_step(p_arrs, key, x, y):
                out, _ = fm_eval(p_arrs, b_arrs, key, x)
                if loss_layer is not None:
                    lo = loss_layer(Tensor(out), Tensor(y))
                    return lo._data if isinstance(lo, Tensor) else lo
                return out.mean()
            st["eval_fn"] = jax.jit(eval_step)
        loader = eval_data if isinstance(eval_data, DataLoader) \
            else DataLoader(eval_data, batch_size=batch_size or 8)
        losses = []
        for i, batch in enumerate(loader):
            xa, ya = self._put_batch(batch[0], batch[1])
            losses.append(float(st["eval_fn"](st["p"], st["fm"].next_key(),
                                              xa, ya)))
            if steps and i + 1 >= steps:
                break
        return {"loss": float(np.mean(losses)) if losses else float("nan")}

    def cost(self, seq_len=None, global_batch=None, chip=None):
        """Tuner-estimated step time/memory for the CURRENT mesh degrees
        (reference ``Engine.cost``): the analytic cost model scores the
        layout the engine will compile."""
        from .cost_model import CostModel, ModelSpec
        cfg = getattr(self.model, "config", None)
        if cfg is None:
            raise ValueError("Engine.cost needs a model with .config "
                             "(transformer shape)")
        mesh = mesh_mod.get_mesh()
        degrees = {a: int(mesh.shape[a]) if a in mesh.shape else 1
                   for a in ("dp", "pp", "sharding", "sep", "mp")}
        if chip is None:
            plat = jax.devices()[0].device_kind.lower()
            chip = next((k for k in ("v6e", "v5p", "v5e", "v4")
                         if k in plat), "v5e")
        spec = ModelSpec.from_config(cfg, seq_len=seq_len,
                                     global_batch=global_batch or 8)
        from ..mesh import _slice_major
        n_slices = _slice_major(jax.devices())[1]
        cm = CostModel(chip=chip, n_slices=n_slices)
        t, breakdown = cm.step_time(spec, degrees)
        return {"step_time_s": t, "mem_per_chip": cm.memory_per_chip(
            spec, degrees), "degrees": degrees, **breakdown}

    def save(self, path):
        """Persist the engine's (sharded) parameters + optimizer state."""
        st = self._state
        if st is None:
            raise RuntimeError("call prepare() first")
        np.savez(path, t=st["t"],
                 **{f"p_{i}": np.asarray(a) for i, a in enumerate(st["p"])},
                 **{f"m_{i}": np.asarray(a) for i, a in enumerate(st["m"])},
                 **{f"v_{i}": np.asarray(a) for i, a in enumerate(st["v"])})

    def load(self, path):
        if self._step_fn is None:
            self.prepare()
        st = self._state
        data = np.load(path if str(path).endswith(".npz") else f"{path}.npz")
        n = len(st["p"])
        st["p"] = [jax.device_put(data[f"p_{i}"], s)
                   for i, s in zip(range(n), st["p_sh"])]
        # eval-prepared engines save params only; a params-only checkpoint
        # must not leave moments computed for the OLD weights paired with
        # the new ones — reset them
        if "m_0" in data:
            st["m"] = [jax.device_put(data[f"m_{i}"], s)
                       for i, s in zip(range(n), st["p_sh"])]
            st["v"] = [jax.device_put(data[f"v_{i}"], s)
                       for i, s in zip(range(n), st["p_sh"])]
        else:
            st["m"] = [jnp.zeros_like(p) for p in st["p"]]
            st["v"] = [jnp.zeros_like(p) for p in st["p"]]
        st["t"] = int(data["t"])
        self._sync_back()
        return self

    def _sync_back(self):
        st = self._state
        for p, a in zip(st["fm"].params, st["p"]):
            p._data = a

    def predict(self, x):
        st = self._state
        fm = FunctionalModule(self.model, training=False)
        xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        out, _ = fm(st["p"], st["b"], fm.next_key(), xa)
        return Tensor(out)

    @property
    def main_program(self):
        """Lowered HLO text of the sharded step (Program analogue)."""
        return "<jitted SPMD step; inspect via .lowered_text()>"

    def lowered_text(self, *example_args):
        if self._step_fn is None:
            raise RuntimeError("call prepare() first")
        return self._step_fn.lower(*example_args).as_text()
