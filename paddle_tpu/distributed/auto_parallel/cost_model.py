"""Auto-parallel cost model + mesh tuner (reference:
``python/paddle/distributed/auto_parallel/static/cost/`` — per-op
comp/comm cost classes rolled up by the rule-based ``tuner/``; SURVEY.md
§2.3 "Auto-parallel ... cost model/tuner").

TPU-native re-design: instead of per-op cost objects over a ProgramDesc,
an ANALYTIC roofline for transformer train steps over the hybrid mesh
``[dp, pp, sharding, sep, mp]`` (the scaling-book recipe):

* compute   = train FLOPs / (chips · peak · efficiency)
* TP comm   = 2 allreduces of [B·S/chips_b, H] per layer over the mp axis
* DP/ZeRO   = grad reduce-scatter + param all-gather over dp·sharding
* PP bubble = (pp-1)/(micro+pp-1) multiplier
* memory/chip = params·(2+opt)/shard + activations — plans that do not
  fit HBM are rejected before timing.

``Tuner.tune`` enumerates degree factorizations of the chip count and
returns ranked ``Plan``s. Estimates steer the search; measured profiles
(profiler.mfu) refine them — same contract as the reference tuner."""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field

from ...profiler.mfu import PEAK_FLOPS, transformer_train_flops

# per-chip hardware characteristics (peak bf16 FLOP/s shared with
# profiler.mfu so tuner estimates and measured MFU agree; HBM bytes,
# ICI GB/s per link — conservative public numbers)
CHIPS = {
    "v4": dict(flops=PEAK_FLOPS["v4"], hbm=32e9, ici=100e9, dcn=6.25e9),
    "v5e": dict(flops=PEAK_FLOPS["v5e"], hbm=16e9, ici=50e9, dcn=6.25e9),
    "v5p": dict(flops=PEAK_FLOPS["v5p"], hbm=95e9, ici=100e9, dcn=6.25e9),
    "v6e": dict(flops=PEAK_FLOPS["v6e"], hbm=32e9, ici=100e9, dcn=6.25e9),
}


@dataclass
class ModelSpec:
    """Transformer shape (derivable from LlamaConfig/GPTConfig)."""
    num_layers: int
    hidden: int
    intermediate: int
    vocab: int
    seq_len: int
    global_batch: int
    num_heads: int = 0
    bytes_per_param: int = 4          # fp32 master params
    optimizer_states: int = 2         # adam m+v

    kv_heads: int = 0                 # GQA; 0 = MHA

    @classmethod
    def from_config(cls, cfg, seq_len=None, global_batch=1):
        return cls(
            num_layers=cfg.num_hidden_layers,
            hidden=cfg.hidden_size,
            intermediate=getattr(cfg, "intermediate_size",
                                 4 * cfg.hidden_size),
            vocab=cfg.vocab_size,
            seq_len=seq_len or getattr(cfg, "max_position_embeddings", 2048),
            global_batch=global_batch,
            num_heads=getattr(cfg, "num_attention_heads", 0),
            kv_heads=getattr(cfg, "num_key_value_heads", 0),
        )

    @property
    def n_params(self):
        """GQA-accurate count (mirrors profiler.mfu.llama_param_count)."""
        head_dim = self.hidden // self.num_heads if self.num_heads else 0
        kv = (self.kv_heads or self.num_heads) * head_dim if head_dim \
            else self.hidden
        per_layer = (2 * self.hidden * self.hidden          # q, o
                     + 2 * self.hidden * kv                 # k, v
                     + 3 * self.hidden * self.intermediate)
        return (self.num_layers * per_layer
                + 2 * self.vocab * self.hidden)             # embed + head

    def train_flops(self):
        """Shared formula with profiler.mfu (causal attention term)."""
        return transformer_train_flops(
            self.n_params, self.global_batch * self.seq_len,
            num_layers=self.num_layers, hidden_size=self.hidden,
            seq_len=self.seq_len, causal=True)


@dataclass
class Plan:
    degrees: dict
    step_time_s: float
    mem_per_chip: float
    breakdown: dict = field(default_factory=dict)

    def __repr__(self):
        d = {k: v for k, v in self.degrees.items() if v > 1} or {"dp": 1}
        return (f"Plan({d}, step={self.step_time_s * 1e3:.1f}ms, "
                f"mem={self.mem_per_chip / 1e9:.1f}GB)")


class CostModel:
    def __init__(self, chip="v5p", mfu_target=0.45, micro_batches=8,
                 recompute=True, n_slices=1):
        """``n_slices``: DCN-connected slice count. mesh.init_mesh puts
        slice boundaries on the outermost (dp) axis, so when the dp
        degree spans slices its grad collectives ride DCN bandwidth,
        not ICI — the cost model must price that or multi-slice plans
        look free."""
        self.hw = CHIPS[chip] if isinstance(chip, str) else chip
        self.eff = mfu_target
        self.micro = micro_batches
        self.recompute = recompute
        self.n_slices = max(int(n_slices), 1)

    # -- memory ---------------------------------------------------------------
    def memory_per_chip(self, m: ModelSpec, d: dict):
        # ZeRO state shards over the 'sharding' axis ONLY (what the
        # runtime's shard_spec_for actually does); plain dp replicates it
        shard = d["sharding"]
        model_parallel = d["mp"] * d["pp"]
        params = m.n_params * m.bytes_per_param / model_parallel
        # params + grads + opt states sharded by ZeRO (stage-3 semantics)
        state = params * (2 + m.optimizer_states) / shard + params / shard
        per_chip_tokens = (m.global_batch * m.seq_len
                           / (d["dp"] * d["sharding"] * d["sep"]))
        act_factor = 4 if self.recompute else 12
        acts = act_factor * per_chip_tokens * m.hidden \
            * (m.num_layers / d["pp"]) * 2 / max(self.micro, 1)
        return state + acts

    # -- time -----------------------------------------------------------------
    def step_time(self, m: ModelSpec, d: dict):
        chips = 1
        for v in d.values():
            chips *= v
        compute = m.train_flops() / (chips * self.hw["flops"] * self.eff)
        # PP bubble stretches compute
        bubble = (d["pp"] - 1) / (self.micro + d["pp"] - 1) if d["pp"] > 1 else 0.0
        compute *= 1.0 / (1.0 - bubble) if bubble < 1 else float("inf")

        ici = self.hw["ici"]
        toks_per_chip = (m.global_batch * m.seq_len
                         / (d["dp"] * d["sharding"] * d["sep"]))
        # TP: 2 allreduces of the activation per layer over mp
        tp = 0.0
        if d["mp"] > 1:
            vol = 2 * m.num_layers * toks_per_chip * m.hidden * 2  # bf16
            tp = 2 * vol * (d["mp"] - 1) / d["mp"] / ici
        # grads: reduce-scatter + all-gather over the dp·sharding group.
        # Multi-slice: the group decomposes hierarchically — intra-slice
        # legs ride ICI, the inter-slice leg rides DCN (mesh.init_mesh
        # guarantees only the outer dp axis crosses slices)
        data = d["dp"] * d["sharding"]
        dpc = 0.0
        if data > 1:
            gbytes = m.n_params * 2 / (d["mp"] * d["pp"])
            if self.n_slices > 1:
                # hierarchical allreduce: intra-slice reduce-scatter on
                # ICI leaves each chip a gbytes/intra shard; only that
                # shard crosses DCN. Keyed on the mesh contract (slice
                # boundaries live on the dp axis; Tuner._valid rejects
                # dp not divisible by n_slices).
                intra = max(data // self.n_slices, 1)
                s = self.n_slices
                dpc = (2 * gbytes * (intra - 1) / intra / ici
                       + 2 * (gbytes / intra) * (s - 1) / s
                       / self.hw.get("dcn", 6.25e9))
            else:
                dpc = 2 * gbytes * (data - 1) / data / ici
        # sep (context parallel): ring K/V exchange per layer
        sp = 0.0
        if d["sep"] > 1:
            kv = m.num_layers * toks_per_chip * m.hidden * 2 * 2
            sp = kv * (d["sep"] - 1) / d["sep"] / ici
        # per-collective launch latency: small, but it is what makes a
        # plain-DP plan win for models where every plan's bandwidth
        # terms round to zero
        lat = 5e-6
        launches = (2 * m.num_layers * (d["mp"] > 1)
                    + 2 * m.num_layers * (d["sep"] > 1)
                    + 2 * (data > 1)
                    + self.micro * 2 * (d["pp"] > 1))
        overhead = lat * launches
        dpc_eff = dpc * 0.5     # grad comm overlaps the backward pass
        return (compute + tp + sp + dpc_eff + overhead,
                {"compute_s": compute, "tp_s": tp, "dp_s": dpc_eff,
                 "dp_raw_s": dpc, "sp_s": sp, "bubble": bubble,
                 "latency_s": overhead})


class Tuner:
    """Enumerate mesh-degree factorizations; reject plans that overflow
    HBM or violate divisibility; rank by estimated step time (reference:
    the rule-based + cost-model tuner)."""

    AXES = ("dp", "pp", "sharding", "sep", "mp")

    def __init__(self, cost_model: CostModel | None = None, chip="v5p",
                 max_mp=8, max_pp=16, n_slices=1):
        self.cm = cost_model or CostModel(chip=chip, n_slices=n_slices)
        self.max_mp = max_mp
        self.max_pp = max_pp

    def _factorizations(self, n):
        divs = [d for d in range(1, n + 1) if n % d == 0]
        for dp, pp, shd, sep, mp in itertools.product(divs, repeat=5):
            if dp * pp * shd * sep * mp == n:
                yield {"dp": dp, "pp": pp, "sharding": shd, "sep": sep,
                       "mp": mp}

    def _valid(self, m: ModelSpec, d: dict):
        if d["mp"] > self.max_mp or d["pp"] > self.max_pp:
            return False
        if d["mp"] > 1 and (m.hidden % d["mp"] or
                            (m.num_heads and m.num_heads % d["mp"])):
            return False
        if d["pp"] > 1 and m.num_layers % d["pp"]:
            return False
        if d["sep"] > 1 and m.seq_len % d["sep"]:
            return False
        if m.global_batch % (d["dp"] * d["sharding"]):
            return False
        # mesh.init_mesh contract: slice boundaries sit on the dp axis,
        # so multi-slice plans need dp divisible by the slice count
        if self.cm.n_slices > 1 and d["dp"] % self.cm.n_slices:
            return False
        return True

    def tune(self, model, n_devices, seq_len=None, global_batch=None,
             top_k=3):
        m = model if isinstance(model, ModelSpec) else ModelSpec.from_config(
            model, seq_len=seq_len, global_batch=global_batch or 8)
        plans = []
        hbm = self.cm.hw["hbm"]
        n_div_ok = 0
        for d in self._factorizations(n_devices):
            if not self._valid(m, d):
                continue
            n_div_ok += 1
            mem = self.cm.memory_per_chip(m, d)
            if mem > 0.9 * hbm:
                continue
            t, br = self.cm.step_time(m, d)
            plans.append(Plan(d, t, mem, br))
        plans.sort(key=lambda p: p.step_time_s)
        if not plans:
            if n_div_ok == 0:
                raise ValueError(
                    f"no valid plan for {n_devices} chips: every degree "
                    "assignment violates divisibility (layers % pp, "
                    "hidden/heads % mp, seq % sep, batch % dp*sharding) — "
                    "adjust the shapes/batch, not the chip count")
            raise ValueError(
                f"no valid plan for {n_devices} chips: the model does not "
                f"fit 90% of HBM under any degree assignment (try more "
                "chips, recompute, or a smaller micro-batch)")
        return plans[:top_k]
