"""Sharding completion — inspectable (reference:
``python/paddle/distributed/auto_parallel/static/completion.py``, the
dist-attr propagation pass that annotates every op/tensor in the program;
SURVEY.md §2.3 "Auto-parallel").

TPU-native: propagation itself is GSPMD — XLA's sharding propagation
derives every intermediate placement from the input/param annotations at
compile time. What the reference additionally offers — and round 3 lacked
(VERDICT missing item 6) — is *visibility*: the ability to inspect and
structurally test what the completer inferred, the way the reference's
``test/auto_parallel/`` suites assert dist-attrs. :class:`Completer`
compiles the program with the given placements and reads back:

* resolved **input/output shardings** as ``NamedSharding``s (exact specs),
* every **intermediate op's** propagated sharding, captured per framework
  op (``linear``, ``matmul``, ``softmax`` …) by threading
  ``jax.debug.inspect_array_sharding`` through the tape's dispatch hook
  during the completion trace,

so a test can assert "the matmul output is split over ('dp', 'mp')" or
"no intermediate fell back to replicated" against the REAL compiled
program, not a shadow analysis.
"""
from __future__ import annotations

import re

__all__ = ["Completer", "ShardingReport"]


def _spec_of(sharding):
    spec = getattr(sharding, "spec", None)
    return tuple(spec) if spec is not None else None


class ShardingReport:
    """What GSPMD inferred for one compiled program."""

    def __init__(self, input_shardings, output_shardings, op_shardings):
        self.input_shardings = input_shardings      # [NamedSharding]
        self.output_shardings = output_shardings    # [NamedSharding]
        self.op_shardings = op_shardings            # [(op label, Sharding)]

    # -- structural assertions (test surface) -------------------------------
    def input_spec(self, i):
        return _spec_of(self.input_shardings[i])

    def output_spec(self, i=0):
        return _spec_of(self.output_shardings[i])

    def op_specs(self, pattern=None):
        """(label, PartitionSpec-tuple-or-str) pairs, optionally filtered
        by a regex over the op label (e.g. ``r"matmul|linear"``)."""
        rx = re.compile(pattern) if pattern is not None else None
        out = []
        for label, sh in self.op_shardings:
            if rx is None or rx.search(label):
                spec = _spec_of(sh)
                out.append((label, spec if spec is not None else str(sh)))
        return out

    def histogram(self):
        """{spec/sharding repr: count} over all captured ops — the quick
        'did anything fall back to replicated' check."""
        out: dict = {}
        for _, spec in self.op_specs():
            key = str(spec)
            out[key] = out.get(key, 0) + 1
        return out

    def __repr__(self):
        return (f"ShardingReport(inputs="
                f"{[str(self.input_spec(i)) for i in range(len(self.input_shardings))]}, "
                f"outputs="
                f"{[str(_spec_of(s)) for s in self.output_shardings]}, "
                f"captured_ops={len(self.op_shardings)})")


class Completer:
    """Run GSPMD completion for ``fn`` under ``mesh`` and report every
    inferred placement.

    ``in_placements``: per-argument PartitionSpec/NamedSharding (None →
    derive from the argument's committed sharding, or replicate)."""

    def __init__(self, mesh=None):
        from .. import mesh as mesh_mod
        self.mesh = mesh if mesh is not None else mesh_mod.get_mesh()

    def _to_sharding(self, placement):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        mesh = getattr(self.mesh, "_mesh", self.mesh)   # ProcessMesh shim
        if placement is None:
            return None
        if isinstance(placement, jax.sharding.Sharding):
            return placement
        if isinstance(placement, (tuple, list)):
            placement = PartitionSpec(*placement)
        return NamedSharding(mesh, placement)

    def complete(self, fn, *example_args, in_placements=None) -> ShardingReport:
        import jax

        from ...autograd import tape as _tape
        from ...framework.core import Tensor

        arrs = [a._data if hasattr(a, "_data") else a for a in example_args]
        if in_placements is None:
            in_shardings = [getattr(a, "sharding", None) for a in arrs]
        else:
            in_shardings = [self._to_sharding(p) for p in in_placements]

        records: list = []

        def hook(name, out):
            leaves = jax.tree.leaves(
                out, is_leaf=lambda x: isinstance(x, Tensor))
            for leaf in leaves:
                arr = leaf._data if isinstance(leaf, Tensor) else leaf
                if not isinstance(arr, jax.core.Tracer):
                    continue
                slot = [f"{name}#{len(records)}", None]
                records.append(slot)
                jax.debug.inspect_array_sharding(
                    arr, callback=lambda sh, s=slot: s.__setitem__(1, sh))

        def pure(*xs):
            out = fn(*[Tensor(x) if not isinstance(x, Tensor) else x
                       for x in xs])
            return jax.tree.map(
                lambda t: t._data if hasattr(t, "_data") else t, out,
                is_leaf=lambda x: isinstance(x, Tensor))

        mesh = getattr(self.mesh, "_mesh", self.mesh)
        prev = _tape._op_inspect[0]
        _tape._op_inspect[0] = hook
        try:
            with mesh:
                compiled = jax.jit(pure, in_shardings=in_shardings).lower(
                    *arrs).compile()
        finally:
            _tape._op_inspect[0] = prev
        ins = compiled.input_shardings[0]
        ins = list(ins) if isinstance(ins, (tuple, list)) else [ins]
        outs = compiled.output_shardings
        outs = list(outs) if isinstance(outs, (tuple, list)) else [outs]
        ops = [(label, sh) for label, sh in records if sh is not None]
        return ShardingReport(ins, outs, ops)
