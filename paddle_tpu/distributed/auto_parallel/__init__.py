"""Semi-automatic parallelism API (reference:
``python/paddle/distributed/auto_parallel/`` — 3.0 dygraph flavor:
``ProcessMesh``, placements ``Shard(d)``/``Replicate``/``Partial``,
``shard_tensor``, ``dtensor_from_fn``, ``reshard``, ``shard_optimizer``;
SURVEY.md §2.3 "Auto-parallel").

TPU-native: the reference's completion/partitioner pipeline (propagate
dist-attrs through a static Program, split per rank, insert collectives) IS
XLA's GSPMD propagation — users annotate a few tensors, the partitioner
infers the rest. So here ``ProcessMesh`` wraps ``jax.sharding.Mesh``,
placements translate to ``PartitionSpec`` dims, ``shard_tensor`` is a
``device_put``/``with_sharding_constraint``, and everything between the
annotations is completed by the XLA SPMD partitioner at jit time.
``Partial(sum)`` (pending-reduction values) has no public NamedSharding
form — it exists transiently inside XLA; the API accepts it for parity and
materializes the reduced (replicated) value.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...framework.core import Tensor, Parameter
from ...autograd.tape import apply
from .. import mesh as mesh_mod

__all__ = [
    "ProcessMesh", "Shard", "Replicate", "Partial", "shard_tensor",
    "dtensor_from_fn", "reshard", "shard_optimizer", "get_mesh", "set_mesh",
    "Engine", "CostModel", "Tuner", "ModelSpec", "Plan",
    "Completer", "ShardingReport",
]

from .static_engine import Engine  # noqa: E402
from .cost_model import CostModel, Tuner, ModelSpec, Plan  # noqa: E402
from .completion import Completer, ShardingReport  # noqa: E402


# ---------------------------------------------------------------------------
# placements
# ---------------------------------------------------------------------------

class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    """Shard tensor dim ``dim`` along this mesh axis."""

    def __init__(self, dim):
        self.dim = int(dim)

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def get_dim(self):
        return self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, o):
        return isinstance(o, Shard) and o.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))


class Replicate(Placement):
    def is_replicated(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, o):
        return isinstance(o, Replicate)

    def __hash__(self):
        return hash("replicate")


class Partial(Placement):
    """Pending reduction along this mesh axis (reference ``Partial``)."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial({self.reduce_type})"

    def __eq__(self, o):
        return isinstance(o, Partial) and o.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("partial", self.reduce_type))


# ---------------------------------------------------------------------------
# ProcessMesh
# ---------------------------------------------------------------------------

class ProcessMesh:
    """N-D mesh of ranks with named dims (reference ProcessMesh). Ranks index
    into ``jax.devices()``; the jax Mesh is built lazily."""

    def __init__(self, mesh, dim_names=None, shape=None, process_ids=None):
        if mesh is not None:
            arr = np.asarray(mesh)
        else:
            arr = np.asarray(process_ids).reshape(shape)
        self._ranks = arr
        self.dim_names = list(dim_names) if dim_names else \
            [f"d{i}" for i in range(arr.ndim)]
        assert len(self.dim_names) == arr.ndim
        self._jax_mesh = None

    @property
    def shape(self):
        return list(self._ranks.shape)

    @property
    def process_ids(self):
        return self._ranks.flatten().tolist()

    @property
    def ndim(self):
        return self._ranks.ndim

    def get_dim_size(self, name):
        return self._ranks.shape[self.dim_names.index(name)]

    def jax_mesh(self) -> Mesh:
        if self._jax_mesh is None:
            devs = jax.devices()
            dev_arr = np.vectorize(lambda r: devs[r % len(devs)])(self._ranks)
            self._jax_mesh = Mesh(dev_arr, tuple(self.dim_names))
        return self._jax_mesh

    def __eq__(self, o):
        return (isinstance(o, ProcessMesh)
                and np.array_equal(o._ranks, self._ranks)
                and o.dim_names == self.dim_names)

    def __repr__(self):
        return (f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names},"
                f" process_ids={self.process_ids})")


_auto_mesh: ProcessMesh | None = None


def set_mesh(mesh: ProcessMesh):
    global _auto_mesh
    _auto_mesh = mesh


def get_mesh() -> ProcessMesh | None:
    return _auto_mesh


# ---------------------------------------------------------------------------
# shard / reshard
# ---------------------------------------------------------------------------

def _to_named_sharding(mesh: ProcessMesh, placements):
    placements = list(placements)
    while len(placements) < mesh.ndim:
        placements.append(Replicate())
    ndim_map = {}
    for axis_name, pl in zip(mesh.dim_names, placements):
        if isinstance(pl, Shard):
            d = pl.dim
            if d in ndim_map:         # two axes shard the same tensor dim
                prev = ndim_map[d]
                ndim_map[d] = (prev if isinstance(prev, tuple)
                               else (prev,)) + (axis_name,)
            else:
                ndim_map[d] = axis_name
    return mesh.jax_mesh(), ndim_map


def _spec_for(ndim, ndim_map):
    return PartitionSpec(*[ndim_map.get(i) for i in range(ndim)])


def shard_tensor(data, mesh: ProcessMesh, placements, dtype=None,
                 stop_gradient=None):
    """Place a Tensor (or array-like) on the mesh per ``placements`` (one per
    mesh dim). Returns a Tensor whose ``.placements``/``.process_mesh``
    mirror the reference dist-tensor attributes."""
    t = data if isinstance(data, Tensor) else Tensor(data, dtype=dtype)
    jmesh, ndim_map = _to_named_sharding(mesh, placements)
    sh = NamedSharding(jmesh, _spec_for(t.ndim, ndim_map))

    def fn(a):
        if isinstance(a, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(a, sh)
        return jax.device_put(a, sh)

    out = apply(fn, t, op_name="shard_tensor")
    if isinstance(t, Parameter):
        out2 = Parameter(out._data, name=t.name)
        out2.stop_gradient = t.stop_gradient
        out = out2
    out.process_mesh = mesh
    out.placements = list(placements)
    if stop_gradient is not None:
        out.stop_gradient = stop_gradient
    return out


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(dist_tensor, mesh: ProcessMesh, placements):
    """Transfer to a (possibly different) mesh/placement layout — the
    reference inserts comm ops; XLA derives them from the device_put."""
    return shard_tensor(dist_tensor, mesh, placements)


def shard_optimizer(optimizer, shard_fn=None):
    """Shard optimizer states like their parameters (reference
    ``shard_optimizer``). States created as ``zeros_like(param)`` inherit
    the param's sharding automatically under jax; this re-places any states
    that already exist and marks the optimizer so checkpoints record specs."""
    params = [p for p in getattr(optimizer, "_parameter_list", []) or []
              if p is not None]
    accs = getattr(optimizer, "_accumulators", None)
    if accs:
        by_name = {p.name: p for p in params}
        for acc_dict in accs.values():
            for pname, acc in acc_dict.items():
                p = by_name.get(pname)
                if p is None or not isinstance(p._data, jax.Array):
                    continue
                if isinstance(acc._data, jax.Array) \
                        and acc._data.shape == p._data.shape:
                    acc._data = jax.device_put(acc._data, p._data.sharding)
    optimizer._auto_parallel_sharded = True
    return optimizer
