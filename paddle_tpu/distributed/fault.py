"""Deterministic fault injection for elastic/chaos testing (ROADMAP 5).

A :class:`FaultPlan` is a list of :class:`Fault` directives — kill rank R
at step S, kill rank R before its N-th tracked collective, delay rank
R by T seconds, or poison rank R's next gradient with NaN — installed
programmatically (:func:`install`) or via the ``PADDLE_FAULT_PLAN`` env
knob. Training loops call :func:`check_step`
at every step boundary; the thread-rank simulator calls the collective
hook at every rendezvous exchange entry (``simulator._FAULT_HOOK`` —
installed only while a plan is active, so the no-plan path stays a
single ``None`` check).

Kill semantics in the simulator: the victim rank is marked dead in the
``SimWorld`` *before* :class:`SimulatedRankKill` unwinds its thread, so
survivors blocked in ``_Rendezvous.exchange`` (or the overlap
scheduler's ``finish()``) immediately surface a structured
:class:`RankFailure` naming the dead rank — no hang, no timeout. Delay
faults just sleep: the rank straggles but lives, which must produce a
flight-recorder straggler report and NO shrink.

Env grammar (``;``-separated directives, ``kind:key=value,...``)::

    PADDLE_FAULT_PLAN="kill:rank=2,step=5"
    PADDLE_FAULT_PLAN="kill:rank=2,seq=12;delay:rank=1,step=3,seconds=0.5"
    PADDLE_FAULT_PLAN="nan:rank=2,step=5"
    PADDLE_FAULT_PLAN="bitflip:rank=2,step=5"
    PADDLE_FAULT_PLAN="kill:replica=r1,request=4"
    PADDLE_FAULT_PLAN="stall:replica=r0,seconds=0.5"

``nan`` faults (numerics chaos — the testable trigger for the
``profiler.tensor_stats`` sentinel) arm the tape's one-shot
:func:`~paddle_tpu.autograd.tape.poison_next_leaf_grad` on the firing
rank's thread: the first leaf gradient its next backward finalizes gets
a NaN before the grad bucket is dispatched, so the poison travels the
same path (grad-ready hook → bucket collective) a real blow-up would.
Step triggers are the natural fit (the poison lands on the rank's own
training thread); seq triggers arm whichever thread entered the
collective.

``bitflip`` faults (silent-corruption chaos — the testable trigger for
the ``profiler.ledger`` determinism observatory) arm the tape's
one-shot :func:`~paddle_tpu.autograd.tape.flip_bit_next_leaf_grad`
through the same once-only machinery: the first leaf gradient the
rank's next backward finalizes gets a single low bit flipped AT THE END
of backward (after the overlap scheduler's synced-grad write-back), so
in data-parallel training the corruption stays rank-local — too small
for the NaN sentinel, exactly what the ledger's cross-rank digest
comparison must catch.

**Serving-fleet directives** (ISSUE 14 — chaos for the fleet control
plane) target a *replica* instead of a rank and trigger on the
replica's N-th routed request (``request=N``, default 1; the
``ServingRouter`` calls :func:`check_fleet_route` each time it routes a
request to a replica):

* ``kill:replica=R,request=N`` — the router hard-kills replica ``R``
  the moment its N-th request is routed (engine aborted, in-flight work
  requeued to survivors) — the mid-burst replica death the
  ``FleetController`` acceptance scenario injects;
* ``stall:replica=R,seconds=T[,request=N]`` — replica ``R``'s serve
  loop sleeps ``T`` seconds at the next tick boundary (a GC pause /
  preempted-host straggler: the replica lives and heartbeats, it just
  stops making progress — SLO burn, no death signal).

Every fault fires at most once. Each firing is recorded as a
flight-recorder event and counted in
``paddle_elastic_events_total{kind="kill"|"delay"|"nan"|"bitflip"|"stall"}``.
"""
from __future__ import annotations

import os
import threading
import time

from . import simulator
from .simulator import RankFailure, SimulatedRankKill  # noqa: F401 (re-export)

__all__ = [
    "Fault", "FaultPlan", "RankFailure", "SimulatedRankKill",
    "install", "clear", "active_plan", "check_step", "check_fleet_route",
    "elastic_telemetry", "FLEET_FAULT_KINDS",
]

#: fault kinds that target a serving-fleet replica (``replica=`` key)
#: rather than a training rank; each appears in docs/ROBUSTNESS.md and
#: is exercised by a test (tools/check_inventory.py enforces both)
FLEET_FAULT_KINDS = ("kill", "stall")

_ELASTIC_TELEMETRY = None


def elastic_telemetry():
    """Registry families shared by the fault harness and the elastic
    train loop (supervisor.py)."""
    global _ELASTIC_TELEMETRY
    if _ELASTIC_TELEMETRY is None:
        from ..profiler.telemetry import get_registry
        r = get_registry()
        _ELASTIC_TELEMETRY = {
            "events": r.counter(
                "paddle_elastic_events_total",
                "elastic/fault lifecycle events (kill, delay, "
                "failure_detected, shrink, regrow, restore, checkpoint)",
                labels=("kind",)),
            "ckpt_async": r.histogram(
                "paddle_ckpt_async_seconds",
                "wall seconds each async checkpoint write spent off the "
                "critical path"),
        }
    return _ELASTIC_TELEMETRY


class Fault:
    """One directive. Rank faults: ``kind`` is ``"kill"``/``"delay"``/
    ``"nan"``/``"bitflip"``; exactly one of ``step`` (fires at that step
    boundary) / ``seq`` (fires before the rank's seq-th tracked
    collective, 1-based) selects the trigger; ``seconds`` is the sleep
    for delay faults. Fleet faults: ``replica=`` targets a serving
    replica instead, ``kind`` is ``"kill"`` or ``"stall"``, and the
    trigger is the replica's ``request``-th routed request (1-based,
    default 1); ``seconds`` is the stall duration."""

    __slots__ = ("kind", "rank", "step", "seq", "seconds", "fired",
                 "replica", "request")

    def __init__(self, kind, rank=None, step=None, seq=None, seconds=0.0,
                 replica=None, request=None):
        if replica is not None:
            if kind not in FLEET_FAULT_KINDS:
                raise ValueError(
                    f"unknown fleet fault kind {kind!r} (replica faults "
                    f"are one of {'/'.join(FLEET_FAULT_KINDS)})")
            if rank is not None or step is not None or seq is not None:
                raise ValueError("replica faults trigger on request=N "
                                 "(not rank/step/seq)")
            if kind == "stall" and seconds <= 0:
                raise ValueError("stall fault needs seconds > 0")
            self.kind = kind
            self.rank = None
            self.step = None
            self.seq = None
            self.seconds = float(seconds)
            self.replica = str(replica)
            self.request = max(int(1 if request is None else request), 1)
            self.fired = False
            return
        if kind not in ("kill", "delay", "nan", "bitflip"):
            raise ValueError(f"unknown fault kind {kind!r} "
                             "(expected 'kill', 'delay', 'nan' or "
                             "'bitflip')")
        if rank is None:
            raise ValueError("a rank fault needs rank=")
        if request is not None:
            raise ValueError("request= triggers need replica= (fleet "
                             "faults)")
        if (step is None) == (seq is None):
            raise ValueError("a fault needs exactly one trigger: "
                             "step=... or seq=...")
        if kind == "delay" and seconds <= 0:
            raise ValueError("delay fault needs seconds > 0")
        self.kind = kind
        self.rank = int(rank)
        self.step = None if step is None else int(step)
        self.seq = None if seq is None else int(seq)
        self.seconds = float(seconds)
        self.replica = None
        self.request = None
        self.fired = False

    def __repr__(self):
        if self.replica is not None:
            extra = (f", seconds={self.seconds:g}"
                     if self.kind == "stall" else "")
            return (f"Fault({self.kind}:replica={self.replica},"
                    f"request={self.request}{extra})")
        trig = (f"step={self.step}" if self.step is not None
                else f"seq={self.seq}")
        extra = f", seconds={self.seconds:g}" if self.kind == "delay" else ""
        return f"Fault({self.kind}:rank={self.rank},{trig}{extra})"


class FaultPlan:
    """An ordered set of faults plus the per-rank collective counters the
    seq triggers consume. Thread-safe: the simulator calls the collective
    hook from rank main threads AND overlap worker lanes."""

    def __init__(self, faults=()):
        self.faults = list(faults)
        self._lock = threading.Lock()
        self._coll_seq: dict = {}        # rank -> collectives entered
        self._route_seq: dict = {}       # replica id -> requests routed

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """Parse the ``PADDLE_FAULT_PLAN`` grammar (see module doc)."""
        faults = []
        for directive in spec.split(";"):
            directive = directive.strip()
            if not directive:
                continue
            kind, _, argstr = directive.partition(":")
            kind = kind.strip()
            kw = {}
            for pair in argstr.split(","):
                pair = pair.strip()
                if not pair:
                    continue
                k, _, v = pair.partition("=")
                k = k.strip()
                if k not in ("rank", "step", "seq", "seconds", "replica",
                             "request"):
                    raise ValueError(
                        f"unknown fault key {k!r} in {directive!r} "
                        "(expected rank/step/seq/seconds/replica/request)")
                kw[k] = (float(v) if k == "seconds"
                         else v.strip() if k == "replica" else int(v))
            if "rank" not in kw and "replica" not in kw:
                raise ValueError(f"fault {directive!r} needs rank= "
                                 "or replica=")
            faults.append(Fault(kind, **kw))
        return cls(faults)

    def collective_seq(self, rank) -> int:
        with self._lock:
            return self._coll_seq.get(rank, 0)

    # -- trigger evaluation --------------------------------------------------
    def _due_step(self, rank, step):
        with self._lock:
            for f in self.faults:
                if (not f.fired and f.rank == rank and f.step is not None
                        and f.step == step):
                    f.fired = True
                    return f
        return None

    def _due_collective(self, rank):
        with self._lock:
            seq = self._coll_seq.get(rank, 0) + 1
            self._coll_seq[rank] = seq
            for f in self.faults:
                if (not f.fired and f.rank == rank and f.seq is not None
                        and seq >= f.seq):
                    f.fired = True
                    return f
        return None

    def _due_fleet(self, replica_id):
        with self._lock:
            rid = str(replica_id)
            n = self._route_seq.get(rid, 0) + 1
            self._route_seq[rid] = n
            for f in self.faults:
                if (not f.fired and f.replica == rid and n >= f.request):
                    f.fired = True
                    return f
        return None


_ACTIVE: list = [None]       # [FaultPlan | None]; env plan parsed lazily
_ENV_PARSED = [False]


def install(plan: "FaultPlan | str | None") -> "FaultPlan | None":
    """Install a plan (object or spec string) and arm the simulator hook.
    ``None`` uninstalls (same as :func:`clear`)."""
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan)
    _ACTIVE[0] = plan
    _ENV_PARSED[0] = True         # an explicit install overrides the env
    simulator._FAULT_HOOK[0] = _collective_hook if plan else None
    return plan


def clear():
    """Remove any installed plan and disarm the hook."""
    _ACTIVE[0] = None
    _ENV_PARSED[0] = False
    simulator._FAULT_HOOK[0] = None


def active_plan() -> "FaultPlan | None":
    """The installed plan, else one parsed from ``PADDLE_FAULT_PLAN``
    (parsed once; re-read after :func:`clear`)."""
    if _ACTIVE[0] is None and not _ENV_PARSED[0]:
        _ENV_PARSED[0] = True
        spec = os.environ.get("PADDLE_FAULT_PLAN")
        if spec:
            _ACTIVE[0] = FaultPlan.parse(spec)
            simulator._FAULT_HOOK[0] = _collective_hook
    return _ACTIVE[0]


def _rank() -> int:
    r = simulator.current_rank()
    if r is not None:
        return r
    try:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    except ValueError:
        return 0


def _fire(fault: Fault, where: str):
    from ..profiler import flight_recorder as _flight
    elastic_telemetry()["events"].inc(kind=fault.kind)
    _flight.record_event("fault_injected", fault=repr(fault), where=where)
    if fault.kind == "delay":
        time.sleep(fault.seconds)
        return
    if fault.kind == "nan":
        # arm the tape's one-shot poison on THIS thread: the next
        # backward's first finalized leaf grad carries the NaN through
        # the normal grad-ready → bucket path (sentinel-detectable)
        from ..autograd import tape
        tape.poison_next_leaf_grad()
        return
    if fault.kind == "bitflip":
        # arm the tape's one-shot single-bit flip on THIS thread: the
        # next backward's first finalized leaf grad gets one low bit
        # flipped post write-back — rank-local silent corruption the
        # determinism ledger's cross-rank comparison must name
        from ..autograd import tape
        tape.flip_bit_next_leaf_grad()
        return
    # kill: mark dead FIRST so blocked survivors detect immediately,
    # then unwind this rank's thread
    w = simulator.active_world()
    if w is not None:
        w.mark_dead(fault.rank)
    raise SimulatedRankKill(fault.rank, where)


def check_step(step: int):
    """Step-boundary hook for training loops: fires any step-triggered
    fault due for the calling rank at ``step``. No-op without a plan."""
    plan = active_plan()
    if plan is None:
        return
    f = plan._due_step(_rank(), step)
    if f is not None:
        _fire(f, where=f"step {step}")


def check_fleet_route(replica_id):
    """Routing hook for the serving fleet: counts one request routed to
    ``replica_id`` and returns a due fleet fault (or None). The caller
    (``ServingRouter._route_locked``) APPLIES the fault — killing the
    replica or stalling its serve loop is router/engine machinery this
    module must not depend on. No-op without an active plan."""
    plan = active_plan()
    if plan is None:
        return None
    f = plan._due_fleet(replica_id)
    if f is not None:
        from ..profiler import flight_recorder as _flight
        elastic_telemetry()["events"].inc(kind=f.kind)
        _flight.record_event("fault_injected", fault=repr(f),
                             where=f"route {replica_id}")
    return f


def _collective_hook(rank, tag):
    """Installed as ``simulator._FAULT_HOOK`` while a plan is active:
    counts the rank's rendezvous entries and fires seq-triggered
    faults."""
    plan = _ACTIVE[0]
    if plan is None:
        return
    f = plan._due_collective(rank)
    if f is not None:
        _fire(f, where=f"collective {tag!r}")
