"""Distributed passes (reference: ``python/paddle/distributed/passes/`` — a
registry of program-rewriting passes for the auto-parallel static engine:
``auto_parallel_amp``, ``auto_parallel_recompute``, ``auto_parallel_sharding``,
``pipeline_scheduler_pass`` (FThenB/1F1B/VPP/ZBH1), fuse-allreduce;
SURVEY.md §2.3 "Distributed passes" + "Static-mode meta-optimizers").

TPU-native framing: the reference's passes rewrite a serialized Program's op
list (insert cast ops, recompute subgraphs, comm ops). Here compilation is
XLA's job, so a "pass" transforms the declarative *plan* — the strategy/
sharding decisions a train step is built from — and the XLA lowering
realizes it. Several reference passes are XLA built-ins and their pass
objects document that (apply = no-op with a note): fused allreduce ≡ XLA
collective combining; fuse-adamw ≡ XLA op fusion.
"""
from __future__ import annotations

_PASS_REGISTRY = {}


def register_pass(name):
    def deco(cls):
        cls.name = name
        _PASS_REGISTRY[name] = cls
        return cls
    return deco


def new_pass(name, attrs=None):
    try:
        cls = _PASS_REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown pass {name!r}; available: "
                         f"{sorted(_PASS_REGISTRY)}")
    return cls(attrs or {})


class PassBase:
    """A pass transforms a plan dict (strategy + shardings + step options).
    ``apply(plan)`` returns the updated plan; ``check`` validates."""

    name = "base"

    def __init__(self, attrs=None):
        self.attrs = dict(attrs or {})

    def check(self, plan):
        return True

    def apply(self, plan, *a, **kw):
        return plan


class PassManager:
    def __init__(self, passes=None):
        self.passes = list(passes or [])

    def append(self, p):
        self.passes.append(p)

    def apply(self, plan=None, *a, **kw):
        plan = dict(plan or {})
        for p in self.passes:
            if p.check(plan):
                plan = p.apply(plan)
        return plan

    @property
    def names(self):
        return [p.name for p in self.passes]


@register_pass("auto_parallel_amp")
class AMPPass(PassBase):
    """Sets the step's compute dtype policy (O1 lists / O2 bf16 + master
    weights) — realized by the amp cast hook, not inserted cast ops."""

    def apply(self, plan, *a, **kw):
        # merge, don't clobber: MasterGradPass may have recorded
        # master_grad in plan['amp'] already (pass order is free)
        plan.setdefault("amp", {}).update(
            {"level": self.attrs.get("level", "O2"),
             "dtype": self.attrs.get("dtype", "bfloat16"),
             "master_weights": True})
        return plan


@register_pass("auto_parallel_fp16")
class FP16Pass(AMPPass):
    def apply(self, plan, *a, **kw):
        plan = super().apply(plan)
        plan["amp"]["dtype"] = "float16"
        return plan


@register_pass("auto_parallel_recompute")
class RecomputePass(PassBase):
    """Marks layer groups for jax.checkpoint (the reference rewrites the
    backward block; XLA rematerializes instead)."""

    def apply(self, plan, *a, **kw):
        plan["recompute"] = {
            "enable": True,
            "granularity": self.attrs.get("granularity", "full"),
            "no_recompute_segments": self.attrs.get(
                "no_recompute_segments", []),
        }
        return plan


@register_pass("auto_parallel_sharding")
class ShardingPass(PassBase):
    """Sets the ZeRO stage realized as parameter/opt-state PartitionSpecs on
    the 'sharding' mesh axis."""

    def apply(self, plan, *a, **kw):
        plan["sharding"] = {"stage": int(self.attrs.get("stage", 2)),
                            "degree": self.attrs.get("degree", None)}
        return plan


@register_pass("pipeline_scheduler")
class PipelineSchedulerPass(PassBase):
    """Selects the microbatch schedule, all realized by the SPMD engine
    (distributed/engine.py): FThenB (grad-through-scan), 1F1B
    (recompute/backward custom_vjp, O(S) memory), VPP (interleaved
    virtual stages), ZBH1 (1F1B with the backward split into B on the
    wire chain and W deferred one tick off it)."""

    SCHEDULES = ("FThenB", "1F1B", "VPP", "ZBH1")

    def check(self, plan):
        mode = self.attrs.get("schedule_mode", "1F1B")
        if mode not in self.SCHEDULES:
            raise ValueError(f"unknown pipeline schedule {mode}")
        return True

    def apply(self, plan, *a, **kw):
        plan["pipeline"] = {
            "schedule_mode": self.attrs.get("schedule_mode", "1F1B"),
            "accumulate_steps": int(self.attrs.get("accumulate_steps", 1)),
            "vpp_degree": int(self.attrs.get("vpp_degree", 1)),
        }
        return plan


@register_pass("fuse_all_reduce")
class FuseAllReducePass(PassBase):
    """Collective combining — realized by XLA; applying the pass pins
    the responsible compiler flags into the plan so
    ``install_xla_flags`` can arm them explicitly."""

    def apply(self, plan, *a, **kw):
        plan.setdefault("xla_flags", []).extend([
            "--xla_tpu_enable_async_collective_fusion=true",
            "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
        ])
        plan.setdefault("notes", []).append(
            "fuse_all_reduce: XLA collective combining (flags pinned)")
        return plan


@register_pass("fused_adamw")
class FusedAdamWPass(PassBase):
    """XLA built-in (op fusion of the update chain); kept for API parity."""

    def apply(self, plan, *a, **kw):
        plan.setdefault("notes", []).append(
            "fused_adamw: XLA fuses the elementwise update chain")
        return plan


@register_pass("auto_parallel_gradient_merge")
class GradientMergePass(PassBase):
    """Gradient merge / large-batch accumulation (reference:
    ``auto_parallel_gradient_merge.py`` rewrites the program to accumulate
    grads over k steps before the optimizer update). Here it is REAL eager
    behavior: ``wrap(optimizer)`` returns an optimizer whose ``step()``
    applies only every ``k_steps``-th call (grads keep accumulating on the
    tape's ``.grad`` between applies — reference avg=True divides)."""

    def apply(self, plan, *a, **kw):
        plan["gradient_merge"] = {
            "k_steps": int(self.attrs.get("k_steps", 1)),
            "avg": bool(self.attrs.get("avg", True)),
        }
        return plan

    def wrap(self, optimizer):
        return _GradientMergeOptimizer(optimizer,
                                       int(self.attrs.get("k_steps", 1)),
                                       bool(self.attrs.get("avg", True)))


class _GradientMergeOptimizer:
    def __init__(self, inner, k_steps, avg):
        self._inner = inner
        self._k = max(1, k_steps)
        self._avg = avg
        self._calls = 0

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def step(self):
        self._calls += 1
        if self._calls % self._k:
            return              # keep accumulating into .grad
        if self._avg and self._k > 1:
            for p in self._inner._parameter_list:
                if p.grad is not None:
                    p.grad._data = p.grad._data / self._k
        self._inner.step()

    def minimize(self, loss, *a, **kw):
        # must route through the merge window, not the inner minimize
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    def clear_grad(self, *a, **kw):
        # grads persist across the merge window; clear only after an apply
        if self._calls % self._k == 0:
            self._inner.clear_grad(*a, **kw)

    clear_gradients = clear_grad


@register_pass("auto_parallel_master_grad")
class MasterGradPass(PassBase):
    """fp32 master gradients under bf16 compute — realized by the AMP
    layer's master-weight path; the pass records the policy."""

    def apply(self, plan, *a, **kw):
        plan.setdefault("amp", {})["master_grad"] = True
        return plan


@register_pass("fuse_gemm_epilogue")
class FuseGemmEpiloguePass(PassBase):
    """XLA built-in (bias/activation fused into the matmul); API parity."""

    def apply(self, plan, *a, **kw):
        plan.setdefault("notes", []).append(
            "fuse_gemm_epilogue: XLA fuses bias+activation epilogues")
        return plan


@register_pass("allreduce_matmul_grad_overlapping")
class AllreduceOverlapPass(PassBase):
    """Grad-collective/compute overlap — realized by XLA's latency-hiding
    scheduler; applying the pass pins the flag into the plan."""

    def apply(self, plan, *a, **kw):
        plan.setdefault("xla_flags", []).append(
            "--xla_tpu_enable_latency_hiding_scheduler=true")
        plan.setdefault("notes", []).append(
            "allreduce overlap: XLA latency-hiding scheduler overlaps "
            "grad collectives with the backward matmuls (flag pinned)")
        return plan


def build_strategy_from_plan(plan):
    """Execute a pass plan: fold the dict the passes produced into a
    concrete ``DistributedStrategy`` (+ model-config knobs via
    :func:`apply_plan_to_config`) that ``fleet.init`` /
    ``distributed_model`` actually run with — the reference's
    program-rewrite step collapsed onto strategy/config space (on TPU the
    rewrites themselves are XLA sharding/fusion passes)."""
    from ..fleet.distributed_strategy import DistributedStrategy

    strat = DistributedStrategy()
    if "amp" in plan:
        strat.amp = True
        amp = dict(plan["amp"])
        strat.amp_configs = {
            "level": amp.get("level", "O2"),
            "dtype": amp.get("dtype", "bfloat16"),
            "use_master_weights": amp.get("master_weights", True),
            "use_master_grad": amp.get("master_grad", False),
        }
    if "recompute" in plan and plan["recompute"].get("enable", True):
        strat.recompute = True
        strat.recompute_configs = dict(plan["recompute"])
    h = dict(strat.hybrid_configs)          # accumulate; assign once at
    if "sharding" in plan:                  # the end (the setter merges
        strat.sharding = True               # from DEFAULTS, not current)
        strat.sharding_configs = dict(plan["sharding"])
        h["sharding_degree"] = int(plan["sharding"].get("degree", 1) or 1)
        # the stage HybridParallelOptimizer actually reads lives under
        # hybrid_configs["sharding_configs"]
        sc = dict(h.get("sharding_configs", {}))
        sc["stage"] = int(plan["sharding"].get("stage", 1))
        h["sharding_configs"] = sc
    if "pipeline" in plan:
        pp = plan["pipeline"]
        h["pp_degree"] = int(pp.get("pp_degree", pp.get("degree", 1)) or 1)
        ppc = dict(h.get("pp_configs", {}))
        ppc["schedule_mode"] = pp.get("schedule_mode", "1F1B")
        ppc["accumulate_steps"] = int(pp.get("accumulate_steps", 1))
        ppc["vpp_degree"] = int(pp.get("vpp_degree", 1))
        h["pp_configs"] = ppc               # the runtime reads pp_configs
    strat.hybrid_configs = h
    if "gradient_merge" in plan:
        strat.gradient_merge = True
        strat.gradient_merge_configs = dict(plan["gradient_merge"])
    return strat


def install_xla_flags(plan, env=None, platform=None):
    """Arm the plan's pinned XLA compiler flags (collective fusion,
    latency-hiding scheduler, ...) in ``env`` — the executable half of
    the XLA-builtin passes. TPU-only flags are only installed when the
    backend is a TPU (XLA rejects unknown flags at init), and flags must
    be set BEFORE the first backend initialization to take effect in
    this process (they always apply to spawned children).

    Returns the list of flags installed."""
    import os
    flags = list(dict.fromkeys(plan.get("xla_flags", [])))  # dedup, ordered
    if not flags:
        return []
    if platform is None:
        # Must not call jax.default_backend() here: that would perform
        # the very backend initialization the flags need to precede,
        # rendering them inert for this process. Probe initialized
        # state / env only.
        try:
            from jax._src import xla_bridge as xb
            initialized = bool(getattr(xb, "backends_are_initialized",
                                       lambda: getattr(xb, "_backends",
                                                       None))())
        except Exception:
            initialized = False
        if initialized:
            import jax
            platform = jax.default_backend()
        else:
            envs = (os.environ.get("JAX_PLATFORMS", "")
                    + os.environ.get("PJRT_DEVICE", "")).lower()
            platform = "tpu" if ("tpu" in envs or "axon" in envs
                                 or os.environ.get("PALLAS_AXON_POOL_IPS")
                                 ) else "unknown"
    if platform != "tpu":
        return []            # tpu-only flags would crash other backends
    env = os.environ if env is None else env
    current = env.get("XLA_FLAGS", "").split()
    merged = current + [f for f in flags if f not in current]
    env["XLA_FLAGS"] = " ".join(merged)
    return [f for f in flags if f not in current]


def apply_plan_to_config(plan, model_config):
    """Push plan knobs that live on the MODEL into its config (recompute
    granularity, sequence parallel) — returns the same config object."""
    rc = plan.get("recompute")
    if rc and rc.get("enable", True) \
            and hasattr(model_config, "use_recompute"):
        model_config.use_recompute = True
        gran = rc.get("granularity")
        if gran and hasattr(model_config, "recompute_granularity"):
            model_config.recompute_granularity = gran
    if plan.get("sequence_parallel") \
            and hasattr(model_config, "sequence_parallel"):
        model_config.sequence_parallel = True
    return model_config
