"""Native (C++) distributed runtime — builds and wraps tcp_store.cpp.

The reference's rendezvous is a C++ TCP KV store
(``paddle/fluid/distributed/store/tcp_store.cc``: master-hosted map with
SET/GET/WAIT/ADD, used for env rendezvous and barriers — SURVEY.md §2.1
"Collective runtime"). This is the TPU-build equivalent, compiled with g++
at first use and driven over a ctypes ABI (no pybind11 in the image).

``TCPStore(host, port, is_master, world_size)`` mirrors the reference's
Python surface: ``set/get/add/wait/delete_key`` + ``barrier()`` built on
ADD+WAIT. ``available()`` gates callers for toolchain-less machines.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading

_LIB = None
_LIB_ERR = None
_BUILD_LOCK = threading.Lock()


def _build_lib():
    src = os.path.join(os.path.dirname(__file__), "tcp_store.cpp")
    build_dir = os.path.join(tempfile.gettempdir(),
                             f"paddle_tpu_native_{os.getuid()}")
    os.makedirs(build_dir, exist_ok=True)
    so = os.path.join(build_dir, "libtcpstore.so")
    if (not os.path.exists(so)
            or os.path.getmtime(so) < os.path.getmtime(src)):
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", src,
               "-o", so + ".tmp", "-pthread"]
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(so + ".tmp", so)
    return so


def _lib():
    global _LIB, _LIB_ERR
    if _LIB is not None or _LIB_ERR is not None:
        return _LIB
    with _BUILD_LOCK:
        if _LIB is not None or _LIB_ERR is not None:
            return _LIB
        try:
            lib = ctypes.CDLL(_build_lib())
        except Exception as e:      # no toolchain: callers fall back
            _LIB_ERR = e
            return None
        lib.ts_server_start.restype = ctypes.c_void_p
        lib.ts_server_start.argtypes = [ctypes.c_int]
        lib.ts_server_port.restype = ctypes.c_int
        lib.ts_server_port.argtypes = [ctypes.c_void_p]
        lib.ts_server_stop.argtypes = [ctypes.c_void_p]
        lib.ts_client_connect.restype = ctypes.c_void_p
        lib.ts_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                          ctypes.c_int]
        lib.ts_client_close.argtypes = [ctypes.c_void_p]
        for name, extra in (("ts_set", [ctypes.c_char_p, ctypes.c_uint32]),
                            ("ts_get", []),
                            ("ts_add", [ctypes.c_int64]),
                            ("ts_wait", [ctypes.c_uint32]),
                            ("ts_delete", []),
                            ("ts_list", [])):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int64
            fn.argtypes = ([ctypes.c_void_p, ctypes.c_char_p,
                            ctypes.c_uint32] + extra)
        lib.ts_read_buf.restype = ctypes.c_int64
        lib.ts_read_buf.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_int64]
        _LIB = lib
        return _LIB


def available():
    return _lib() is not None


class TCPStore:
    """Reference-compatible TCP rendezvous store.

    The master rank hosts the server in-process; every rank (master
    included) talks to it through a client connection.
    """

    def __init__(self, host="127.0.0.1", port=0, is_master=False,
                 world_size=1, timeout=120):
        lib = _lib()
        if lib is None:
            raise RuntimeError(
                f"native TCPStore unavailable (g++ build failed: "
                f"{_LIB_ERR})")
        self._lib = lib
        self._server = None
        self.world_size = int(world_size)
        self.timeout = float(timeout)
        if is_master:
            self._server = lib.ts_server_start(int(port))
            if not self._server:
                raise RuntimeError(f"TCPStore: cannot listen on {port}")
            port = lib.ts_server_port(self._server)
        self.host, self.port = host, int(port)
        self._client = lib.ts_client_connect(
            host.encode(), int(port), int(self.timeout * 1000))
        if not self._client:
            raise RuntimeError(
                f"TCPStore: cannot reach master at {host}:{port} within "
                f"{timeout}s")

    # -- KV surface (reference core.TCPStore methods) ----------------------
    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        k = key.encode()
        st = self._lib.ts_set(self._client, k, len(k), bytes(value),
                              len(value))
        if st != 0:
            raise RuntimeError("TCPStore.set failed (connection lost)")

    def get(self, key, wait=True, timeout=None):
        k = key.encode()
        if wait:
            self.wait(key, timeout)
        n = self._lib.ts_get(self._client, k, len(k))
        if n == -1:
            raise KeyError(key)
        if n < -1:
            raise RuntimeError("TCPStore.get failed (connection lost)")
        buf = ctypes.create_string_buffer(int(n) or 1)
        got = self._lib.ts_read_buf(self._client, buf, int(n) or 1)
        return buf.raw[:got]

    _CONN_LOST = -(2 ** 63)    # C++ kConnLost sentinel

    def add(self, key, amount=1):
        k = key.encode()
        out = self._lib.ts_add(self._client, k, len(k), int(amount))
        if out == self._CONN_LOST:
            raise RuntimeError("TCPStore.add failed (connection lost)")
        return int(out)

    def wait(self, key, timeout=None):
        k = key.encode()
        tmo = int((self.timeout if timeout is None else timeout) * 1000)
        st = self._lib.ts_wait(self._client, k, len(k), tmo)
        if st == self._CONN_LOST:
            raise RuntimeError("TCPStore.wait failed (connection lost)")
        if st != 0:
            raise TimeoutError(f"TCPStore.wait({key!r}) timed out")

    def delete_key(self, key):
        k = key.encode()
        self._lib.ts_delete(self._client, k, len(k))

    def keys(self, prefix=""):
        p = prefix.encode()
        n = self._lib.ts_list(self._client, p, len(p))
        if n < 0:
            raise RuntimeError("TCPStore.keys failed")
        buf = ctypes.create_string_buffer(int(n) or 1)
        got = self._lib.ts_read_buf(self._client, buf, int(n) or 1)
        out, i = [], 0
        raw = buf.raw[:got]
        while i + 4 <= len(raw):
            ln = int.from_bytes(raw[i:i + 4], "little")
            out.append(raw[i + 4:i + 4 + ln].decode())
            i += 4 + ln
        return out

    # -- synchronization helpers ------------------------------------------
    def barrier(self, name="barrier", timeout=None):
        """All ``world_size`` ranks rendezvous: ADD a shared counter; the
        last arrival of each ROUND publishes that round's release key
        everyone WAITs on — reusable for any number of rounds (the count
        key is monotone; the round index is derived from it)."""
        n = self.add(f"__{name}/count", 1)
        rnd = (n - 1) // self.world_size
        if n == (rnd + 1) * self.world_size:
            self.set(f"__{name}/release/{rnd}", b"1")
        self.wait(f"__{name}/release/{rnd}", timeout)

    def close(self):
        if getattr(self, "_client", None):
            self._lib.ts_client_close(self._client)
            self._client = None
        if getattr(self, "_server", None):
            self._lib.ts_server_stop(self._server)
            self._server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
