// TCP key-value rendezvous store — the native runtime piece behind
// paddle_tpu.distributed.TCPStore (reference:
// paddle/fluid/distributed/store/tcp_store.cc + tcp_utils.cc — SURVEY.md
// §2.1 "Collective runtime": master-hosted KV with SET/GET/WAIT/ADD used
// for env rendezvous and barriers).
//
// Design: one server thread per listening store, one handler thread per
// accepted connection (rank count is tens, not thousands); a mutex+condvar
// protected unordered_map<string,string>; WAIT blocks server-side until the
// key exists (with client-supplied timeout). ctypes ABI (no pybind11 in
// the image): plain C functions over opaque handles.
//
// Wire protocol (little-endian):
//   request:  u8 op | u32 klen | key | u32 vlen | val
//     op: 1=SET 2=GET 3=ADD(val=i64 delta) 4=WAIT(val=u32 timeout_ms)
//         5=DELETE 6=LIST_KEYS(prefix=key)
//   response: i64 status | payload
//     status >=0: payload length (GET/LIST) or new counter value (ADD)
//     status -1: key missing (GET) / timeout (WAIT)

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <climits>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct Store {
  std::unordered_map<std::string, std::string> kv;
  std::mutex mu;
  std::condition_variable cv;
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stop{false};
  std::thread accept_thread;
  std::vector<std::thread> handlers;
  std::vector<int> conn_fds;
  std::mutex handlers_mu;
};

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool reply(int fd, int64_t status, const std::string& payload = "") {
  if (!write_full(fd, &status, sizeof(status))) return false;
  if (!payload.empty() && !write_full(fd, payload.data(), payload.size()))
    return false;
  return true;
}

void handle_conn(Store* s, int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  for (;;) {
    uint8_t op;
    uint32_t klen, vlen;
    if (!read_full(fd, &op, 1) || !read_full(fd, &klen, 4)) break;
    std::string key(klen, '\0');
    if (klen && !read_full(fd, &key[0], klen)) break;
    if (!read_full(fd, &vlen, 4)) break;
    std::string val(vlen, '\0');
    if (vlen && !read_full(fd, &val[0], vlen)) break;

    bool ok = true;
    switch (op) {
      case 1: {  // SET
        {
          std::lock_guard<std::mutex> lk(s->mu);
          s->kv[key] = val;
        }
        s->cv.notify_all();
        ok = reply(fd, 0);
        break;
      }
      case 2: {  // GET
        std::string out;
        bool found = false;
        {
          std::lock_guard<std::mutex> lk(s->mu);
          auto it = s->kv.find(key);
          if (it != s->kv.end()) {
            out = it->second;
            found = true;
          }
        }
        ok = found ? reply(fd, static_cast<int64_t>(out.size()), out)
                   : reply(fd, -1);
        break;
      }
      case 3: {  // ADD
        int64_t delta = 0;
        if (val.size() == 8) std::memcpy(&delta, val.data(), 8);
        int64_t cur = 0;
        {
          std::lock_guard<std::mutex> lk(s->mu);
          auto it = s->kv.find(key);
          if (it != s->kv.end() && it->second.size() == 8)
            std::memcpy(&cur, it->second.data(), 8);
          cur += delta;
          std::string stored(8, '\0');
          std::memcpy(&stored[0], &cur, 8);
          s->kv[key] = stored;
        }
        s->cv.notify_all();
        ok = reply(fd, cur);
        break;
      }
      case 4: {  // WAIT
        uint32_t timeout_ms = 0;
        if (val.size() == 4) std::memcpy(&timeout_ms, val.data(), 4);
        std::unique_lock<std::mutex> lk(s->mu);
        bool found = s->cv.wait_for(
            lk, std::chrono::milliseconds(timeout_ms),
            [&] { return s->stop.load() || s->kv.count(key) > 0; });
        lk.unlock();
        ok = reply(fd, (found && !s->stop.load()) ? 0 : -1);
        break;
      }
      case 5: {  // DELETE
        {
          std::lock_guard<std::mutex> lk(s->mu);
          s->kv.erase(key);
        }
        s->cv.notify_all();
        ok = reply(fd, 0);
        break;
      }
      case 6: {  // LIST_KEYS with prefix
        std::string out;
        {
          std::lock_guard<std::mutex> lk(s->mu);
          for (auto& it : s->kv) {
            if (it.first.rfind(key, 0) == 0) {
              uint32_t n = static_cast<uint32_t>(it.first.size());
              out.append(reinterpret_cast<char*>(&n), 4);
              out.append(it.first);
            }
          }
        }
        ok = reply(fd, static_cast<int64_t>(out.size()), out);
        break;
      }
      default:
        ok = reply(fd, -2);
    }
    if (!ok) break;
  }
  ::close(fd);
}

void accept_loop(Store* s) {
  for (;;) {
    sockaddr_in peer{};
    socklen_t plen = sizeof(peer);
    int fd = ::accept(s->listen_fd, reinterpret_cast<sockaddr*>(&peer),
                      &plen);
    if (fd < 0) {
      if (s->stop.load()) return;
      continue;
    }
    std::lock_guard<std::mutex> lk(s->handlers_mu);
    if (s->stop.load()) {
      ::close(fd);
      return;
    }
    s->conn_fds.push_back(fd);
    s->handlers.emplace_back(handle_conn, s, fd);
  }
}

struct Client {
  int fd = -1;
  std::mutex mu;
  std::string buf;
};

}  // namespace

extern "C" {

// ---- server ----
void* ts_server_start(int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 128) < 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  auto* s = new Store();
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  s->accept_thread = std::thread(accept_loop, s);
  return s;
}

int ts_server_port(void* h) { return static_cast<Store*>(h)->port; }

void ts_server_stop(void* h) {
  auto* s = static_cast<Store*>(h);
  s->stop.store(true);
  s->cv.notify_all();           // wake WAIT handlers
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  {
    // shut down every live connection so blocked recv()s return, then
    // JOIN the handlers — deleting the Store under detached threads that
    // still hold its mutex would be a use-after-free
    std::lock_guard<std::mutex> lk(s->handlers_mu);
    for (int fd : s->conn_fds) ::shutdown(fd, SHUT_RDWR);
  }
  for (auto& t : s->handlers)
    if (t.joinable()) t.join();
  delete s;
}

// ---- client ----
void* ts_client_connect(const char* host, int port, int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  for (;;) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      ::close(fd);
      return nullptr;
    }
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr),
                  sizeof(addr)) == 0) {
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      auto* c = new Client();
      c->fd = fd;
      return c;
    }
    ::close(fd);
    if (std::chrono::steady_clock::now() >= deadline) return nullptr;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

void ts_client_close(void* h) {
  auto* c = static_cast<Client*>(h);
  ::close(c->fd);
  delete c;
}

// connection-loss sentinel: cannot collide with ADD counter values in
// practice (callers would need a counter at INT64_MIN)
static constexpr int64_t kConnLost = INT64_MIN;

static int64_t request(Client* c, uint8_t op, const char* key, uint32_t klen,
                       const char* val, uint32_t vlen, int with_payload) {
  std::lock_guard<std::mutex> lk(c->mu);
  std::string msg;
  msg.push_back(static_cast<char>(op));
  msg.append(reinterpret_cast<char*>(&klen), 4);
  msg.append(key, klen);
  msg.append(reinterpret_cast<char*>(&vlen), 4);
  if (vlen) msg.append(val, vlen);
  if (!write_full(c->fd, msg.data(), msg.size())) return kConnLost;
  int64_t status;
  if (!read_full(c->fd, &status, 8)) return kConnLost;
  if (with_payload && status > 0) {
    c->buf.resize(static_cast<size_t>(status));
    if (!read_full(c->fd, &c->buf[0], c->buf.size())) return kConnLost;
  } else if (with_payload) {
    c->buf.clear();
  }
  return status;
}

int64_t ts_set(void* h, const char* key, uint32_t klen, const char* val,
               uint32_t vlen) {
  return request(static_cast<Client*>(h), 1, key, klen, val, vlen, 0);
}

int64_t ts_get(void* h, const char* key, uint32_t klen) {
  return request(static_cast<Client*>(h), 2, key, klen, nullptr, 0, 1);
}

int64_t ts_add(void* h, const char* key, uint32_t klen, int64_t delta) {
  return request(static_cast<Client*>(h), 3, key, klen,
                 reinterpret_cast<const char*>(&delta), 8, 0);
}

int64_t ts_wait(void* h, const char* key, uint32_t klen,
                uint32_t timeout_ms) {
  return request(static_cast<Client*>(h), 4, key, klen,
                 reinterpret_cast<const char*>(&timeout_ms), 4, 0);
}

int64_t ts_delete(void* h, const char* key, uint32_t klen) {
  return request(static_cast<Client*>(h), 5, key, klen, nullptr, 0, 0);
}

int64_t ts_list(void* h, const char* prefix, uint32_t plen) {
  return request(static_cast<Client*>(h), 6, prefix, plen, nullptr, 0, 1);
}

// copy out the payload of the last GET/LIST on this client
int64_t ts_read_buf(void* h, char* out, int64_t cap) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  int64_t n = static_cast<int64_t>(c->buf.size());
  if (n > cap) return -n;
  std::memcpy(out, c->buf.data(), static_cast<size_t>(n));
  return n;
}

}  // extern "C"
