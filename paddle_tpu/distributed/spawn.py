"""paddle.distributed.spawn (reference: ``python/paddle/distributed/spawn.py``
— per-device child processes with env rendezvous; SURVEY.md §4 pattern (1) for
distributed unit tests).

TPU-native: per-rank *threads* via the simulator (simulator.py) — the single
JAX process owns all devices, so per-rank OS processes would fight over the
backend; threads give the same per-rank SPMD semantics for the imperative
collective API while the mesh path needs no ranks at all.
"""
from __future__ import annotations

from . import simulator


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    import jax
    if nprocs in (-1, None):
        nprocs = jax.local_device_count()
    results = simulator.run(func, nprocs, args=args)

    class _Context:
        def __init__(self, results):
            self.results = results

        def join(self):
            return True

    return _Context(results)
