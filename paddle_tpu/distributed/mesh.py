"""Global device mesh — the TPU-native backbone of the distributed stack.

Reference analogue: ``HybridCommunicateGroup``'s N-D rank mesh in axis order
[dp, pp, sharding, sep, mp] (``python/paddle/distributed/fleet/base/topology.py``,
SURVEY.md §2.3) — but instead of a rank-coordinate bookkeeping object backed by
NCCL comm rings, the mesh IS a ``jax.sharding.Mesh``: every parallelism axis is
a named mesh axis, shardings are ``NamedSharding``/``PartitionSpec`` over those
axes, and XLA emits the collectives over ICI/DCN (SURVEY.md §7.0).

Axis order convention matches the reference: mp innermost (fastest links —
on a TPU torus, the last mesh axis maps to the tightest ICI ring), dp
outermost.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# canonical hybrid axis order (reference: fixed order [dp, pp, sharding, sep, mp])
HYBRID_AXES = ("dp", "pp", "sharding", "sep", "mp")

_global_mesh: Mesh | None = None


def _slice_major(devices):
    """Order devices slice-major for multi-slice (DCN-connected) systems.

    Reference analogue: multi-node Fleet keeps NCCL rings node-local and
    crosses nodes only on the outer (dp) axis. On TPU the slow links are
    DCN between slices; jax exposes slice membership as
    ``device.slice_index``. Returns ``(ordered_devices, n_slices)`` with
    each slice's devices contiguous, so a row-major reshape puts slice
    boundaries on the OUTERMOST mesh axis and every inner axis (mp/sep/
    sharding/pp collectives) rides ICI only.
    """
    by_slice: dict[int, list] = {}
    for d in devices:
        by_slice.setdefault(getattr(d, "slice_index", 0) or 0, []).append(d)
    groups = [by_slice[k] for k in sorted(by_slice)]
    if len(groups) > 1 and len({len(g) for g in groups}) != 1:
        raise ValueError(
            f"uneven DCN slices: {[len(g) for g in groups]} devices per "
            "slice — a hybrid mesh needs equal-size slices")
    return [d for g in groups for d in g], len(groups)


def init_mesh(degrees: dict[str, int] | None = None, devices=None) -> Mesh:
    """Build (and install) the global mesh from parallelism degrees.

    ``degrees`` maps axis name -> size; unspecified hybrid axes get 1. A
    remainder of devices is folded into dp. With no args: 1-D dp mesh over
    all devices. On multi-slice systems devices are ordered slice-major
    and the dp degree must be a multiple of the slice count, so only the
    outermost (DCN) axis crosses slices.
    """
    global _global_mesh
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    degrees = dict(degrees or {})
    sizes = [int(degrees.get(ax, 1)) for ax in HYBRID_AXES]
    prod = int(np.prod([s for s in sizes if s > 0]))
    if n % max(prod, 1) != 0:
        raise ValueError(f"device count {n} not divisible by degree product {prod} "
                         f"({dict(zip(HYBRID_AXES, sizes))})")
    # fold leftover devices into dp (paddle: dp_degree inferred from world size)
    if degrees.get("dp") in (None, -1):
        sizes[0] = n // (prod // max(sizes[0], 1)) if sizes[0] > 0 else n // prod
    prod = int(np.prod(sizes))
    if prod != n:
        raise ValueError(f"degrees {dict(zip(HYBRID_AXES, sizes))} use {prod} "
                         f"devices, but {n} are available")
    devices, n_slices = _slice_major(devices)
    if n_slices > 1 and sizes[0] % n_slices != 0:
        raise ValueError(
            f"multi-slice mesh: dp degree {sizes[0]} must be a multiple of "
            f"the DCN slice count {n_slices} — inner axes (pp/sharding/sep/"
            "mp) must not straddle slices (their collectives would ride "
            "DCN instead of ICI)")
    arr = np.array(devices).reshape(sizes)
    _global_mesh = Mesh(arr, HYBRID_AXES)
    return _global_mesh


def set_mesh(mesh: Mesh):
    global _global_mesh
    _global_mesh = mesh


def get_mesh() -> Mesh:
    if _global_mesh is None:
        init_mesh()
    return _global_mesh


def has_mesh() -> bool:
    return _global_mesh is not None


def reset_mesh():
    global _global_mesh
    _global_mesh = None


def axis_size(name: str) -> int:
    m = get_mesh()
    return int(m.shape[name]) if name in m.shape else 1


def axis_index(name: str):
    """Trace-time index along a mesh axis (inside shard_map)."""
    return jax.lax.axis_index(name)


def sharding(*spec) -> NamedSharding:
    """NamedSharding over the global mesh for a PartitionSpec."""
    return NamedSharding(get_mesh(), PartitionSpec(*spec))


def replicated() -> NamedSharding:
    return NamedSharding(get_mesh(), PartitionSpec())


def batch_spec(ndim: int = 3):
    """Canonical activation PartitionSpec for a [B, T, ...] tensor on the
    hybrid mesh: batch over the data axes (dp + sharding — ZeRO shards the
    batch over both), sequence over sep, feature dims replicated (mp splits
    happen inside attention/MLP via weight shardings). None when no
    multi-device mesh is active."""
    if not has_mesh():
        return None
    m = get_mesh()
    if len(m.devices.flat) <= 1:
        return None
    data_axes = tuple(ax for ax in ("dp", "sharding")
                      if int(m.shape.get(ax, 1)) > 1)
    sep = "sep" if int(m.shape.get("sep", 1)) > 1 else None
    if not data_axes and sep is None:
        return None
    parts = [data_axes if data_axes else None]
    if ndim >= 2:
        parts.append(sep)
    parts += [None] * (ndim - len(parts))
    return PartitionSpec(*parts)


def strip_axis(spec: PartitionSpec, axis: str) -> PartitionSpec:
    """Remove ``axis`` from every dim entry of a PartitionSpec."""
    parts = []
    for e in tuple(spec):
        if e == axis:
            parts.append(None)
        elif isinstance(e, tuple):
            kept = tuple(a for a in e if a != axis)
            parts.append(kept if len(kept) > 1 else (kept[0] if kept else None))
        else:
            parts.append(e)
    return PartitionSpec(*parts)


def unshard_for_compute(arrs, specs, fsdp_axis="sharding"):
    """ZeRO all-gather at step entry (reference semantics:
    ``GroupShardedStage3`` gathers each param before forward and
    reduce-scatters its grad after backward — SURVEY.md §2.3 sharding).

    Constrains every array to its PartitionSpec with ``fsdp_axis``
    stripped: XLA materializes that as an all-gather over the fsdp axis,
    and the constraint's transpose reduce-scatters the cotangent back to
    the sharded layout — grads land already fsdp-sharded for the (also
    sharded) optimizer update. Being explicit here keeps GSPMD from ever
    propagating the storage-layout 'sharding' split into activations
    (the "Involuntary full rematerialization" failure)."""
    if not has_mesh() or axis_size(fsdp_axis) <= 1:
        return list(arrs)
    out = []
    for a, s in zip(arrs, specs):
        stripped = strip_axis(s, fsdp_axis)
        out.append(jax.lax.with_sharding_constraint(a, sharding(*stripped)))
    return out
