"""``python -m paddle_tpu.distributed.launch`` — the job launcher CLI
(reference: ``python/paddle/distributed/launch/main.py`` — CollectiveController
builds Pod/Containers, sets PADDLE_TRAINER_* env per rank, spawns one process
per device; elastic restart via master watchdog, SURVEY.md §3.4/§5.3).

TPU-native differences:
* One worker process per **host**, not per chip — a JAX process drives every
  local chip; ranks = hosts. ``--nnodes``/``--master`` wire up
  ``jax.distributed.initialize`` through the PADDLE_* env compat shim
  (parallel_env.py).
* ``--run_mode=elastic`` gives checkpoint-restart supervision: on a nonzero
  exit the worker is relaunched (TPU preemption/halt recovery model,
  SURVEY.md §5.3 "TPU equivalent"), up to ``--max_restarts``.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def _parse_args(argv):
    p = argparse.ArgumentParser(prog="paddle_tpu.distributed.launch")
    p.add_argument("--master", default=None,
                   help="coordinator ip:port (defaults to first endpoint)")
    p.add_argument("--nnodes", type=int, default=1, help="number of hosts")
    p.add_argument("--rank", type=int, default=None,
                   help="this host's rank (default: env PADDLE_TRAINER_ID or 0)")
    p.add_argument("--devices", "--gpus", "--xpus", default=None,
                   help="accepted for reference-CLI compat; a TPU host process "
                        "always drives all local chips")
    p.add_argument("--log_dir", default="log")
    p.add_argument("--run_mode", default="collective",
                   choices=["collective", "elastic"])
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("--job_id", default="default")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _worker_env(args):
    env = dict(os.environ)
    rank = args.rank if args.rank is not None else int(env.get("PADDLE_TRAINER_ID", 0))
    master = args.master or env.get("PADDLE_MASTER") or "127.0.0.1:6170"
    endpoints = env.get("PADDLE_TRAINER_ENDPOINTS")
    if not endpoints:
        host, _, port = master.partition(":")
        endpoints = ",".join(f"{host}:{int(port or 6170) + i}"
                             for i in range(args.nnodes))
    eps = endpoints.split(",")
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(args.nnodes),
        "PADDLE_TRAINER_ENDPOINTS": endpoints,
        "PADDLE_CURRENT_ENDPOINT": eps[rank % len(eps)],
        "PADDLE_MASTER": master,
        "PADDLE_JOB_ID": args.job_id,
    })
    return env, rank


def launch_main(argv=None):
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    env, rank = _worker_env(args)
    os.makedirs(args.log_dir, exist_ok=True)
    log_path = os.path.join(args.log_dir, f"workerlog.{rank}")
    cmd = [sys.executable, args.training_script] + args.training_script_args

    restarts = 0
    while True:
        with open(log_path, "ab") as logf:
            proc = subprocess.Popen(cmd, env=env, stdout=logf, stderr=logf)
            try:
                code = proc.wait()
            except KeyboardInterrupt:
                proc.send_signal(signal.SIGTERM)
                proc.wait()
                return 130
        if code == 0:
            return 0
        if args.run_mode != "elastic" or restarts >= args.max_restarts:
            print(f"worker rank {rank} exited with code {code} "
                  f"(log: {log_path})", file=sys.stderr)
            return code
        restarts += 1
        print(f"[elastic] worker failed (code {code}); restart "
              f"{restarts}/{args.max_restarts}", file=sys.stderr)
        time.sleep(1.0)


if __name__ == "__main__":
    sys.exit(launch_main())
