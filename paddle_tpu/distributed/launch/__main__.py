"""``python -m paddle_tpu.distributed.launch`` CLI entry (reference:
``python -m paddle.distributed.launch``)."""
import sys

from .main import launch_main

sys.exit(launch_main())
