"""Jitted SPMD pipeline engine (reference: the 1F1B / interleaved schedules
of ``python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py`` +
the p2p activation exchange in ``pp_utils/p2p_communication.py``; SURVEY.md
§2.3 "PP", §3.4, §7.1 M4, §7.3 item 2).

TPU-native design: instead of per-rank processes exchanging tensors with
``batch_isend_irecv``, the whole pipeline is ONE jitted SPMD program over the
'pp' mesh axis:

* every stage's weights are the same pytree stacked on a leading axis,
  sharded ``P('pp')`` — each device holds its stage's slice;
* a ``lax.scan`` over ``n_micro + n_stages - 1`` ticks runs the classic
  skewed schedule: at tick ``t`` the device at stage ``s`` works on
  microbatch ``t - s`` (masked during the bubble), then hands its activation
  to stage ``s+1`` with ``lax.ppermute`` — the ICI neighbor exchange;
* the backward pass is ``jax.grad`` through the scan: the transpose of
  ``ppermute`` is the reverse rotation, so XLA derives the cooldown
  backward schedule and overlaps transfers with compute automatically.

Constraint (same as the reference's p2p tensor-meta contract): every stage
maps activations to ONE pytree of shapes/dtypes — any pytree (tuples/dicts
of arrays), but uniform across stages; per-stage shape variance must be
padded by the caller (lockstep SPMD rotates one buffer structure). Bubble
fraction matches 1F1B: ``(S-1) / (M + S-1)`` for S stages, M microbatches.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _shard_map(f, mesh, in_specs, out_specs, axis_names=None,
               check_vma=False):
    """Version portability for shard_map: ``jax.shard_map`` (new API,
    ``axis_names``/``check_vma``) when present, else the experimental
    module's (``auto``/``check_rep``) with the argument translation —
    ``axis_names`` lists the MANUAL axes, ``auto`` its complement."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  axis_names=axis_names, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as esm
    manual = frozenset(axis_names) if axis_names else \
        frozenset(mesh.axis_names)
    auto = frozenset(mesh.axis_names) - manual
    return esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma, auto=auto)


def _chunk_key(base_key, micro_idx, chunk_id):
    """Deterministic per-(microbatch, chunk) PRNG key — the reference's
    ``RNGStatesTracker`` contract (``fleet/layers/mpu/random.py``): each
    microbatch × pipeline chunk draws an independent, schedule-invariant
    stream, so a pipelined run with dropout reproduces the sequential
    run bit-for-bit given the same base key."""
    import jax.random as jrandom
    return jrandom.fold_in(jrandom.fold_in(base_key, micro_idx), chunk_id)


def pipeline_spmd(stage_fn, n_stages, n_micro, axis_name="pp",
                  with_keys=False):
    """Per-device pipelined runner (call inside shard_map over ``axis_name``).

    ``stage_fn(stage_params, x) -> y`` applies ONE stage (y.shape == x.shape).
    The returned ``run(stacked_params, micro_inputs)`` expects the local pp
    shard of the [S, ...]-stacked params (leading dim 1) and replicated
    ``micro_inputs`` [M, mb, ...]; it returns the last stage's outputs
    [M, mb, ...], broadcast to every pp rank.

    ``with_keys=True`` changes the contracts to
    ``stage_fn(stage_params, x, key)`` / ``run(..., base_key)`` —
    each tick's call receives the deterministic per-(microbatch, stage)
    key, so stochastic blocks (dropout) are supported.
    """

    def run(stacked_params, micro_inputs, base_key=None):
        params = jax.tree.map(lambda a: a[0], stacked_params)
        stage = jax.lax.axis_index(axis_name)
        m = jax.tree.leaves(micro_inputs)[0].shape[0]
        ticks = m + n_stages - 1
        perm = [(i, i + 1) for i in range(n_stages - 1)]
        is_last = stage == n_stages - 1
        tmap = jax.tree.map

        def tick(carry, t):
            recv, out_buf = carry
            idx = t - stage                     # my microbatch this tick
            active = jnp.logical_and(idx >= 0, idx < m)
            feed = tmap(lambda a: a[jnp.clip(t, 0, m - 1)], micro_inputs)
            x = tmap(lambda f, r: jnp.where(stage == 0, f, r), feed, recv)
            if with_keys:
                key = _chunk_key(base_key, jnp.clip(idx, 0, m - 1), stage)
                y = stage_fn(params, x, key)
            else:
                y = stage_fn(params, x)
            y = tmap(lambda a: jnp.where(active, a, jnp.zeros_like(a)), y)
            slot = jnp.clip(idx, 0, m - 1)
            write = jnp.logical_and(active, is_last)
            out_buf = tmap(lambda b, a: jnp.where(write, b.at[slot].set(a),
                                                  b), out_buf, y)
            recv_next = tmap(lambda a: jax.lax.ppermute(a, axis_name, perm),
                             y)
            return (recv_next, out_buf), None

        out_buf = tmap(jnp.zeros_like, micro_inputs)
        recv0 = tmap(lambda a: jnp.zeros(a.shape[1:], a.dtype), micro_inputs)
        (_, out_buf), _ = jax.lax.scan(tick, (recv0, out_buf),
                                       jnp.arange(ticks))
        # only the last stage wrote non-zeros; broadcast across pp ranks
        return tmap(lambda a: jax.lax.psum(a, axis_name), out_buf)

    return run


def pipeline_spmd_1f1b_bwd(stage_fn, n_stages, n_micro, axis_name="pp",
                           with_keys=False):
    """Per-device interleaved fwd-recompute/backward runner — the memory
    half of the reference's 1F1B schedule
    (``fleet/meta_parallel/pipeline_parallel.py``: steady state holds at
    most S in-flight activations per rank, vs GPipe's M).

    Differentiating :func:`pipeline_spmd` with ``jax.grad`` reproduces
    1F1B's *bubble* but not its *memory*: the scan saves every tick's
    stage residuals, so peak activation memory is O(M·S). This runner is
    the explicit alternative used as the backward of a ``custom_vjp``
    (see :func:`_forward_1f1b`): ONE scan of ``M + 2(S-1)`` ticks where
    every tick recomputes one microbatch's forward (rematerialisation —
    the TPU-native trade of FLOPs for HBM) and back-propagates another,
    keeping stage-input activations in a ``2S-1``-slot ring buffer that
    forward writes and backward releases. Peak memory is
    O(S)·microbatch + one tick's residuals, independent of M.

    Tick math (stage ``s``, microbatch ``j``): forward fires at tick
    ``j + s`` (same skew as the forward scan), backward at tick
    ``j + 2(S-1) - s`` — cotangents enter at the last stage and ride
    the reverse ``ppermute`` one hop per tick. A ring slot is reused
    only after ``2S-1`` microbatches, strictly after its release.
    """

    def run(stacked_params, micro_inputs, d_out, base_key=None):
        import jax.random as jrandom
        params = jax.tree.map(lambda a: a[0], stacked_params)
        stage = jax.lax.axis_index(axis_name)
        m = jax.tree.leaves(micro_inputs)[0].shape[0]
        s_n = n_stages
        ring_n = 2 * s_n - 1
        ticks = m + 2 * (s_n - 1)
        perm_up = [(i, i + 1) for i in range(s_n - 1)]
        perm_dn = [(i + 1, i) for i in range(s_n - 1)]
        is_last = stage == s_n - 1
        const_key = jrandom.PRNGKey(0)
        tmap = jax.tree.map

        def apply(p, x, key):
            return stage_fn(p, x, key) if with_keys else stage_fn(p, x)

        def tick(carry, t):
            recv_f, recv_b, ring, dparams, dx_buf = carry
            # -- forward (recompute) half: microbatch t - stage ----------
            fi = t - stage
            f_act = jnp.logical_and(fi >= 0, fi < m)
            fi_c = jnp.clip(fi, 0, m - 1)
            x_in = tmap(lambda mi, r: jnp.where(stage == 0, mi[fi_c], r),
                        micro_inputs, recv_f)
            kf = (_chunk_key(base_key, fi_c, stage) if with_keys
                  else const_key)
            y = apply(params, x_in, kf)
            y = tmap(lambda a: jnp.where(f_act, a, jnp.zeros_like(a)), y)
            ring = tmap(lambda rg, xa: jnp.where(
                f_act, rg.at[fi_c % ring_n].set(xa), rg), ring, x_in)
            # -- backward half: microbatch t - (2(S-1) - stage) ----------
            bi = t - (2 * s_n - 2 - stage)
            b_act = jnp.logical_and(bi >= 0, bi < m)
            bi_c = jnp.clip(bi, 0, m - 1)
            g_in = tmap(lambda d, r: jnp.where(is_last, d[bi_c], r),
                        d_out, recv_b)
            x_sav = tmap(lambda rg: rg[bi_c % ring_n], ring)
            kb = (_chunk_key(base_key, bi_c, stage) if with_keys
                  else const_key)
            _, vjp = jax.vjp(lambda p, x: apply(p, x, kb), params, x_sav)
            dp, dx = vjp(g_in)
            dparams = tmap(
                lambda acc, g: acc + jnp.where(b_act, g, jnp.zeros_like(g)),
                dparams, dp)
            dx = tmap(lambda a: jnp.where(b_act, a, jnp.zeros_like(a)), dx)
            dx_buf = tmap(lambda b, a: jnp.where(
                jnp.logical_and(b_act, stage == 0), b.at[bi_c].set(a), b),
                dx_buf, dx)
            recv_f = tmap(lambda a: jax.lax.ppermute(a, axis_name, perm_up),
                          y)
            recv_b = tmap(lambda a: jax.lax.ppermute(a, axis_name, perm_dn),
                          dx)
            return (recv_f, recv_b, ring, dparams, dx_buf), None

        def act0(a):
            return jnp.zeros(a.shape[1:], a.dtype)

        carry0 = (tmap(act0, micro_inputs),
                  tmap(act0, micro_inputs),
                  tmap(lambda a: jnp.zeros((ring_n,) + a.shape[1:], a.dtype),
                       micro_inputs),
                  jax.tree.map(jnp.zeros_like, params),
                  tmap(jnp.zeros_like, micro_inputs))
        (_, _, _, dparams, dx_buf), _ = jax.lax.scan(
            tick, carry0, jnp.arange(ticks))
        dstacked = jax.tree.map(lambda a: a[None], dparams)
        return dstacked, tmap(lambda a: jax.lax.psum(a, axis_name), dx_buf)

    return run


def pipeline_spmd_zb_bwd(stage_fn, n_stages, n_micro, axis_name="pp",
                         with_keys=False):
    """Per-device ZB-H1 backward runner (reference:
    ``pipeline_scheduler_pass`` ZBH1 — SURVEY.md §2.3 "Distributed
    passes"): the backward splits into **B** (activation grad — the only
    part the ppermute chain waits on) and **W** (weight grad — no
    inter-stage dependency), with W deferred one tick so it fills slots
    off the wire chain.

    TPU-native split: the tick linearizes its microbatch ONCE
    (``jax.vjp``), evaluates only the dx cotangent in that tick (XLA
    dead-code-eliminates the dW transpose half), and carries the vjp
    closure — a ``jax.tree_util.Partial`` whose leaves are the
    linearization residuals — to the NEXT tick, which evaluates only the
    dp half. Same total FLOPs as the 1F1B-memory scan (one forward
    recompute + one full transpose per microbatch), but the dW matmuls
    sit outside the recv→B→ppermute dependency chain, giving XLA's
    scheduler slack to overlap them with the inter-stage transfers —
    ZBH1's defining property under lockstep SPMD. One extra tick drains
    the last W; one extra (residuals, cotangent) slot per stage is the
    memory cost (ZBH1 ≈ 1F1B memory, unlike ZB-V's 2×).
    """

    def run(stacked_params, micro_inputs, d_out, base_key=None):
        import jax.random as jrandom
        import jax.tree_util as jtu
        params = jax.tree.map(lambda a: a[0], stacked_params)
        stage = jax.lax.axis_index(axis_name)
        m = jax.tree.leaves(micro_inputs)[0].shape[0]
        s_n = n_stages
        ring_n = 2 * s_n - 1
        ticks = m + 2 * (s_n - 1) + 1      # +1: W trails B by one tick
        perm_up = [(i, i + 1) for i in range(s_n - 1)]
        perm_dn = [(i + 1, i) for i in range(s_n - 1)]
        is_last = stage == s_n - 1
        const_key = jrandom.PRNGKey(0)
        tmap = jax.tree.map

        def apply(p, x, key):
            return stage_fn(p, x, key) if with_keys else stage_fn(p, x)

        def lin(p, x, key):
            _, vjp = jax.vjp(lambda pp, xx: apply(pp, xx, key), p, x)
            return vjp

        # The VJP closure is a pytree whose LEAVES are the linearization
        # residuals but whose treedef embeds trace-local metadata — it
        # cannot ride the scan carry as-is. Carry the residual leaves;
        # each tick re-flattens ITS OWN (structurally identical) vjp and
        # unflattens the carried leaves with that tick's treedef to
        # evaluate the previous microbatch's W half.
        def tick(carry, t):
            (recv_f, recv_b, ring, res_prev, g_prev, dparams,
             dx_buf) = carry
            # -- forward (recompute) half: microbatch t - stage ----------
            fi = t - stage
            f_act = jnp.logical_and(fi >= 0, fi < m)
            fi_c = jnp.clip(fi, 0, m - 1)
            x_in = tmap(lambda mi, r: jnp.where(stage == 0, mi[fi_c], r),
                        micro_inputs, recv_f)
            kf = (_chunk_key(base_key, fi_c, stage) if with_keys
                  else const_key)
            y = apply(params, x_in, kf)
            y = tmap(lambda a: jnp.where(f_act, a, jnp.zeros_like(a)), y)
            ring = tmap(lambda rg, xa: jnp.where(
                f_act, rg.at[fi_c % ring_n].set(xa), rg), ring, x_in)
            # -- B half: activation grad of microbatch t - (2(S-1) - s).
            # Linearize once; evaluate ONLY dx (the dW transpose half has
            # no consumer this tick — XLA DCEs it off the wire chain).
            bi = t - (2 * s_n - 2 - stage)
            b_act = jnp.logical_and(bi >= 0, bi < m)
            bi_c = jnp.clip(bi, 0, m - 1)
            g_in = tmap(lambda d, r: jnp.where(is_last, d[bi_c], r),
                        d_out, recv_b)
            x_sav = tmap(lambda rg: rg[bi_c % ring_n], ring)
            kb = (_chunk_key(base_key, bi_c, stage) if with_keys
                  else const_key)
            vjp_now = lin(params, x_sav, kb)
            leaves_now, treedef = jtu.tree_flatten(vjp_now)
            _dp_dead, dx = vjp_now(g_in)       # dW half DCE'd here
            dx = tmap(lambda a: jnp.where(b_act, a, jnp.zeros_like(a)), dx)
            dx_buf = tmap(lambda b, a: jnp.where(
                jnp.logical_and(b_act, stage == 0), b.at[bi_c].set(a), b),
                dx_buf, dx)
            # -- W half: weight grad of the PREVIOUS tick's B microbatch.
            # No wire dependency — only the carried residuals/cotangent.
            wi = t - 1 - (2 * s_n - 2 - stage)
            w_act = jnp.logical_and(wi >= 0, wi < m)
            vjp_prev = jtu.tree_unflatten(treedef, res_prev)
            dp, _dx_dead = vjp_prev(g_prev)    # dx half DCE'd here
            dparams = tmap(
                lambda acc, g: acc + jnp.where(w_act, g, jnp.zeros_like(g)),
                dparams, dp)
            recv_f = tmap(lambda a: jax.lax.ppermute(a, axis_name, perm_up),
                          y)
            recv_b = tmap(lambda a: jax.lax.ppermute(a, axis_name, perm_dn),
                          dx)
            return (recv_f, recv_b, ring, leaves_now, g_in, dparams,
                    dx_buf), None

        def act0(a):
            return jnp.zeros(a.shape[1:], a.dtype)

        zero_x = tmap(act0, micro_inputs)
        res0_shapes = jax.eval_shape(
            lambda p, x: jtu.tree_flatten(lin(p, x, const_key))[0],
            params, zero_x)
        res0 = [jnp.zeros(s.shape, s.dtype) for s in res0_shapes]
        carry0 = (zero_x,
                  tmap(act0, micro_inputs),
                  tmap(lambda a: jnp.zeros((ring_n,) + a.shape[1:], a.dtype),
                       micro_inputs),
                  res0,
                  tmap(act0, micro_inputs),
                  jax.tree.map(jnp.zeros_like, params),
                  tmap(jnp.zeros_like, micro_inputs))
        (_, _, _, _, _, dparams, dx_buf), _ = jax.lax.scan(
            tick, carry0, jnp.arange(ticks))
        dstacked = jax.tree.map(lambda a: a[None], dparams)
        return dstacked, tmap(lambda a: jax.lax.psum(a, axis_name), dx_buf)

    return run


def _forward_1f1b(stage_fn, mesh, n_stages, n_micro, axis_name, with_keys,
                  schedule="1f1b"):
    """Differentiable pipelined forward whose VJP is the interleaved
    1F1B-memory scan (:func:`pipeline_spmd_1f1b_bwd`) — or its ZB-H1
    B/W-split variant (:func:`pipeline_spmd_zb_bwd`) — instead of
    ``jax.grad``-through-scan. Forward results are bit-identical to the
    default schedule (it IS the same forward runner); only the backward's
    schedule/memory differ — gradients remain exact (rematerialised)."""
    import numpy as np

    fwd_run = pipeline_spmd(stage_fn, n_stages, n_micro, axis_name,
                            with_keys=with_keys)
    bwd_maker = (pipeline_spmd_zb_bwd if schedule == "zb"
                 else pipeline_spmd_1f1b_bwd)
    bwd_run = bwd_maker(stage_fn, n_stages, n_micro, axis_name,
                        with_keys=with_keys)

    def _p_specs(tree):
        return jax.tree.map(lambda a: P(axis_name), tree)

    @jax.custom_vjp
    def call(stacked_params, micro_inputs, rng_key):
        mapped = _shard_map(
            fwd_run, mesh=mesh,
            in_specs=(_p_specs(stacked_params), P(), P()), out_specs=P(),
            axis_names={axis_name}, check_vma=False)
        return jax.jit(mapped)(stacked_params, micro_inputs, rng_key)

    def fwd(stacked_params, micro_inputs, rng_key):
        return (call(stacked_params, micro_inputs, rng_key),
                (stacked_params, micro_inputs, rng_key))

    def bwd(res, d_out):
        stacked_params, micro_inputs, rng_key = res
        specs = _p_specs(stacked_params)
        mapped = _shard_map(
            bwd_run, mesh=mesh, in_specs=(specs, P(), P(), P()),
            out_specs=(specs, P()), axis_names={axis_name}, check_vma=False)
        dstacked, dmicro = jax.jit(mapped)(stacked_params, micro_inputs,
                                           d_out, rng_key)
        dkey = np.zeros(rng_key.shape, dtype=jax.dtypes.float0)
        return dstacked, dmicro, dkey

    call.defvjp(fwd, bwd)
    return call


def pipeline_spmd_interleaved(stage_fn, n_stages, n_micro, vpp,
                              axis_name="pp", with_keys=False):
    """Interleaved (VPP) per-device runner — the reference
    ``PipelineParallelWithInterleave``: L = S·v chunks, chunk c on device
    c mod S; each tick every device runs its v chunks and the ring wraps
    (S-1 → 0) carrying activations to the next virtual stage. Expects the
    local param shard with leading dim v in *slot* order (slot k = chunk
    ``stage + k·S``) — ``pipeline_forward`` pre-permutes.
    ``with_keys`` as in :func:`pipeline_spmd` (chunk id = stage + k·S).
    """

    def run(stacked_params, micro_inputs, base_key=None):
        stage = jax.lax.axis_index(axis_name)
        m = micro_inputs.shape[0]
        chunks = n_stages * vpp
        ticks = m + chunks - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        act_shape = micro_inputs.shape[1:]
        act_dtype = micro_inputs.dtype
        is_last = stage == n_stages - 1

        def tick(carry, t):
            recv, out_buf = carry          # recv [v, ...]
            outs = []
            for k in range(vpp):
                params_k = jax.tree.map(lambda a: a[k], stacked_params)
                c = stage + k * n_stages   # my chunk id at slot k
                idx = t - c
                active = jnp.logical_and(idx >= 0, idx < m)
                if k == 0:
                    feed = micro_inputs[jnp.clip(t, 0, m - 1)]
                    x = jnp.where(stage == 0, feed, recv[0])
                else:
                    x = recv[k]
                if with_keys:
                    key = _chunk_key(base_key, jnp.clip(idx, 0, m - 1), c)
                    y = stage_fn(params_k, x, key)
                else:
                    y = stage_fn(params_k, x)
                y = jnp.where(active, y, jnp.zeros_like(y))
                if k == vpp - 1:
                    slot = jnp.clip(idx, 0, m - 1)
                    write = jnp.logical_and(active, is_last)
                    out_buf = jnp.where(write, out_buf.at[slot].set(y),
                                        out_buf)
                outs.append(y)
            sent = jax.lax.ppermute(jnp.stack(outs), axis_name, perm)
            # ring wrap S-1 → 0 advances the virtual stage: on device 0,
            # incoming slot k feeds chunk (k+1)·S, i.e. local slot k+1
            shifted = jnp.concatenate(
                [jnp.zeros((1,) + act_shape, act_dtype), sent[:-1]], axis=0)
            recv_next = jnp.where(stage == 0, shifted, sent)
            return (recv_next, out_buf), None

        out_buf = jnp.zeros((m,) + act_shape, act_dtype)
        recv0 = jnp.zeros((vpp,) + act_shape, act_dtype)
        (_, out_buf), _ = jax.lax.scan(tick, (recv0, out_buf),
                                       jnp.arange(ticks))
        return jax.lax.psum(out_buf, axis_name)

    return run


def pipeline_seq_forward(block_fn, stacked_params, micro_inputs, *, pre=None,
                         post=None, mesh=None, axis_name="pp",
                         n_stages=None, vpp_degree=1, rng_key=None,
                         schedule="fthenb"):
    """Full-model pipelined forward for stage-heterogeneous LMs (reference:
    ``pp_layers.py`` stage partition with embedding on stage 0, head on
    stage S-1, ``SharedLayerDesc`` tied weights).

    TPU-native stage heterogeneity: on GPU pipelines the embedding/head
    live on the first/last rank because weights are pinned to processes.
    Under SPMD there is no pinning — so ``pre`` (embedding) and ``post``
    (final norm + head) run as plain sharded compute over the WHOLE mesh
    (every chip's MXU works on the vocab matmul instead of 1/S of them),
    and only the homogeneous decoder-block run is scheduled through the
    ppermute pipeline. Tied embeddings need no ``allreduce_shared_weight``:
    reference (``pipeline_parallel.py`` shared-weight sync) — here the tied
    array simply appears in both ``pre`` and ``post`` closures and
    ``jax.grad`` sums the two contributions.

    ``pre``/``post``: batched callables ``x -> y`` applied to the
    microbatches flattened to ONE [M·mb, ...] batch (bigger MXU matmuls
    than per-micro application, and activation sharding constraints see
    their canonical [B, T, H] rank); ``block_fn(chunk_params, x)`` applies
    one pipeline chunk. ``micro_inputs``: [M, mb, ...]. With ``rng_key``
    set, ``block_fn(chunk_params, x, key)`` gets per-(micro, chunk) keys
    and ``pre``/``post`` become ``fn(x, key)`` with their own derived
    keys (they run once over the flat batch, outside the schedule, so a
    single key each keeps them schedule-invariant too).
    """
    def _flat_apply(fn, x, key=None):
        m, mb = x.shape[:2]
        flat = x.reshape((m * mb,) + tuple(x.shape[2:]))
        y = fn(flat) if key is None else fn(flat, key)
        return y.reshape((m, mb) + tuple(y.shape[1:]))

    import jax.random as jrandom
    h = micro_inputs
    if pre is not None:
        h = _flat_apply(pre, h, None if rng_key is None
                        else jrandom.fold_in(rng_key, 0x5e90))
    h = pipeline_forward(block_fn, stacked_params, h, mesh=mesh,
                         axis_name=axis_name, n_stages=n_stages,
                         vpp_degree=vpp_degree, rng_key=rng_key,
                         schedule=schedule)
    if post is not None:
        h = _flat_apply(post, h, None if rng_key is None
                        else jrandom.fold_in(rng_key, 0x5e91))
    return h


class PipelinedModule:
    """Functionalize a ``PipelineLayer`` for the jitted SPMD engine —
    the bridge that lets a REAL stage-heterogeneous LM (embedding stage,
    N decoder blocks, norm+head stage, optionally tied embeddings) train
    through ``pipeline_forward`` (reference:
    ``fleet/meta_parallel/pipeline_parallel.py`` 1F1B over the stage
    modules built by ``pp_layers.py``).

    Split: ``PipelineLayer.homogeneous_run()`` finds the longest run of
    identical-signature layers (the decoder blocks); everything before is
    the *pre* segment (embedding), everything after the *post* segment
    (final norm + lm head). Pre/post params stay unstacked ("edge"
    params, sharded by the caller's TP/fsdp rules); block params are
    stacked ``[S·vpp, layers_per_chunk, ...]`` and sharded ``P('pp')``.
    Tied embeddings (``SharedLayerDesc``) need no shared-weight allreduce:
    the tied Parameter is deduped into ONE edge array consumed by both
    segments, so ``jax.grad`` sums the two contributions.

    Stochastic blocks (dropout): pass ``rng_key`` to ``__call__`` — the
    engine threads deterministic per-(microbatch, chunk) keys through
    the scan (reference ``RNGStatesTracker`` semantics), so a pipelined
    run reproduces the sequential run given the same base key. Without
    a key the blocks run with a constant key (dropout degenerates to a
    fixed mask — fine for the dropout-free pretrain configs).

    Mutable buffers (BN running stats) remain unsupported by design:
    under the skewed schedule each stage sees microbatches at different
    ticks, so a buffer update order would be schedule-dependent — the
    reference has the same constraint in spirit (per-stage BN is local
    to a rank there; here weights are stacked across stages).

    Usage::

        pm = PipelinedModule(pipe_layer, mesh=mesh)
        out = pm(pm.edge_arrays(), pm.stacked_arrays(), micro_x)  # [M, ...]
    """

    def __init__(self, pipe_layer, mesh=None, axis_name="pp", n_stages=None,
                 vpp_degree=None, schedule="fthenb"):
        from . import mesh as mesh_mod
        from ..framework.functional import FunctionalModule

        self.schedule = schedule
        self.axis_name = axis_name
        self.mesh = mesh or (mesh_mod.get_mesh() if mesh_mod.has_mesh()
                             else None)
        if n_stages is None:
            n_stages = (int(self.mesh.shape[axis_name])
                        if self.mesh is not None and
                        axis_name in self.mesh.shape else pipe_layer._num_stages)
        self.n_stages = n_stages
        self.vpp = int(vpp_degree if vpp_degree is not None
                       else getattr(pipe_layer, "_vpp", 1))
        n_chunks = self.n_stages * self.vpp

        lo, hi = pipe_layer.homogeneous_run()
        if hi - lo < n_chunks:
            raise ValueError(
                f"homogeneous block run has {hi - lo} layers < "
                f"{n_chunks} pipeline chunks (stages {self.n_stages} × vpp "
                f"{self.vpp})")
        # trailing blocks that don't fill a chunk fold into the post segment
        hi -= (hi - lo) % n_chunks
        self.blocks = pipe_layer.run_function[lo:hi]
        self.lpc = len(self.blocks) // n_chunks          # layers per chunk
        self.n_chunks = n_chunks

        self._edge = _EdgeSegments(pipe_layer.run_function[:lo],
                                   pipe_layer.run_function[hi:])
        self._fm_pre = FunctionalModule(self._edge, method=self._edge.run_pre)
        self._fm_post = FunctionalModule(self._edge, method=self._edge.run_post)
        self._fm_blk = FunctionalModule(self.blocks[0])
        self._blk_params = [list(b.parameters()) for b in self.blocks]
        for ps in self._blk_params:
            assert len(ps) == len(self._fm_blk.params), \
                "pipeline blocks must share one parameter signature"
        if any(b for blk in self.blocks for b in blk.buffers()):
            raise ValueError("pipelined blocks with mutable buffers are not "
                             "supported (BN stats can't thread the schedule)")
        self.edge_params = self._fm_pre.params           # deduped, tied once

    # -- state ---------------------------------------------------------------
    def edge_arrays(self):
        return [p._data for p in self.edge_params]

    def stacked_arrays(self):
        """Stack each block-param leaf [n_chunks, lpc, ...] in chunk order
        (chunk c = blocks [c·lpc, (c+1)·lpc))."""
        outs = []
        for j in range(len(self._fm_blk.params)):
            leaf = jnp.stack([ps[j]._data for ps in self._blk_params])
            outs.append(leaf.reshape((self.n_chunks, self.lpc)
                                     + tuple(leaf.shape[1:])))
        return outs

    def write_back(self, edge_arrs, stacked_arrs):
        """Write updated arrays back into the eager Parameters."""
        for p, a in zip(self.edge_params, edge_arrs):
            p._data = a
        for j, a in enumerate(stacked_arrs):
            flat = a.reshape((-1,) + tuple(a.shape[2:]))
            for i, ps in enumerate(self._blk_params):
                ps[j]._data = flat[i]

    def unstack_grads(self, stacked_grads):
        """Per-block grad list (parallel to ``self.blocks``) from stacked
        grads — for eager ``.grad`` write-back in train_batch."""
        per_block = [[] for _ in self.blocks]
        for g in stacked_grads:
            flat = g.reshape((-1,) + tuple(g.shape[2:]))
            for i in range(len(self.blocks)):
                per_block[i].append(flat[i])
        return per_block

    # -- the pure pipelined forward -----------------------------------------
    def __call__(self, edge_arrs, stacked_arrs, micro_inputs, rng_key=None):
        import jax.random as jrandom
        const_key = jrandom.PRNGKey(0)
        threaded = rng_key is not None

        if threaded:
            def chunk_fn(chunk_arrs, x, key):
                for l in range(self.lpc):
                    arrs = [a[l] for a in chunk_arrs]
                    x, _ = self._fm_blk(arrs, [],
                                        jrandom.fold_in(key, l), x)
                return x

            pre = post = None
            if self._edge.has_pre:
                def pre(x, key):
                    return self._fm_pre(edge_arrs, [], key, x)[0]
            if self._edge.has_post:
                def post(x, key):
                    return self._fm_post(edge_arrs, [], key, x)[0]
        else:
            def chunk_fn(chunk_arrs, x):
                for l in range(self.lpc):
                    arrs = [a[l] for a in chunk_arrs]
                    x, _ = self._fm_blk(arrs, [], const_key, x)
                return x

            pre = post = None
            if self._edge.has_pre:
                def pre(x):
                    return self._fm_pre(edge_arrs, [], const_key, x)[0]
            if self._edge.has_post:
                def post(x):
                    return self._fm_post(edge_arrs, [], const_key, x)[0]
        return pipeline_seq_forward(chunk_fn, stacked_arrs, micro_inputs,
                                    pre=pre, post=post, mesh=self.mesh,
                                    axis_name=self.axis_name,
                                    n_stages=self.n_stages,
                                    vpp_degree=self.vpp, rng_key=rng_key,
                                    schedule=self.schedule)


class _EdgeSegments:
    """Container for the pre/post (embedding / norm+head) segments with
    tied parameters deduped across both (``Layer.named_parameters`` memo)."""

    def __init__(self, pre_layers, post_layers):
        from ..nn.layer import Layer

        class _Holder(Layer):
            pass

        holder = _Holder()
        for i, l in enumerate(pre_layers):
            holder.add_sublayer(f"pre_{i}", l)
        for i, l in enumerate(post_layers):
            holder.add_sublayer(f"post_{i}", l)
        self._holder = holder
        self._pre = list(pre_layers)
        self._post = list(post_layers)
        self.has_pre = bool(pre_layers)
        self.has_post = bool(post_layers)

    # FunctionalModule protocol: parameters()/buffers()/sublayers()
    def parameters(self):
        return self._holder.parameters()

    def named_parameters(self):
        return self._holder.named_parameters()

    def buffers(self):
        return self._holder.buffers()

    def sublayers(self, include_self=False):
        return self._holder.sublayers(include_self=False)

    @staticmethod
    def _run(layers, x):
        for l in layers:
            fwd = getattr(l, "_shared_forward", None)
            x = fwd(l, x) if fwd is not None else l(x)
        return x

    def run_pre(self, x):
        return self._run(self._pre, x)

    def run_post(self, x):
        return self._run(self._post, x)


def _pad_to(a, shape):
    pads = [(0, t - s) for s, t in zip(a.shape, shape)]
    return jnp.pad(a, pads) if any(p[1] for p in pads) else a


def pipeline_forward_hetero(stage_fns, per_stage_params, micro_inputs, *,
                            mesh=None, axis_name="pp", rng_key=None,
                            schedule="fthenb"):
    """Pipelined forward over stages with DIFFERENT bodies, parameter
    pytrees, and activation widths (reference: per-microbatch tensor-meta
    exchange in ``pp_utils/p2p_communication.py`` — recv shapes are
    negotiated per stage, so heterogeneous stages work; VERDICT round-4
    item 7 asks for the same freedom here).

    TPU-native handling: lockstep SPMD rotates ONE wire buffer, so the
    engine (not the caller) absorbs the heterogeneity —

    * per-stage param leaves are zero-padded to the positionwise max
      shape and stacked ``[S, ...]`` (shardable ``P('pp')`` like the
      homogeneous path; the padding is dead weight only on the stages
      that don't use it);
    * activations ride the wire padded to the elementwise max of every
      stage's in/out shape; each stage statically slices its true input
      shape and re-pads its output (pad/slice transpose cleanly, so all
      three backward schedules work unchanged);
    * the per-stage body is picked by ``lax.switch`` on a stage-id leaf
      threaded through the stacked params (each device evaluates only
      its own branch).

    ``stage_fns``: list of S callables ``fn(params_s, x)`` (or
    ``fn(params_s, x, key)`` with ``rng_key``); ``per_stage_params``:
    list of S pytrees; ``micro_inputs``: [M, mb, *in_shape_0] single
    array. Returns the last stage's outputs [M, mb, *out_shape_last],
    exactly as a sequential apply would.
    """
    from . import mesh as mesh_mod
    mesh = mesh or mesh_mod.get_mesh()
    n_stages_ = len(stage_fns)
    if len(per_stage_params) != n_stages_:
        raise ValueError(f"{n_stages_} stage_fns but "
                         f"{len(per_stage_params)} param trees")
    with_keys = rng_key is not None
    m, mb = micro_inputs.shape[0], micro_inputs.shape[1]

    # per-stage activation shapes by abstract evaluation of the chain
    flat_stages = [list(jax.tree.leaves(p)) for p in per_stage_params]
    treedefs = [jax.tree.structure(p) for p in per_stage_params]
    x_shape = tuple(micro_inputs.shape[2:])
    in_shapes, out_shapes = [], []
    x_sds = jax.ShapeDtypeStruct((mb,) + x_shape, micro_inputs.dtype)
    key0 = jax.random.PRNGKey(0) if with_keys else None
    for s in range(n_stages_):
        in_shapes.append(tuple(x_sds.shape[1:]))
        x_sds = jax.eval_shape(
            lambda p, x, fn=stage_fns[s]: (fn(p, x, key0) if with_keys
                                           else fn(p, x)),
            per_stage_params[s], x_sds)
        out_shapes.append(tuple(x_sds.shape[1:]))
    if len(set(len(sh) for sh in in_shapes + out_shapes)) != 1:
        raise ValueError("heterogeneous stages must agree on activation "
                         f"RANK (got in={in_shapes}, out={out_shapes})")
    wire_shape = tuple(max(sh[i] for sh in in_shapes + out_shapes)
                       for i in range(len(x_shape)))

    # Storage slots: stages may have entirely different leaf counts and
    # orders, so leaves are binned by (rank, dtype) — the j-th rank-R
    # dtype-D leaf of any stage shares a stacked slot with the j-th such
    # leaf of every other stage, zero-padded to the slot's max shape.
    slots = []                       # slot id -> (rank, dtype)
    slot_of = []                     # per stage: leaf index -> slot id
    for f in flat_stages:
        seen = {}
        ids = []
        for leaf in f:
            kkey = (jnp.ndim(leaf), jnp.asarray(leaf).dtype)
            occ = seen.get(kkey, 0)
            seen[kkey] = occ + 1
            have = [i for i, sk in enumerate(slots) if sk == kkey]
            if occ < len(have):
                ids.append(have[occ])
            else:
                slots.append(kkey)
                ids.append(len(slots) - 1)
        slot_of.append(ids)
    max_shapes = []
    for sid, (rk, dt) in enumerate(slots):
        shs = [jnp.shape(f[j]) for f, ids in zip(flat_stages, slot_of)
               for j, s_id in enumerate(ids) if s_id == sid]
        max_shapes.append(tuple(max(sh[i] for sh in shs)
                                for i in range(rk)))
    stacked = []
    for sid, (rk, dt) in enumerate(slots):
        per_stage = []
        for f, ids in zip(flat_stages, slot_of):
            js = [j for j, s_id in enumerate(ids) if s_id == sid]
            per_stage.append(_pad_to(jnp.asarray(f[js[0]]), max_shapes[sid])
                             if js else jnp.zeros(max_shapes[sid], dt))
        stacked.append(jnp.stack(per_stage))
    # stage-id leaf: [S] — the switch index each device reads from its
    # shard. Stored as float32 so the backward schedules can form its
    # (discarded) cotangent; int leaves would yield float0 grads the
    # scan accumulators cannot add.
    stacked_all = {"leaves": stacked,
                   "sid": jnp.arange(n_stages_, dtype=jnp.float32)}

    def uni_stage(params_slice, x, key=None):
        sid = params_slice["sid"].astype(jnp.int32)
        leaves = params_slice["leaves"]

        def make_branch(s):
            def branch(leaves_x):
                lvs, xx = leaves_x
                f_leaves = [lvs[slot_of[s][j]][tuple(
                                slice(0, d) for d in
                                jnp.shape(flat_stages[s][j]))]
                            for j in range(len(flat_stages[s]))]
                p_s = jax.tree.unflatten(treedefs[s], f_leaves)
                x_s = xx[(slice(None),) + tuple(slice(0, d)
                                                for d in in_shapes[s])]
                y = (stage_fns[s](p_s, x_s, key) if with_keys
                     else stage_fns[s](p_s, x_s))
                return _pad_to(y, (y.shape[0],) + wire_shape)
            return branch

        return jax.lax.switch(sid, [make_branch(s)
                                    for s in range(n_stages_)], (leaves, x))

    micro_padded = _pad_to(micro_inputs, micro_inputs.shape[:2] + wire_shape)
    out = pipeline_forward(uni_stage, stacked_all, micro_padded,
                           mesh=mesh, axis_name=axis_name,
                           n_stages=n_stages_, vpp_degree=1,
                           rng_key=rng_key, schedule=schedule)
    last = out_shapes[-1]
    return out[(slice(None), slice(None))
               + tuple(slice(0, d) for d in last)]


def stacked_fsdp_spec(arr, pp_axis="pp", fsdp_axis="sharding"):
    """PartitionSpec for a ``[n_chunks, lpc, *param]`` stacked block leaf:
    pp on dim 0, ZeRO-3 ``fsdp_axis`` on the first weight dim of 2-D
    weights when divisible (params-sharded-at-rest; GSPMD all-gathers on
    use and reduce-scatters grads). Shared by the config-4 dryrun and the
    hybrid tests so the placement rule lives in one place."""
    from . import mesh as mesh_mod
    n = mesh_mod.axis_size(fsdp_axis)
    if n > 1 and arr.ndim >= 4 and arr.shape[2] % n == 0:
        return P(pp_axis, None, fsdp_axis)
    return P(pp_axis)


def stacked_hybrid_spec(arr, pp_axis="pp", fsdp_axis="sharding",
                        mp_axis="mp"):
    """Full config-4 placement for a ``[n_chunks, lpc, *param]`` stacked
    block leaf: pp on dim 0, ZeRO-3 ``fsdp_axis`` on the input dim and
    Megatron ``mp_axis`` (column parallel) on the output dim of 2-D
    weights, each applied when the mesh axis exists >1 and divides the
    dim (reference: the GPT-1.3B dp×mp×pp×sharding hybrid —
    ``fleet/meta_parallel`` HybridParallelClipGrad world; SURVEY.md §2.4
    config 4, §3.4)."""
    from . import mesh as mesh_mod
    n_f = mesh_mod.axis_size(fsdp_axis)
    n_m = mesh_mod.axis_size(mp_axis)
    fsdp_ok = n_f > 1 and arr.ndim >= 4 and arr.shape[2] % n_f == 0
    mp_ok = n_m > 1 and arr.ndim == 4 and arr.shape[3] % n_m == 0
    if fsdp_ok and mp_ok:
        return P(pp_axis, None, fsdp_axis, mp_axis)
    if fsdp_ok:
        return P(pp_axis, None, fsdp_axis)
    if mp_ok:
        return P(pp_axis, None, None, mp_axis)
    return P(pp_axis)


def pipeline_forward(stage_fn, stacked_params, micro_inputs, *, mesh=None,
                     axis_name="pp", n_stages=None, vpp_degree=1,
                     rng_key=None, schedule="fthenb"):
    """Pipelined forward over the global mesh's pp axis (differentiable,
    jit-compatible).

    ``stacked_params``: pytree, leaves stacked [S·vpp, ...] in chunk order
    (chunk = consecutive layer group). ``micro_inputs``: [M, mb, ...].
    ``vpp_degree`` > 1 selects the interleaved (VPP) schedule.
    With ``rng_key`` set, ``stage_fn(params, x, key)`` receives a
    deterministic per-(microbatch, chunk) key — stochastic stages
    (dropout) produce the same result as a sequential run with the same
    base key, regardless of schedule or pp size.

    ``schedule`` picks the *backward* memory profile (reference:
    ``pipeline_scheduler_pass`` FThenB/1F1B — SURVEY.md §2.3):

    * ``"fthenb"`` (default): ``jax.grad`` through the forward scan —
      1F1B-like bubble, GPipe-like memory (O(M) saved residual sets).
    * ``"1f1b"``: ``custom_vjp`` with the interleaved recompute/backward
      scan — O(S) in-flight activations independent of M, one extra
      forward of FLOPs (remat). Requires ``vpp_degree == 1``.
    * ``"zb"``: ZB-H1 — like ``"1f1b"`` but the backward splits into B
      (activation grad, on the ppermute chain) and W (weight grad,
      deferred one tick off the chain). Same FLOPs and O(S) memory;
      the dW matmuls gain scheduling slack against the transfers.
    """
    from . import mesh as mesh_mod
    mesh = mesh or mesh_mod.get_mesh()
    mesh_pp = int(mesh.shape[axis_name]) if axis_name in mesh.shape else 1
    if n_stages is not None and mesh_pp > 1 and n_stages != mesh_pp:
        raise ValueError(f"n_stages={n_stages} != mesh '{axis_name}' size "
                         f"{mesh_pp}: chunks would be silently dropped")
    n_stages = mesh_pp
    if schedule not in ("fthenb", "1f1b", "zb"):
        raise ValueError(f"unknown pipeline schedule {schedule!r} "
                         "(expected 'fthenb', '1f1b' or 'zb')")
    with_keys = rng_key is not None
    if n_stages == 1:
        n_chunks = jax.tree.leaves(stacked_params)[0].shape[0]

        def seq_all(x, micro_idx):
            for c in range(n_chunks):
                p = jax.tree.map(lambda a: a[c], stacked_params)
                if with_keys:
                    x = stage_fn(p, x, _chunk_key(rng_key, micro_idx, c))
                else:
                    x = stage_fn(p, x)
            return x
        m = jax.tree.leaves(micro_inputs)[0].shape[0]
        return jax.vmap(seq_all)(micro_inputs, jnp.arange(m))
    n_micro = int(jax.tree.leaves(micro_inputs)[0].shape[0])
    if schedule in ("1f1b", "zb"):
        if vpp_degree > 1:
            raise ValueError(f"schedule={schedule!r} supports vpp_degree == "
                             "1 only (interleaved-VPP keeps the default "
                             "backward)")
        import jax.random as jrandom
        key = rng_key if with_keys else jrandom.PRNGKey(0)
        call = _forward_1f1b(stage_fn, mesh, n_stages, n_micro, axis_name,
                             with_keys, schedule=schedule)
        return call(stacked_params, micro_inputs, key)
    if vpp_degree > 1:
        if not hasattr(micro_inputs, "shape"):
            raise ValueError("the interleaved (VPP) schedule supports a "
                             "single-array activation; pack pytree "
                             "activations into one array or use vpp=1")
        # chunk-major [c] → slot-major [(k, d) → d*v + k ... ]: device d's
        # slot k must hold chunk d + k·S, and P('pp') splits contiguously,
        # so global order becomes [d=0: chunks 0, S, 2S…; d=1: 1, S+1, …]
        order = jnp.asarray([d + k * n_stages
                             for d in range(n_stages)
                             for k in range(vpp_degree)])
        stacked_params = jax.tree.map(
            lambda a: jnp.take(a, order, axis=0), stacked_params)
        run = pipeline_spmd_interleaved(stage_fn, n_stages, n_micro,
                                        vpp_degree, axis_name,
                                        with_keys=with_keys)
    else:
        run = pipeline_spmd(stage_fn, n_stages, n_micro, axis_name,
                            with_keys=with_keys)
    p_specs = jax.tree.map(lambda a: P(axis_name), stacked_params)
    # bare P() is a pytree-prefix spec: replicates every activation leaf
    in_specs = (p_specs, P()) + ((P(),) if with_keys else ())
    mapped = _shard_map(
        run, mesh=mesh, in_specs=in_specs, out_specs=P(),
        axis_names={axis_name}, check_vma=False)
    args = (stacked_params, micro_inputs) + ((rng_key,) if with_keys else ())
    # axes outside axis_name stay in "auto" sharding mode, which shard_map
    # only supports under jit — so compile here; callers' outer jit still
    # fuses through (nested jit is inlined)
    return jax.jit(mapped)(*args)
