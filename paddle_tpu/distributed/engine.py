"""Jitted SPMD pipeline engine (reference: the 1F1B / interleaved schedules
of ``python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py`` +
the p2p activation exchange in ``pp_utils/p2p_communication.py``; SURVEY.md
§2.3 "PP", §3.4, §7.1 M4, §7.3 item 2).

TPU-native design: instead of per-rank processes exchanging tensors with
``batch_isend_irecv``, the whole pipeline is ONE jitted SPMD program over the
'pp' mesh axis:

* every stage's weights are the same pytree stacked on a leading axis,
  sharded ``P('pp')`` — each device holds its stage's slice;
* a ``lax.scan`` over ``n_micro + n_stages - 1`` ticks runs the classic
  skewed schedule: at tick ``t`` the device at stage ``s`` works on
  microbatch ``t - s`` (masked during the bubble), then hands its activation
  to stage ``s+1`` with ``lax.ppermute`` — the ICI neighbor exchange;
* the backward pass is ``jax.grad`` through the scan: the transpose of
  ``ppermute`` is the reverse rotation, so XLA derives the cooldown
  backward schedule and overlaps transfers with compute automatically.

Constraint (same as the reference's p2p tensor-meta contract): every stage
maps activations to the same shape/dtype. Bubble fraction matches 1F1B:
``(S-1) / (M + S-1)`` for S stages, M microbatches.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def pipeline_spmd(stage_fn, n_stages, n_micro, axis_name="pp"):
    """Per-device pipelined runner (call inside shard_map over ``axis_name``).

    ``stage_fn(stage_params, x) -> y`` applies ONE stage (y.shape == x.shape).
    The returned ``run(stacked_params, micro_inputs)`` expects the local pp
    shard of the [S, ...]-stacked params (leading dim 1) and replicated
    ``micro_inputs`` [M, mb, ...]; it returns the last stage's outputs
    [M, mb, ...], broadcast to every pp rank.
    """

    def run(stacked_params, micro_inputs):
        params = jax.tree.map(lambda a: a[0], stacked_params)
        stage = jax.lax.axis_index(axis_name)
        m = micro_inputs.shape[0]
        ticks = m + n_stages - 1
        perm = [(i, i + 1) for i in range(n_stages - 1)]
        act_shape = micro_inputs.shape[1:]
        act_dtype = micro_inputs.dtype
        is_last = stage == n_stages - 1

        def tick(carry, t):
            recv, out_buf = carry
            idx = t - stage                     # my microbatch this tick
            active = jnp.logical_and(idx >= 0, idx < m)
            feed = micro_inputs[jnp.clip(t, 0, m - 1)]
            x = jnp.where(stage == 0, feed, recv)
            y = stage_fn(params, x)
            y = jnp.where(active, y, jnp.zeros_like(y))
            slot = jnp.clip(idx, 0, m - 1)
            write = jnp.logical_and(active, is_last)
            out_buf = jnp.where(write, out_buf.at[slot].set(y), out_buf)
            recv_next = jax.lax.ppermute(y, axis_name, perm)
            return (recv_next, out_buf), None

        out_buf = jnp.zeros((m,) + act_shape, act_dtype)
        recv0 = jnp.zeros(act_shape, act_dtype)
        (_, out_buf), _ = jax.lax.scan(tick, (recv0, out_buf),
                                       jnp.arange(ticks))
        # only the last stage wrote non-zeros; broadcast across pp ranks
        return jax.lax.psum(out_buf, axis_name)

    return run


def pipeline_spmd_interleaved(stage_fn, n_stages, n_micro, vpp,
                              axis_name="pp"):
    """Interleaved (VPP) per-device runner — the reference
    ``PipelineParallelWithInterleave``: L = S·v chunks, chunk c on device
    c mod S; each tick every device runs its v chunks and the ring wraps
    (S-1 → 0) carrying activations to the next virtual stage. Expects the
    local param shard with leading dim v in *slot* order (slot k = chunk
    ``stage + k·S``) — ``pipeline_forward`` pre-permutes.
    """

    def run(stacked_params, micro_inputs):
        stage = jax.lax.axis_index(axis_name)
        m = micro_inputs.shape[0]
        chunks = n_stages * vpp
        ticks = m + chunks - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        act_shape = micro_inputs.shape[1:]
        act_dtype = micro_inputs.dtype
        is_last = stage == n_stages - 1

        def tick(carry, t):
            recv, out_buf = carry          # recv [v, ...]
            outs = []
            for k in range(vpp):
                params_k = jax.tree.map(lambda a: a[k], stacked_params)
                c = stage + k * n_stages   # my chunk id at slot k
                idx = t - c
                active = jnp.logical_and(idx >= 0, idx < m)
                if k == 0:
                    feed = micro_inputs[jnp.clip(t, 0, m - 1)]
                    x = jnp.where(stage == 0, feed, recv[0])
                else:
                    x = recv[k]
                y = stage_fn(params_k, x)
                y = jnp.where(active, y, jnp.zeros_like(y))
                if k == vpp - 1:
                    slot = jnp.clip(idx, 0, m - 1)
                    write = jnp.logical_and(active, is_last)
                    out_buf = jnp.where(write, out_buf.at[slot].set(y),
                                        out_buf)
                outs.append(y)
            sent = jax.lax.ppermute(jnp.stack(outs), axis_name, perm)
            # ring wrap S-1 → 0 advances the virtual stage: on device 0,
            # incoming slot k feeds chunk (k+1)·S, i.e. local slot k+1
            shifted = jnp.concatenate(
                [jnp.zeros((1,) + act_shape, act_dtype), sent[:-1]], axis=0)
            recv_next = jnp.where(stage == 0, shifted, sent)
            return (recv_next, out_buf), None

        out_buf = jnp.zeros((m,) + act_shape, act_dtype)
        recv0 = jnp.zeros((vpp,) + act_shape, act_dtype)
        (_, out_buf), _ = jax.lax.scan(tick, (recv0, out_buf),
                                       jnp.arange(ticks))
        return jax.lax.psum(out_buf, axis_name)

    return run


def pipeline_seq_forward(block_fn, stacked_params, micro_inputs, *, pre=None,
                         post=None, mesh=None, axis_name="pp",
                         vpp_degree=1):
    """Full-model pipelined forward for stage-heterogeneous LMs (reference:
    ``pp_layers.py`` stage partition with embedding on stage 0, head on
    stage S-1, ``SharedLayerDesc`` tied weights).

    TPU-native stage heterogeneity: on GPU pipelines the embedding/head
    live on the first/last rank because weights are pinned to processes.
    Under SPMD there is no pinning — so ``pre`` (embedding) and ``post``
    (final norm + head) run as plain sharded compute over the WHOLE mesh
    (every chip's MXU works on the vocab matmul instead of 1/S of them),
    and only the homogeneous decoder-block run is scheduled through the
    ppermute pipeline. Tied embeddings need no ``allreduce_shared_weight``:
    reference (``pipeline_parallel.py`` shared-weight sync) — here the tied
    array simply appears in both ``pre`` and ``post`` closures and
    ``jax.grad`` sums the two contributions.

    ``pre``/``post``: single-microbatch callables ``x -> y`` (vmapped over
    the micro axis); ``block_fn(chunk_params, x)`` applies one pipeline
    chunk. ``micro_inputs``: [M, mb, ...].
    """
    h = micro_inputs
    if pre is not None:
        h = jax.vmap(pre)(h)
    h = pipeline_forward(block_fn, stacked_params, h, mesh=mesh,
                         axis_name=axis_name, vpp_degree=vpp_degree)
    if post is not None:
        h = jax.vmap(post)(h)
    return h


def pipeline_forward(stage_fn, stacked_params, micro_inputs, *, mesh=None,
                     axis_name="pp", n_stages=None, vpp_degree=1):
    """Pipelined forward over the global mesh's pp axis (differentiable,
    jit-compatible).

    ``stacked_params``: pytree, leaves stacked [S·vpp, ...] in chunk order
    (chunk = consecutive layer group). ``micro_inputs``: [M, mb, ...].
    ``vpp_degree`` > 1 selects the interleaved (VPP) schedule.
    """
    from . import mesh as mesh_mod
    mesh = mesh or mesh_mod.get_mesh()
    n_stages = n_stages or int(mesh.shape[axis_name])
    if n_stages == 1:
        def seq_all(x):
            n_chunks = jax.tree.leaves(stacked_params)[0].shape[0]
            for c in range(n_chunks):
                x = stage_fn(jax.tree.map(lambda a: a[c], stacked_params), x)
            return x
        return jax.vmap(seq_all)(micro_inputs)
    n_micro = int(micro_inputs.shape[0])
    if vpp_degree > 1:
        # chunk-major [c] → slot-major [(k, d) → d*v + k ... ]: device d's
        # slot k must hold chunk d + k·S, and P('pp') splits contiguously,
        # so global order becomes [d=0: chunks 0, S, 2S…; d=1: 1, S+1, …]
        order = jnp.asarray([d + k * n_stages
                             for d in range(n_stages)
                             for k in range(vpp_degree)])
        stacked_params = jax.tree.map(
            lambda a: jnp.take(a, order, axis=0), stacked_params)
        run = pipeline_spmd_interleaved(stage_fn, n_stages, n_micro,
                                        vpp_degree, axis_name)
    else:
        run = pipeline_spmd(stage_fn, n_stages, n_micro, axis_name)
    p_specs = jax.tree.map(lambda a: P(axis_name), stacked_params)
    mapped = jax.shard_map(
        run, mesh=mesh, in_specs=(p_specs, P()), out_specs=P(),
        axis_names={axis_name}, check_vma=False)
    # axes outside axis_name stay in "auto" sharding mode, which shard_map
    # only supports under jit — so compile here; callers' outer jit still
    # fuses through (nested jit is inlined)
    return jax.jit(mapped)(stacked_params, micro_inputs)
