"""paddle.distributed — TPU-native distributed stack (SURVEY.md §2.3, §7).

Perf path: named global mesh [dp, pp, sharding, sep, mp] + sharding
annotations; XLA emits collectives over ICI/DCN (mesh.py, fleet/).
Compat path: imperative per-rank collectives (collective.py) over the thread
simulator or the multi-host coordinator.
"""
from __future__ import annotations

from .parallel_env import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, is_initialized, ParallelEnv,
)
from .collective import (  # noqa: F401
    ReduceOp, Group, new_group, get_group, destroy_process_group,
    all_reduce, all_gather, all_gather_object, reduce_scatter,
    alltoall, alltoall_single, broadcast, broadcast_object_list,
    reduce, scatter, gather, scatter_object_list, barrier,
    send, recv, isend, irecv, P2POp, batch_isend_irecv, stream,
)
from .parallel import DataParallel, shard_tensor_on_axis  # noqa: F401
from .spawn import spawn  # noqa: F401
from . import mesh  # noqa: F401
from .mesh import init_mesh, get_mesh, HYBRID_AXES  # noqa: F401
from . import simulator  # noqa: F401
from .simulator import RankFailure, SimulatedRankKill  # noqa: F401
from . import fault  # noqa: F401  (deterministic fault injection)
from .native import TCPStore  # noqa: F401  (C++ rendezvous store)

# fleet namespace (hybrid parallelism facade)
from . import fleet  # noqa: F401

# sharded/async checkpoint (paddle.distributed.checkpoint)
from . import checkpoint  # noqa: F401
from .checkpoint import save_state_dict, load_state_dict  # noqa: F401

# pass registry (paddle.distributed.passes)
from . import passes  # noqa: F401

# semi-auto parallelism (paddle.distributed.auto_parallel + top-level API)
from . import auto_parallel  # noqa: F401
from .auto_parallel import (  # noqa: F401
    ProcessMesh, Shard, Replicate, Partial, shard_tensor, dtensor_from_fn,
    shard_optimizer,
)

# communication subpackage alias (paddle.distributed.communication.*)
from . import collective as communication  # noqa: F401

# bucketed + quantized gradient communication layer (EQuARX-style)
from . import comm  # noqa: F401
from .comm import (  # noqa: F401
    GradientBucketer, all_reduce_quantized, reduce_scatter_quantized,
    get_comm_stats, reset_comm_stats,
)


def get_backend():
    return "xla"


def is_available():
    return True
