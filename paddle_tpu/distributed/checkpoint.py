"""Distributed (sharded) checkpoint — ``paddle.distributed.checkpoint``
(reference: ``save_state_dict``/``load_state_dict`` for auto-parallel dist
tensors with metadata files + re-shard-on-load across different meshes;
``save_group_sharded_model`` gathers stage-3 shards; SURVEY.md §5.4).

TPU-native design: a ``jax.Array``'s shards map 1:1 to the reference's
dist-tensor metadata. Each host writes only its *addressable* shards
(`.npy` per shard) plus one ``metadata.json`` describing global shape/dtype
and per-shard index slices — so saving is embarrassingly parallel across
hosts (Orbax's layout, hand-rolled to stay self-contained). Loading
assembles the requested tensors and ``device_put``s them to the *target*
sharding — which may differ from the save-time mesh (re-shard-on-load).
``async_save=True`` snapshots device→host off the critical path and writes
in a background thread (the reference has no in-core async writer; the TPU
build needs one to keep the train step running — SURVEY.md §7.1 M5).
"""
from __future__ import annotations

import json
import os
import threading

import numpy as np
import jax

from ..framework.core import Tensor

_SENTINEL_META = "metadata.json"


def _proc_index():
    try:
        return jax.process_index()
    except RuntimeError:
        return 0


def _proc_count():
    try:
        return jax.process_count()
    except RuntimeError:
        return 1


def _rank_meta_name(rank, save_id=None):
    if save_id is not None:
        return f"metadata.rank{rank}.{save_id}.json"
    return f"metadata.rank{rank}.json"


def _shard_filename(key, idx):
    """Filename derived from the *global slice tuple*, not a per-process
    counter — so two hosts holding different slices of the same tensor can
    never collide, and the same slice always maps to the same file
    (fix for round-1 ADVICE high finding: per-process enumerate index)."""
    safe = key.replace("/", "__")
    if not idx:
        return f"{safe}.full.npy"
    span = "_".join(f"{a}-{b}" for a, b in idx)
    return f"{safe}.s{span}.npy"


def _tensor_shards(arr):
    """Yield (index_slices, np_array) for addressable shards this process
    must write. Only ``replica_id == 0`` copies are written — exactly one
    process globally owns each slice, so replicated tensors are written
    once cluster-wide (not once per host)."""
    for s in arr.addressable_shards:
        if getattr(s, "replica_id", 0) != 0:
            continue
        idx = tuple((sl.start or 0, sl.stop if sl.stop is not None else dim)
                    for sl, dim in zip(s.index, arr.shape)) if s.index else ()
        yield idx, np.asarray(s.data)


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    async_save=False, save_id=None, **kw):
    """Save a (possibly sharded) state_dict to ``path`` (a directory).

    ``save_id``: optional token identifying THIS save (e.g. the global
    step). Strongly recommended for multi-host periodic saves into a
    reused directory — rank metadata files are then namespaced per save,
    so the coordinator can never merge a previous save's stale rank file.
    Without it, a best-effort mtime guard is used instead.

    Returns None, or an object with ``.wait()`` when ``async_save``.
    """
    os.makedirs(path, exist_ok=True)
    rank, nprocs = _proc_index(), _proc_count()
    # Staleness reference for the no-save_id metadata merge: the save-start
    # instant measured on the checkpoint FILESYSTEM's clock (a probe file's
    # mtime), so NFS/local clock skew cannot misclassify fresh rank files.
    t_start = None
    if rank == coordinator_rank and nprocs > 1 and save_id is None:
        probe = os.path.join(path, f".save_probe.{os.getpid()}")
        try:
            with open(probe, "w") as f:
                f.write("x")
            t_start = os.path.getmtime(probe)
            os.remove(probe)
        except OSError:
            t_start = None
    flat = _flatten(state_dict)
    meta = {"version": 1, "tensors": {}, "nonarray": {}}
    jobs = []
    for key, val in flat.items():
        if isinstance(val, Tensor):
            val = val._data
        if isinstance(val, jax.Array):
            entries = []
            for idx, npdata in _tensor_shards(val):
                fname = _shard_filename(key, idx)
                entries.append({"file": fname,
                                "index": [list(p) for p in idx]})
                jobs.append((os.path.join(path, fname), npdata))
            meta["tensors"][key] = {
                "shape": list(val.shape),
                "dtype": str(np.dtype(val.dtype)),
                "shards": entries,
            }
        elif isinstance(val, np.ndarray):
            # host-side arrays are identical on every rank: only the
            # coordinator writes (uncoordinated same-file writes on a
            # shared fs can tear)
            if rank == coordinator_rank:
                fname = _shard_filename(key, ())
                meta["tensors"][key] = {
                    "shape": list(val.shape), "dtype": str(val.dtype),
                    "shards": [{"file": fname, "index": []}]}
                jobs.append((os.path.join(path, fname), val))
        else:
            if rank == coordinator_rank:
                meta["nonarray"][key] = val

    def write_all():
        for fpath, data in jobs:
            np.save(fpath, data)
        # Every rank publishes its shard metadata (atomically: tmp +
        # os.replace, so the coordinator can never read a torn file); the
        # coordinator merges all rank files into the global metadata.json
        # (the reference gathers metadata to rank 0 the same way —
        # without this, shards written by other hosts are invisible at
        # load and _assemble zero-fills them).
        rank_file = os.path.join(path, _rank_meta_name(rank, save_id))
        tmp = rank_file + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, rank_file)
        if rank == coordinator_rank:
            merge_timeout = float(kw.get(
                "merge_timeout",
                os.environ.get("PADDLE_CKPT_MERGE_TIMEOUT", "120")))
            merged = _merge_rank_meta(path, nprocs, own=meta,
                                      timeout=merge_timeout,
                                      save_id=save_id, min_mtime=t_start)
            tmp = os.path.join(path, _SENTINEL_META) + f".tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(merged, f)
            os.replace(tmp, os.path.join(path, _SENTINEL_META))

    if not async_save:
        write_all()
        return None

    th = threading.Thread(target=write_all, daemon=True)
    th.start()

    class _Handle:
        def wait(self):
            th.join()

        def result(self):
            th.join()

    return _Handle()


def _merge_rank_meta(path, nprocs, own=None, timeout=120.0, poll=0.25,
                     save_id=None, min_mtime=None):
    """Union the per-rank metadata files into one global metadata dict.

    Waits (bounded) for all ``nprocs`` rank files to appear on the shared
    filesystem and parse cleanly; merges whatever is usable at timeout
    with a warning — a partial merge on a non-shared fs degrades to the
    round-1 behavior rather than failing the save. Rank files are written
    via os.replace so a visible file is never torn; a transient parse
    failure is retried until the deadline. Without a ``save_id``
    namespace, ``min_mtime`` (save start time, minus clock-skew slack)
    rejects stale rank files left by a previous save into the same dir.
    """
    import time as _time
    import warnings

    deadline = _time.monotonic() + timeout
    want = {r: _rank_meta_name(r, save_id) for r in range(nprocs)}
    metas = {}
    stale = {}      # rank -> path of a file that predates this save
    while True:
        for r, name in want.items():
            if r in metas:
                continue
            fpath = os.path.join(path, name)
            try:
                # min_mtime is measured on the same filesystem clock (a
                # probe file written at save start), so a small slack
                # covers mtime granularity, not clock skew
                if min_mtime is not None and save_id is None \
                        and os.path.getmtime(fpath) < min_mtime - 2.0:
                    # leftover from a previous save; keep polling for a
                    # rewrite and only fall back to it at deadline —
                    # merging an old file beats zero-filling its shards
                    stale[r] = fpath
                    continue
                with open(fpath) as f:
                    metas[r] = json.load(f)
            except (OSError, ValueError):
                continue        # absent or mid-write — retry until deadline
        if len(metas) == nprocs or _time.monotonic() >= deadline:
            break
        _time.sleep(poll)
    for r, fpath in stale.items():
        if r not in metas:
            try:
                with open(fpath) as f:
                    metas[r] = json.load(f)
                warnings.warn(f"dist checkpoint: using possibly-stale rank "
                              f"{r} metadata (mtime predates this save)")
            except (OSError, ValueError):
                pass
    if len(metas) < nprocs:
        warnings.warn(
            f"dist checkpoint: only {len(metas)}/{nprocs} rank metadata "
            f"files usable after {timeout}s; metadata.json will cover "
            f"those ranks only")
    metas = [metas[r] for r in sorted(metas)]
    if own is not None and own not in metas:
        metas.append(own)
    merged = {"version": 1, "tensors": {}, "nonarray": {}}
    for m in metas:
        merged["nonarray"].update(m.get("nonarray", {}))
        for key, entry in m.get("tensors", {}).items():
            tgt = merged["tensors"].setdefault(
                key, {"shape": entry["shape"], "dtype": entry["dtype"],
                      "shards": []})
            have = {s["file"] for s in tgt["shards"]}
            tgt["shards"].extend(s for s in entry["shards"]
                                 if s["file"] not in have)
    return merged


def _flatten(d, prefix=""):
    out = {}
    for k, v in d.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def _unflatten_into(template, flat_vals):
    for k, v in flat_vals.items():
        parts = k.split(".")
        cur = template
        ok = True
        for p in parts[:-1]:
            if isinstance(cur, dict) and p in cur:
                cur = cur[p]
            else:
                ok = False
                break
        if ok and isinstance(cur, dict):
            cur[parts[-1]] = v
    return template


def _assemble(entry, path):
    """Rebuild the global np array from shard files."""
    shape = tuple(entry["shape"])
    dtype = np.dtype(entry["dtype"])
    shards = entry["shards"]
    if len(shards) == 1 and not shards[0]["index"]:
        return np.load(os.path.join(path, shards[0]["file"])).astype(dtype)
    out = np.zeros(shape, dtype)
    for sh in shards:
        data = np.load(os.path.join(path, sh["file"]))
        if sh["index"]:
            sl = tuple(slice(a, b) for a, b in sh["index"])
            out[sl] = data
        else:
            out[...] = data
    return out


def load_state_dict(state_dict, path, process_group=None, **kw):
    """Fill ``state_dict``'s Tensors in place from a checkpoint dir.

    Re-shard-on-load: each tensor keeps its *current* sharding (or the one in
    ``kw['shardings'][key]``) — the assembled global value is device_put to
    that sharding, so loading across a different mesh/degree layout works.
    """
    with open(os.path.join(path, _SENTINEL_META)) as f:
        meta = json.load(f)
    shardings = kw.get("shardings") or {}
    flat = _flatten(state_dict)
    for key, tgt in flat.items():
        if key not in meta["tensors"]:
            continue
        val = _assemble(meta["tensors"][key], path)
        if isinstance(tgt, Tensor):
            sh = shardings.get(key)
            if sh is None and isinstance(tgt._data, jax.Array) \
                    and len(tgt._data.devices()) > 1:
                sh = tgt._data.sharding
            arr = jax.device_put(val, sh) if sh is not None else val
            tgt.set_value(arr)
        else:
            flat[key] = val
    _unflatten_into(state_dict, {k: v for k, v in flat.items()
                                 if not isinstance(v, Tensor)})
    for k, v in meta.get("nonarray", {}).items():
        _unflatten_into(state_dict, {k: v})
    return state_dict


def save(state_dict, path, **kw):
    return save_state_dict(state_dict, path, **kw)


def load(state_dict, path, **kw):
    return load_state_dict(state_dict, path, **kw)


# ---------------------------------------------------------------------------
# group-sharded (ZeRO/stage-3) save facade
# ---------------------------------------------------------------------------

def save_group_sharded_model(model, output, optimizer=None):
    """Reference ``paddle.distributed.sharding.save_group_sharded_model``:
    gather sharded params to full values and save with paddle.save format."""
    from ..framework import io as fio
    os.makedirs(output, exist_ok=True)
    sd = model.state_dict()
    gathered = {}
    for k, v in sd.items():
        if isinstance(v, Tensor) and isinstance(v._data, jax.Array) \
                and len(v._data.devices()) > 1:
            gathered[k] = Tensor(np.asarray(jax.device_get(v._data)))
        else:
            gathered[k] = v
    fio.save(gathered, os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        fio.save(optimizer.state_dict(),
                 os.path.join(output, "model.pdopt"))
