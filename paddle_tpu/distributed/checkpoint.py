"""Distributed (sharded) checkpoint — ``paddle.distributed.checkpoint``
(reference: ``save_state_dict``/``load_state_dict`` for auto-parallel dist
tensors with metadata files + re-shard-on-load across different meshes;
``save_group_sharded_model`` gathers stage-3 shards; SURVEY.md §5.4).

TPU-native design: a ``jax.Array``'s shards map 1:1 to the reference's
dist-tensor metadata. Each host writes only its *addressable* shards
(`.npy` per shard) plus one ``metadata.json`` describing global shape/dtype
and per-shard index slices — so saving is embarrassingly parallel across
hosts (Orbax's layout, hand-rolled to stay self-contained). Loading
assembles the requested tensors and ``device_put``s them to the *target*
sharding — which may differ from the save-time mesh (re-shard-on-load).
``async_save=True`` snapshots device→host off the critical path and writes
in a background thread (the reference has no in-core async writer; the TPU
build needs one to keep the train step running — SURVEY.md §7.1 M5).
"""
from __future__ import annotations

import json
import os
import threading

import numpy as np
import jax

from ..framework.core import Tensor

_SENTINEL_META = "metadata.json"


def _proc_index():
    try:
        return jax.process_index()
    except RuntimeError:
        return 0


def _shard_filename(key, idx):
    safe = key.replace("/", "__")
    return f"{safe}.shard{idx}.npy"


def _tensor_shards(arr):
    """Yield (shard_idx, index_slices, np_array) for addressable shards; a
    fully-replicated array yields one shard (process 0 writes it)."""
    shards = [s for s in arr.addressable_shards]
    seen = set()
    for s in shards:
        idx = tuple((sl.start or 0, sl.stop if sl.stop is not None else dim)
                    for sl, dim in zip(s.index, arr.shape)) if s.index else ()
        if idx in seen:
            continue          # replicated copy — write once
        seen.add(idx)
        yield idx, np.asarray(s.data)


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    async_save=False, **kw):
    """Save a (possibly sharded) state_dict to ``path`` (a directory).

    Returns None, or an object with ``.wait()`` when ``async_save``.
    """
    os.makedirs(path, exist_ok=True)
    flat = _flatten(state_dict)
    meta = {"version": 1, "tensors": {}, "nonarray": {}}
    jobs = []
    for key, val in flat.items():
        if isinstance(val, Tensor):
            val = val._data
        if isinstance(val, jax.Array):
            entries = []
            for i, (idx, npdata) in enumerate(_tensor_shards(val)):
                fname = _shard_filename(key, i)
                entries.append({"file": fname,
                                "index": [list(p) for p in idx]})
                jobs.append((os.path.join(path, fname), npdata))
            meta["tensors"][key] = {
                "shape": list(val.shape),
                "dtype": str(np.dtype(val.dtype)),
                "shards": entries,
            }
        elif isinstance(val, np.ndarray):
            fname = _shard_filename(key, 0)
            meta["tensors"][key] = {
                "shape": list(val.shape), "dtype": str(val.dtype),
                "shards": [{"file": fname, "index": []}]}
            jobs.append((os.path.join(path, fname), val))
        else:
            meta["nonarray"][key] = val

    def write_all():
        for fpath, data in jobs:
            np.save(fpath, data)
        if _proc_index() == coordinator_rank:
            with open(os.path.join(path, _SENTINEL_META), "w") as f:
                json.dump(meta, f)

    if not async_save:
        write_all()
        return None

    th = threading.Thread(target=write_all, daemon=True)
    th.start()

    class _Handle:
        def wait(self):
            th.join()

        def result(self):
            th.join()

    return _Handle()


def _flatten(d, prefix=""):
    out = {}
    for k, v in d.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def _unflatten_into(template, flat_vals):
    for k, v in flat_vals.items():
        parts = k.split(".")
        cur = template
        ok = True
        for p in parts[:-1]:
            if isinstance(cur, dict) and p in cur:
                cur = cur[p]
            else:
                ok = False
                break
        if ok and isinstance(cur, dict):
            cur[parts[-1]] = v
    return template


def _assemble(entry, path):
    """Rebuild the global np array from shard files."""
    shape = tuple(entry["shape"])
    dtype = np.dtype(entry["dtype"])
    shards = entry["shards"]
    if len(shards) == 1 and not shards[0]["index"]:
        return np.load(os.path.join(path, shards[0]["file"])).astype(dtype)
    out = np.zeros(shape, dtype)
    for sh in shards:
        data = np.load(os.path.join(path, sh["file"]))
        if sh["index"]:
            sl = tuple(slice(a, b) for a, b in sh["index"])
            out[sl] = data
        else:
            out[...] = data
    return out


def load_state_dict(state_dict, path, process_group=None, **kw):
    """Fill ``state_dict``'s Tensors in place from a checkpoint dir.

    Re-shard-on-load: each tensor keeps its *current* sharding (or the one in
    ``kw['shardings'][key]``) — the assembled global value is device_put to
    that sharding, so loading across a different mesh/degree layout works.
    """
    with open(os.path.join(path, _SENTINEL_META)) as f:
        meta = json.load(f)
    shardings = kw.get("shardings") or {}
    flat = _flatten(state_dict)
    for key, tgt in flat.items():
        if key not in meta["tensors"]:
            continue
        val = _assemble(meta["tensors"][key], path)
        if isinstance(tgt, Tensor):
            sh = shardings.get(key)
            if sh is None and isinstance(tgt._data, jax.Array) \
                    and len(tgt._data.devices()) > 1:
                sh = tgt._data.sharding
            arr = jax.device_put(val, sh) if sh is not None else val
            tgt.set_value(arr)
        else:
            flat[key] = val
    _unflatten_into(state_dict, {k: v for k, v in flat.items()
                                 if not isinstance(v, Tensor)})
    for k, v in meta.get("nonarray", {}).items():
        _unflatten_into(state_dict, {k: v})
    return state_dict


def save(state_dict, path, **kw):
    return save_state_dict(state_dict, path, **kw)


def load(state_dict, path, **kw):
    return load_state_dict(state_dict, path, **kw)


# ---------------------------------------------------------------------------
# group-sharded (ZeRO/stage-3) save facade
# ---------------------------------------------------------------------------

def save_group_sharded_model(model, output, optimizer=None):
    """Reference ``paddle.distributed.sharding.save_group_sharded_model``:
    gather sharded params to full values and save with paddle.save format."""
    from ..framework import io as fio
    os.makedirs(output, exist_ok=True)
    sd = model.state_dict()
    gathered = {}
    for k, v in sd.items():
        if isinstance(v, Tensor) and isinstance(v._data, jax.Array) \
                and len(v._data.devices()) > 1:
            gathered[k] = Tensor(np.asarray(jax.device_get(v._data)))
        else:
            gathered[k] = v
    fio.save(gathered, os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        fio.save(optimizer.state_dict(),
                 os.path.join(output, "model.pdopt"))
