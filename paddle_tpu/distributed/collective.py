"""Imperative collective API (reference: ``python/paddle/distributed/
communication/*`` — all_reduce/all_gather/reduce_scatter/alltoall/broadcast/
scatter/reduce/send/recv/barrier over ProcessGroupNCCL, SURVEY.md §2.3/§5.8).

TPU-native execution tiers (SURVEY.md §7.0 "NCCL ProcessGroups → compat
layer"):

1. **Inside jit / sharded arrays** — the perf path never calls these: XLA's
   SPMD partitioner emits collectives from sharding annotations; fleet layers
   use shardings, not this API.
2. **Thread simulator** (same-host per-rank tests, simulator.py): rendezvous
   exchange on numpy values — the analogue of the reference's multi-process
   single-host test mode.
3. **Multi-host eager** (one process per host): cross-process gather via the
   jax coordinator (``multihost_utils``-style), correctness path for the rare
   eager collective outside jit.
4. **World size 1**: identity semantics.

Paddle semantics preserved: collectives mutate ``tensor`` in place and return
a task object with ``.wait()``.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..profiler import flight_recorder as _flight
from . import simulator
from .parallel_env import get_rank, get_world_size


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


_REDUCERS = {
    ReduceOp.SUM: lambda vs: np.sum(vs, axis=0),
    ReduceOp.MAX: lambda vs: np.max(vs, axis=0),
    ReduceOp.MIN: lambda vs: np.min(vs, axis=0),
    ReduceOp.PROD: lambda vs: np.prod(vs, axis=0),
    ReduceOp.AVG: lambda vs: np.mean(vs, axis=0),
}


class Group:
    """A communication group ≡ a subset of ranks; when created by the fleet
    topology it is axis-aligned (``axis`` = the mesh axis it spans)."""

    _next_id = [0]

    def __init__(self, ranks=None, axis=None, name=None):
        world = get_world_size()
        self.ranks = list(ranks) if ranks is not None else list(range(world))
        self.axis = axis
        Group._next_id[0] += 1
        self.id = Group._next_id[0]
        self.name = name or f"group_{self.id}"

    @property
    def nranks(self):
        return len(self.ranks)

    @property
    def world_size(self):
        return len(self.ranks)

    @property
    def rank(self):
        """This rank's index within the group (-1 if not a member)."""
        return self.get_group_rank(get_rank())

    def get_group_rank(self, global_rank):
        try:
            return self.ranks.index(global_rank)
        except ValueError:
            return -1

    def is_member(self):
        return get_rank() in self.ranks

    @property
    def process_ids(self):
        return self.ranks

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks}, axis={self.axis})"


_default_group: Group | None = None


def _get_default_group() -> Group:
    w = simulator.active_world()
    if w is not None:
        # one default group per simulated world (stored on it — ids of dead
        # worlds get reused by the allocator, so no external cache)
        g = getattr(w, "_default_group", None)
        if g is None:
            g = w._default_group = Group(list(range(w.nprocs)))
        return g
    global _default_group
    if _default_group is None or _default_group.nranks != get_world_size():
        _default_group = Group(list(range(get_world_size())))
    return _default_group


def new_group(ranks=None, backend=None, timeout=None):
    return Group(ranks if ranks is not None else list(range(get_world_size())))


def get_group(gid=0):
    return _get_default_group()


def destroy_process_group(group=None):
    global _default_group
    _default_group = None


class _Task:
    def __init__(self, result=None):
        self._result = result

    def wait(self):
        return True

    def is_completed(self):
        return True


# ---------------------------------------------------------------------------
# core exchange
# ---------------------------------------------------------------------------


_SIM_WIRE = [None]   # (lat_seconds, bytes_per_second) | False when off


def _sim_wire_cost():
    """Optional simulated-wire fidelity for the thread-rank tier: the
    in-memory rendezvous is instantaneous, so comm/compute overlap has
    nothing to hide on a laptop — these knobs model a real interconnect's
    per-collective latency (``PADDLE_SIM_WIRE_LAT_US``) and bandwidth
    (``PADDLE_SIM_WIRE_GBPS``) as idle sleep after each exchange. Off by
    default (no behavior change); ``BENCH_MODEL=comm`` enables it for the
    overlapped-vs-barrier comparison (both variants pay the same cost)."""
    v = _SIM_WIRE[0]
    if v is None:
        import os
        lat = float(os.environ.get("PADDLE_SIM_WIRE_LAT_US", "0")) * 1e-6
        gbps = float(os.environ.get("PADDLE_SIM_WIRE_GBPS", "0"))
        v = _SIM_WIRE[0] = (lat, gbps * 2 ** 30) if (lat or gbps) else False
    return v


def _payload_nbytes(v):
    if isinstance(v, (bytes, bytearray)):
        return len(v)
    if hasattr(v, "nbytes"):
        return int(v.nbytes)
    if isinstance(v, (tuple, list)):
        return sum(_payload_nbytes(x) for x in v)
    return 0


def _exchange(kind: str, value, group: Group):
    """All ranks in ``group`` deposit ``value``; returns {group_rank: value}."""
    w = simulator.active_world()
    if w is not None:
        rank = simulator.current_rank()
        # group identity = its rank set (each rank constructs its own Group
        # object; ids differ but the ranks tuple is the collective's name)
        tag = w.next_tag(kind, tuple(group.ranks))
        got = w.rendezvous.exchange(tag, rank, value, tuple(group.ranks))
        wire = _sim_wire_cost()
        if wire:
            import time as _time
            lat, bps = wire
            recv = sum(_payload_nbytes(v) for r, v in got.items()
                       if r != rank)
            _time.sleep(lat + (recv / bps if bps else 0.0))
        return {group.get_group_rank(r): v for r, v in got.items()}
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        gathered = multihost_utils.process_allgather(value)
        return {group.get_group_rank(r): gathered[r]
                for r in group.ranks}
    return {0: value}


_DEV_MESH = [None]
_DEV_REDUCERS = {}


def _normalize_op(op):
    """Legacy integer enum -> ReduceOp name (reference core.ReduceOp)."""
    return _LEGACY_OPS.get(op, op) if isinstance(op, int) else op


def _dev_reducer(red, out_sharding):
    """Per-op jitted reducer, created once so repeat eager collectives hit
    the jit compile cache."""
    key = (red, out_sharding)
    if key not in _DEV_REDUCERS:
        fn = {ReduceOp.SUM: lambda a: a.sum(0),
              ReduceOp.MAX: lambda a: a.max(0),
              ReduceOp.MIN: lambda a: a.min(0),
              ReduceOp.PROD: lambda a: a.prod(0)}[red]
        _DEV_REDUCERS[key] = jax.jit(fn, out_shardings=out_sharding)
    return _DEV_REDUCERS[key]


def _device_reduce(value: np.ndarray, op, group: Group):
    """Device-collective tier for reduce ops when the group spans every
    process: each process feeds its value into a global [n_devices, ...]
    array (extra local devices hold the op's identity element) and ONE
    jitted reduction runs over ICI/Gloo — O(tensor) traffic instead of the
    gather tier's O(world × tensor) host round-trip. Returns the reduced
    np array, or None when this tier doesn't apply."""
    if jax.process_count() <= 1 or list(group.ranks) != list(
            range(get_world_size())):
        return None
    if op == ReduceOp.AVG:
        red, post = ReduceOp.SUM, 1.0 / jax.process_count()
    else:
        red, post = op, None
    if red not in (ReduceOp.SUM, ReduceOp.MAX, ReduceOp.MIN, ReduceOp.PROD):
        return None
    dt = np.dtype(value.dtype)
    if dt == np.bool_:
        return None                       # identity elements ill-defined
    if red == ReduceOp.SUM:
        ident = dt.type(0)
    elif red == ReduceOp.PROD:
        ident = dt.type(1)
    elif np.issubdtype(dt, np.integer):   # MAX/MIN int bounds, not ±inf
        info = np.iinfo(dt)
        ident = info.min if red == ReduceOp.MAX else info.max
    else:
        ident = -np.inf if red == ReduceOp.MAX else np.inf
    if _DEV_MESH[0] is None:
        from jax.sharding import Mesh
        _DEV_MESH[0] = Mesh(np.array(jax.devices()), ("p",))
    mesh = _DEV_MESH[0]
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_local = len(jax.local_devices())
    local = np.broadcast_to(np.asarray(ident, dt),
                            (n_local,) + value.shape).copy()
    local[0] = value
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("p")), local)
    out = _dev_reducer(red, NamedSharding(mesh, P()))(garr)
    res = np.asarray(out.addressable_data(0))
    if post is not None:                  # AVG: scale in float, cast back
        res = (res.astype(np.float64) * post).astype(dt)
    return res


_PROC_MESH = [None]


def _proc_mesh():
    """1-D mesh with exactly ONE device per process — the natural carrier
    for eager rank↔rank collectives (rank r's data lives on process r's
    first device; shardings over this mesh map 1:1 to ranks regardless of
    how many local devices each process drives)."""
    if _PROC_MESH[0] is None:
        from jax.sharding import Mesh
        by_proc = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, d)
        devs = [by_proc[p] for p in sorted(by_proc)]
        _PROC_MESH[0] = Mesh(np.array(devs), ("p",))
    return _PROC_MESH[0]


def _device_reduce_scatter(stacked: np.ndarray, op, group: Group):
    """Device-collective tier for reduce_scatter: the [nranks, ...] local
    contributions of every process form a global [world, nranks, ...]
    array on the per-process mesh; ONE jitted sum over the process axis
    with rank-sharded output makes XLA emit a real reduce-scatter over
    ICI/Gloo — O(tensor) traffic instead of the host-gather tier's
    O(world × tensor). Returns this rank's reduced slice, or None when
    the tier doesn't apply."""
    world = jax.process_count()
    if world <= 1 or list(group.ranks) != list(range(get_world_size())) \
            or stacked.shape[0] != world:
        return None
    op = _normalize_op(op)
    if op == ReduceOp.AVG:
        red, post = ReduceOp.SUM, 1.0 / world
    else:
        red, post = op, None
    fns = {ReduceOp.SUM: lambda a: a.sum(0),
           ReduceOp.MAX: lambda a: a.max(0),
           ReduceOp.MIN: lambda a: a.min(0),
           ReduceOp.PROD: lambda a: a.prod(0)}
    if red not in fns or np.dtype(stacked.dtype) == np.bool_:
        return None
    mesh = _proc_mesh()
    from jax.sharding import NamedSharding, PartitionSpec as P
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("p")), stacked[None])    # my [1, world, ...]
    key = ("rs", red)
    if key not in _DEV_REDUCERS:
        _DEV_REDUCERS[key] = jax.jit(
            fns[red], out_shardings=NamedSharding(mesh, P("p")))
    out = _DEV_REDUCERS[key](garr)
    res = np.asarray(out.addressable_data(0))[0]       # my rank's slice
    if post is not None:
        res = (res.astype(np.float64) * post).astype(stacked.dtype)
    return res


def _device_alltoall(stacked: np.ndarray, group: Group):
    """Device-collective tier for alltoall: global [world, nranks, ...]
    on the per-process mesh, ONE jitted swap of the process/rank axes
    with rank-sharded output — XLA emits a true all-to-all. Returns this
    rank's [nranks, ...] received block, or None when inapplicable."""
    world = jax.process_count()
    if world <= 1 or list(group.ranks) != list(range(get_world_size())) \
            or stacked.shape[0] != world:
        return None
    mesh = _proc_mesh()
    from jax.sharding import NamedSharding, PartitionSpec as P
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("p")), stacked[None])
    if "a2a" not in _DEV_REDUCERS:
        _DEV_REDUCERS["a2a"] = jax.jit(
            lambda a: jnp.swapaxes(a, 0, 1),
            out_shardings=NamedSharding(mesh, P("p")))
    out = _DEV_REDUCERS["a2a"](garr)
    return np.asarray(out.addressable_data(0))[0]      # my received block


def _np(tensor):
    return np.asarray(tensor.numpy() if isinstance(tensor, Tensor) else tensor)


def _record_comm(kind: str, nbytes: int, group: Group):
    """CommStats accounting for the dense collectives: one record per
    issuing rank, wire == logical (no compression on this path)."""
    if group.nranks <= 1:
        return
    from .comm.stats import get_comm_stats
    get_comm_stats().record(kind, logical_bytes=nbytes, wire_bytes=nbytes)


def _write_back(tensor: Tensor, arr):
    tensor._data = jnp.asarray(np.asarray(arr), dtype=tensor.dtype)
    return tensor


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------


# legacy integer enum values (reference core.ReduceOp): 0=SUM 1=MAX 2=MIN 3=PROD 4=AVG
_LEGACY_OPS = {0: ReduceOp.SUM, 1: ReduceOp.MAX, 2: ReduceOp.MIN,
               3: ReduceOp.PROD, 4: ReduceOp.AVG}


def _reduce_fn(op):
    op = _normalize_op(op)
    if op not in _REDUCERS:
        raise ValueError(f"unknown ReduceOp {op!r}")
    return _REDUCERS[op]


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
               use_calc_stream=False):
    group = group or _get_default_group()
    if group.nranks == 1:
        return _Task()
    arr = _np(tensor)
    _record_comm("all_reduce", arr.nbytes, group)
    ev = _flight.collective_begin("all_reduce", arr.nbytes, group.ranks)
    try:
        if simulator.active_world() is None:
            dev = _device_reduce(arr, _normalize_op(op), group)
            if dev is not None:
                _write_back(tensor, dev)
                return _Task()
        got = _exchange("all_reduce", arr, group)
        vals = [got[i] for i in range(group.nranks)]
        _write_back(tensor, _reduce_fn(op)(vals))
        return _Task()
    finally:
        _flight.collective_end(ev)


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    group = group or _get_default_group()
    if group.nranks == 1:
        tensor_list.append(Tensor(tensor._data) if isinstance(tensor, Tensor) else Tensor(tensor))
        return _Task()
    arr = _np(tensor)
    _record_comm("all_gather", arr.nbytes, group)
    ev = _flight.collective_begin("all_gather", arr.nbytes, group.ranks)
    try:
        got = _exchange("all_gather", arr, group)
        for i in range(group.nranks):
            tensor_list.append(Tensor(jnp.asarray(got[i])))
        return _Task()
    finally:
        _flight.collective_end(ev)


def all_gather_object(object_list, obj, group=None):
    group = group or _get_default_group()
    if group.nranks == 1:
        object_list.append(obj)
        return
    ev = _flight.collective_begin("all_gather_object", 0, group.ranks)
    try:
        got = _exchange("all_gather_object", obj, group)
        for i in range(group.nranks):
            object_list.append(got[i])
    finally:
        _flight.collective_end(ev)


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None, sync_op=True):
    group = group or _get_default_group()
    if group.nranks == 1:
        src = tensor_list[0]
        _write_back(tensor, _np(src))
        return _Task()
    stacked = np.stack([_np(t) for t in tensor_list])  # [nranks, ...] local inputs
    _record_comm("reduce_scatter", stacked.nbytes, group)
    ev = _flight.collective_begin("reduce_scatter", stacked.nbytes,
                                  group.ranks)
    try:
        mine = group.rank
        if simulator.active_world() is None:
            dev = _device_reduce_scatter(stacked, op, group)
            if dev is not None:
                _write_back(tensor, dev)
                return _Task()
            dev = _device_reduce(stacked, _normalize_op(op), group)
            if dev is not None:
                _write_back(tensor, dev[mine])
                return _Task()
        got = _exchange("reduce_scatter", stacked, group)
        all_stacked = [got[i] for i in range(group.nranks)]  # per-rank [nranks, ...]
        reduced = _reduce_fn(op)([s[mine] for s in all_stacked])
        _write_back(tensor, reduced)
        return _Task()
    finally:
        _flight.collective_end(ev)


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    group = group or _get_default_group()
    if group.nranks == 1:
        out_tensor_list.extend(Tensor(t._data) for t in in_tensor_list)
        return _Task()
    stacked = np.stack([_np(t) for t in in_tensor_list])
    ev = _flight.collective_begin("alltoall", stacked.nbytes, group.ranks)
    try:
        if simulator.active_world() is None:
            dev = _device_alltoall(stacked, group)
            if dev is not None:
                for i in range(group.nranks):
                    out_tensor_list.append(Tensor(jnp.asarray(dev[i])))
                return _Task()
        got = _exchange("alltoall", stacked, group)
        mine = group.rank
        for i in range(group.nranks):
            out_tensor_list.append(Tensor(jnp.asarray(got[i][mine])))
        return _Task()
    finally:
        _flight.collective_end(ev)


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    group = group or _get_default_group()
    n = group.nranks
    if n == 1:
        _write_back(out_tensor, _np(in_tensor))
        return _Task()
    arr = _np(in_tensor)
    ev = _flight.collective_begin("alltoall_single", arr.nbytes, group.ranks)
    try:
        splits = in_split_sizes or [arr.shape[0] // n] * n
        offs = np.cumsum([0] + list(splits))
        chunks = [arr[offs[i]:offs[i + 1]] for i in range(n)]
        got = _exchange("alltoall_single", chunks, group)
        mine = group.rank
        out = np.concatenate([got[i][mine] for i in range(n)], axis=0)
        _write_back(out_tensor, out)
        return _Task()
    finally:
        _flight.collective_end(ev)


def broadcast(tensor, src, group=None, sync_op=True):
    group = group or _get_default_group()
    if group.nranks == 1:
        return _Task()
    arr = _np(tensor)
    ev = _flight.collective_begin("broadcast", arr.nbytes, group.ranks)
    try:
        got = _exchange("broadcast", arr, group)
        src_group_rank = group.get_group_rank(src) if src in group.ranks \
            else src
        _write_back(tensor, got[src_group_rank])
        return _Task()
    finally:
        _flight.collective_end(ev)


def broadcast_object_list(object_list, src, group=None):
    group = group or _get_default_group()
    if group.nranks == 1:
        return
    ev = _flight.collective_begin("broadcast_object_list", 0, group.ranks)
    try:
        got = _exchange("broadcast_object_list", list(object_list), group)
        src_group_rank = group.get_group_rank(src) if src in group.ranks \
            else src
        object_list[:] = got[src_group_rank]
    finally:
        _flight.collective_end(ev)


def reduce(tensor, dst, op=ReduceOp.SUM, group=None, sync_op=True):
    group = group or _get_default_group()
    if group.nranks == 1:
        return _Task()
    arr = _np(tensor)
    ev = _flight.collective_begin("reduce", arr.nbytes, group.ranks)
    try:
        got = _exchange("reduce", arr, group)
        if get_rank() == dst:
            vals = [got[i] for i in range(group.nranks)]
            _write_back(tensor, _reduce_fn(op)(vals))
        return _Task()
    finally:
        _flight.collective_end(ev)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    group = group or _get_default_group()
    if group.nranks == 1:
        if tensor_list:
            _write_back(tensor, _np(tensor_list[0]))
        return _Task()
    payload = [_np(t) for t in tensor_list] if tensor_list else None
    nbytes = sum(a.nbytes for a in payload) if payload else 0
    ev = _flight.collective_begin("scatter", nbytes, group.ranks)
    try:
        got = _exchange("scatter", payload, group)
        src_group_rank = group.get_group_rank(src) if src in group.ranks \
            else src
        chunks = got[src_group_rank]
        _write_back(tensor, chunks[group.rank])
        return _Task()
    finally:
        _flight.collective_end(ev)


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """Gather tensors from every rank to ``dst`` (reference:
    ``paddle.distributed.gather``); non-dst ranks receive nothing."""
    group = group or _get_default_group()
    if group.nranks == 1:
        if gather_list is not None:
            gather_list.append(Tensor(_np(tensor)))
        return _Task()
    arr = _np(tensor)
    ev = _flight.collective_begin("gather", arr.nbytes, group.ranks)
    try:
        got = _exchange("gather", arr, group)
        dst_group_rank = group.get_group_rank(dst) if dst in group.ranks \
            else dst
        if group.rank == dst_group_rank and gather_list is not None:
            for i in range(group.nranks):
                gather_list.append(Tensor(jnp.asarray(got[i])))
        return _Task()
    finally:
        _flight.collective_end(ev)


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """Scatter a list of picklable objects from ``src`` (reference:
    ``paddle.distributed.scatter_object_list``)."""
    group = group or _get_default_group()
    if group.nranks == 1:
        out_object_list.append(in_object_list[0])
        return
    ev = _flight.collective_begin("scatter_object_list", 0, group.ranks)
    try:
        got = _exchange("scatter_object_list", in_object_list, group)
        src_group_rank = group.get_group_rank(src) if src in group.ranks \
            else src
        out_object_list.append(got[src_group_rank][group.rank])
    finally:
        _flight.collective_end(ev)


def barrier(group=None):
    group = group or _get_default_group()
    if group.nranks == 1:
        return
    ev = _flight.collective_begin("barrier", 0, group.ranks)
    try:
        _exchange("barrier", None, group)
    finally:
        _flight.collective_end(ev)


# ---------------------------------------------------------------------------
# point-to-point
# ---------------------------------------------------------------------------

# Cross-process p2p rides the C++ TCPStore (native/tcp_store.cpp) — the
# reference's ProcessGroup send/recv contract (SURVEY.md §2.3) served by
# the same rendezvous KV the launch/elastic stack uses. Rank 0 hosts the
# store server; message keys are (src, dst, seq) with per-direction
# sequence counters on both ends, so ordered matched pairs never collide.
_P2P_STORE = [None]
_P2P_SEQ: dict = {}


def _p2p_store():
    if _P2P_STORE[0] is not None:
        return _P2P_STORE[0]
    import os
    from .native import TCPStore
    rank, world = get_rank(), get_world_size()
    host, port = "127.0.0.1", 0
    ep = os.environ.get("PADDLE_MASTER") or \
        (os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",") or [""])[0]
    if ":" in ep:
        host, p = ep.rsplit(":", 1)
        port = int(p) + 17            # offset: base port holds the jax
        #                               coordinator / launch rendezvous
    port = int(os.environ.get("PADDLE_P2P_PORT", port))
    if not port:
        raise RuntimeError(
            "cross-process send/recv needs a rendezvous endpoint: launch "
            "via paddle_tpu.distributed.launch (sets PADDLE_MASTER) or set "
            "PADDLE_P2P_PORT")
    _P2P_STORE[0] = TCPStore(host=host, port=port, is_master=(rank == 0),
                             world_size=world)
    # Elastic hygiene: _P2P_SEQ is process-local but messages persist in
    # the rank-0 store, so a restarted worker pair (seq reset to 0) could
    # consume a payload a previous incarnation deposited. Purge only keys
    # this rank SENT: they are all from its previous life (the purge runs
    # before any send in this life), so nothing live can be deleted —
    # purging keys merely *addressed* to this rank could race a peer's
    # legitimate first send on a fresh job.
    try:
        me = str(rank)
        for key in _P2P_STORE[0].keys("p2p/"):
            parts = key.split("/")
            if len(parts) == 4 and parts[2].split(">", 1)[0] == me:
                _P2P_STORE[0].delete_key(key)
    except Exception:
        pass  # best-effort; a fresh job has nothing to purge
    return _P2P_STORE[0]


def _p2p_pack(arr: np.ndarray) -> bytes:
    import io
    buf = io.BytesIO()
    np.save(buf, arr, allow_pickle=False)
    return buf.getvalue()


def _p2p_unpack(raw: bytes) -> np.ndarray:
    import io
    return np.load(io.BytesIO(raw), allow_pickle=False)


def _gid(group: Group) -> str:
    """Stable group identity for p2p keys — the rank set, not the
    per-process Group id (ids differ across ranks). Keeps concurrent
    p2p in two subgroups between the same rank pair from crossing
    payloads (the simulator path keys the same way)."""
    return "-".join(map(str, group.ranks))


def send(tensor, dst=0, group=None, sync_op=True):
    w = simulator.active_world()
    group = group or _get_default_group()
    arr = _np(tensor)
    ev = _flight.collective_begin("send", arr.nbytes, group.ranks)
    try:
        if w is not None:
            gkey = tuple(group.ranks)  # group identity = rank set (ids differ per rank)
            seq = w.next_tag("p2p_send",
                             (gkey, simulator.current_rank(), dst))[2]
            w.rendezvous.put((gkey, simulator.current_rank(), dst, seq), arr)
            return _Task()
        if get_world_size() <= 1:
            raise RuntimeError("send/recv needs a multi-process launch or "
                               "the thread simulator")
        store = _p2p_store()
        me, gid = get_rank(), _gid(group)
        k = ("s", gid, me, dst)
        seq = _P2P_SEQ[k] = _P2P_SEQ.get(k, -1) + 1
        store.set(f"p2p/{gid}/{me}>{dst}/{seq}", _p2p_pack(arr))
        return _Task()
    finally:
        _flight.collective_end(ev)


def recv(tensor, src=0, group=None, sync_op=True):
    w = simulator.active_world()
    group = group or _get_default_group()
    ev = _flight.collective_begin("recv", _np(tensor).nbytes, group.ranks)
    try:
        if w is not None:
            gkey = tuple(group.ranks)
            seq = w.next_tag("p2p_recv",
                             (gkey, src, simulator.current_rank()))[2]
            val = w.rendezvous.get((gkey, src, simulator.current_rank(), seq))
            _write_back(tensor, val)
            return _Task()
        if get_world_size() <= 1:
            raise RuntimeError("send/recv needs a multi-process launch or "
                               "the thread simulator")
        store = _p2p_store()
        me, gid = get_rank(), _gid(group)
        k = ("r", gid, src, me)
        seq = _P2P_SEQ[k] = _P2P_SEQ.get(k, -1) + 1
        key = f"p2p/{gid}/{src}>{me}/{seq}"
        val = _p2p_unpack(store.get(key, wait=True))
        store.delete_key(key)
        _write_back(tensor, val)
        return _Task()
    finally:
        _flight.collective_end(ev)


isend = send
irecv = recv


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    """Reference: ``ProcessGroupNCCL::batch_isend_irecv`` — here sends are
    deposited first, then recvs drained, so matched pairs can't deadlock."""
    tasks = []
    for p in p2p_op_list:
        if p.op in (send, isend):
            tasks.append(send(p.tensor, p.peer, p.group))
    for p in p2p_op_list:
        if p.op in (recv, irecv):
            tasks.append(recv(p.tensor, p.peer, p.group))
    return tasks


# low-level "stream" namespace compat (paddle.distributed.stream.*)
class stream:
    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    reduce_scatter = staticmethod(reduce_scatter)
    alltoall = staticmethod(alltoall)
    broadcast = staticmethod(broadcast)
    reduce = staticmethod(reduce)
    scatter = staticmethod(scatter)
    send = staticmethod(send)
    recv = staticmethod(recv)
