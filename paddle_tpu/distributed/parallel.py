"""DataParallel (reference: ``python/paddle/distributed/parallel.py`` +
the C++ reducer ``paddle/fluid/imperative/reducer.cc`` — grad bucketing with
allreduce overlapped in backward, ``no_sync``, SURVEY.md §2.3 "DP").

TPU-native: two execution modes.

* **Mesh mode** (single-controller SPMD, the perf path): parameters stay
  replicated over the global mesh; ``forward`` shards batch inputs on the dp
  axis. Every eager op then runs data-parallel under GSPMD, and gradient
  reduction is inserted by XLA — no reducer, no buckets, no explicit
  allreduce (why: grads of replicated params w.r.t. dp-sharded activations
  are psum'd by the partitioner automatically; bucketing exists in the
  reference only to amortise NCCL launch overhead, which has no analogue
  here).
* **Simulated/multi-process per-rank mode**: classic Paddle semantics — a
  post-backward callback averages each parameter's grad over the dp group
  (the reducer flush), disabled inside ``no_sync()``.
"""
from __future__ import annotations

import contextlib

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ..framework.core import Tensor
from ..nn.layer import Layer
from ..autograd import tape
from . import simulator
from . import mesh as mesh_mod
from . import collective
from .parallel_env import init_parallel_env, get_rank, get_world_size  # noqa: F401


def shard_tensor_on_axis(t: Tensor, axis: str, dim: int = 0) -> Tensor:
    """Reshard a tensor over a mesh axis along ``dim`` (mesh mode)."""
    mesh = mesh_mod.get_mesh()
    if axis not in mesh.shape or mesh.shape[axis] == 1:
        return t
    spec = [None] * t.ndim
    spec[dim] = axis
    t._data = jax.device_put(t._data, NamedSharding(mesh, PartitionSpec(*spec)))
    return t


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self._grad_sync_enabled = True
        self.group = group
        self.find_unused_parameters = find_unused_parameters
        self._sim_mode = simulator.in_simulation() or jax.process_count() > 1
        self._overlap_scheduler = None
        self._strategy = strategy
        if self._sim_mode:
            if self.group is None:
                self.group = collective._get_default_group()
            # weak self-ref: a discarded DataParallel must not keep syncing
            # (or keep alive) its model from the thread's callback list
            import weakref
            ref = weakref.ref(self)

            def _cb():
                dp = ref()
                if dp is None:
                    tape.unregister_post_backward_callback(_cb)
                    return
                dp._post_backward()

            self._cb = tape.register_post_backward_callback(_cb)

            def _ready(t):
                dp = ref()
                if dp is None:
                    tape.unregister_grad_ready_callback(_ready)
                    return
                dp._on_grad_ready(t)

            self._ready_cb = tape.register_grad_ready_callback(_ready)
        else:
            # mesh mode: ensure params are replicated over the mesh so that
            # dp-sharded activations trigger GSPMD grad reduction
            if mesh_mod.has_mesh() and len(mesh_mod.get_mesh().devices.flat) > 1:
                repl = mesh_mod.replicated()
                with tape.no_grad():
                    for p in layers.parameters():
                        if p is not None and not isinstance(p._data, jax.core.Tracer):
                            if getattr(p, "_sharding_spec", None) is None:
                                p._data = jax.device_put(p._data, repl)

    def forward(self, *inputs, **kwargs):
        if not self._sim_mode and mesh_mod.has_mesh():
            inputs = tuple(
                shard_tensor_on_axis(x, "dp", 0) if isinstance(x, Tensor) and x.ndim > 0
                else x
                for x in inputs)
        return self._layers(*inputs, **kwargs)

    # -- per-rank grad sync (simulated / multi-process) ----------------------
    def _dp_strategy(self):
        if self._strategy is not None:
            return self._strategy
        from . import fleet
        return fleet.get_strategy()

    def _on_grad_ready(self, t):
        """Tape grad-ready hook: route the just-finalized gradient into the
        ready-bucket scheduler so its bucket's collective can dispatch
        while backward still runs (the reference reducer's per-variable
        hook → ``MarkVarReady`` path)."""
        if not self._grad_sync_enabled or not self._sim_mode:
            return
        sched = self._overlap_scheduler
        if sched is False:       # overlap disabled — latched once per model
            return
        if sched is None:
            strategy = self._dp_strategy()
            if not getattr(strategy, "comm_overlap", True):
                self._overlap_scheduler = False
                return
            params = [p for p in self._layers.parameters()
                      if p is not None and p.trainable]
            if not params:
                return
            from .comm import GradientBucketer, ReadyBucketScheduler
            sched = self._overlap_scheduler = ReadyBucketScheduler(
                GradientBucketer.from_strategy(params, strategy),
                name="dp", group=self.group, op=collective.ReduceOp.AVG)
        sched.mark_ready(t)

    def _post_backward(self):
        """The reducer flush: consume the overlap round when one is live
        (wait on in-flight buckets, dispatch leftovers), else run the
        legacy barrier exchange."""
        if not self._grad_sync_enabled or not self._sim_mode:
            return
        sched = self._overlap_scheduler
        if sched is not None and sched is not False:
            params = [p for p in self._layers.parameters()
                      if p is not None and p.trainable]
            if sched.matches(params):
                sched.finish()
                return
            # parameter set changed under the scheduler — rebuild next
            # backward; this one syncs barrier-style for full coverage
            sched.close()
            self._overlap_scheduler = None
        self._sync_gradients()

    def _sync_gradients(self):
        """The reducer flush: bucketed (and, per the fleet strategy's
        ``comm_quantization`` knob, quantized) gradient exchange through
        ``distributed.comm`` — one collective per fusion bucket instead of
        one per tensor (reference ``reducer.cc`` grad buckets)."""
        if not self._grad_sync_enabled or not self._sim_mode:
            return
        params = [p for p in self._layers.parameters()
                  if p is not None and p.trainable]
        if not any(p.grad is not None for p in params):
            return
        from .comm import GradientBucketer
        b = getattr(self, "_comm_bucketer", None)
        if b is None or [id(p) for p in b._params] != [id(p) for p in params]:
            b = self._comm_bucketer = GradientBucketer.from_strategy(
                params, self._dp_strategy())
        b.sync_grads(group=self.group, op=collective.ReduceOp.AVG)

    def shutdown(self):
        """Retire this wrapper explicitly (elastic shrink/regrow rebuild):
        unregister the thread-local tape callbacks and close the overlap
        scheduler's worker lanes. Without this, an abandoned generation's
        post-backward callback would flush stale buckets over the OLD
        group (which may contain a dead rank) into the new world's
        backward."""
        cb = getattr(self, "_cb", None)
        if cb is not None:
            tape.unregister_post_backward_callback(cb)
            self._cb = None
        rcb = getattr(self, "_ready_cb", None)
        if rcb is not None:
            tape.unregister_grad_ready_callback(rcb)
            self._ready_cb = None
        sched = self._overlap_scheduler
        if sched is not None and sched is not False:
            sched.close()
        self._overlap_scheduler = False
        self._grad_sync_enabled = False

    @contextlib.contextmanager
    def no_sync(self):
        """Skip grad sync inside (grad accumulation); reference ``no_sync``."""
        prev = self._grad_sync_enabled
        self._grad_sync_enabled = False
        try:
            yield
        finally:
            self._grad_sync_enabled = prev

    # -- delegation ----------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def train(self):
        self._layers.train()
        self.training = True
        return self

    def eval(self):
        self._layers.eval()
        self.training = False
        return self

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        self._sync_gradients()
