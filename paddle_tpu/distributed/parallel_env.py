"""Process/topology bootstrap (reference: ``python/paddle/distributed/parallel.py``
``init_parallel_env`` + ``ParallelEnv``, env vars ``PADDLE_TRAINER_ID`` /
``PADDLE_TRAINERS_NUM`` / ``PADDLE_TRAINER_ENDPOINTS`` set by launch —
SURVEY.md §2.3 "Env/topology bootstrap").

TPU-native: rendezvous is ``jax.distributed.initialize`` (coordinator service)
instead of TCPStore; one process per *host* (TPU convention), not per chip.
The same PADDLE_* env names are honoured as a compat shim. Under the thread
simulator (simulator.py), rank/world come from the simulated context.
"""
from __future__ import annotations

import os

import jax

from . import simulator
from . import mesh as mesh_mod

_initialized = [False]


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def init_parallel_env():
    """Initialize the distributed context. Safe to call more than once.

    Multi-host: if PADDLE_TRAINERS_NUM > 1 (or JAX coordinator env present),
    calls ``jax.distributed.initialize`` using PADDLE_* env as the compat
    source; then installs the default global mesh.
    """
    if _initialized[0] or simulator.in_simulation():
        return ParallelEnv()
    nranks = _env_int("PADDLE_TRAINERS_NUM", 1)
    if nranks > 1 and not jax._src.distributed.global_state.client:  # noqa: SLF001
        rank = _env_int("PADDLE_TRAINER_ID", 0)
        endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        coordinator = endpoints.split(",")[0] if endpoints else None
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=nranks,
            process_id=rank,
        )
    if not mesh_mod.has_mesh():
        mesh_mod.init_mesh()
    _initialized[0] = True
    if nranks > 1 and _env_int("PADDLE_TRAINER_ID", 0) == 0:
        # host the p2p rendezvous store NOW: lazy creation at rank 0's
        # first send/recv would deadlock jobs where only non-zero ranks
        # exchange p2p (they'd wait on a server nobody starts)
        try:
            from . import collective
            collective._p2p_store()
        except Exception as e:     # best-effort: p2p then errors at use
            import sys
            print(f"init_parallel_env: p2p store not hosted ({e})",
                  file=sys.stderr)
    return ParallelEnv()


def get_rank(group=None) -> int:
    r = simulator.current_rank()
    if r is not None:
        if group is not None:
            return group.get_group_rank(r)
        return r
    if group is not None:
        return group.get_group_rank(jax.process_index())
    return jax.process_index()


def get_world_size(group=None) -> int:
    w = simulator.active_world()
    if w is not None:
        return group.nranks if group is not None else w.nprocs
    if group is not None:
        return group.nranks
    return jax.process_count()


def is_initialized() -> bool:
    return _initialized[0] or simulator.in_simulation()


class ParallelEnv:
    """paddle.distributed.ParallelEnv — rank/world/device view."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def nranks(self):
        return get_world_size()

    @property
    def local_rank(self):
        return get_rank()

    @property
    def device_id(self):
        return _env_int("FLAGS_selected_tpus", 0)

    @property
    def dev_id(self):
        return self.device_id

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else [self.current_endpoint]
