"""Program-level IR inspection + pass/rewrite infrastructure (reference:
PIR — ``paddle/fluid/pir/`` Program/pattern-rewriter and the inference
``analysis`` fusion passes; SURVEY.md §2.1 "PIR", "Inference engine").

TPU-native design: the lowered program IS StableHLO (SURVEY §7.0), so the
pass infrastructure operates on the real MLIR module through jaxlib's IR
bindings rather than on a re-invented graph format:

* :class:`ProgramIR` wraps a lowered/exported program — walk it, take an
  op histogram, match ops, rewrite, and round-trip back to an executable
  ``jax.export.Exported`` (versioned portable artifact).
* :class:`MLIRPipelinePass` runs real MLIR passes (``canonicalize``,
  ``cse``, …) through ``jaxlib.mlir.passmanager`` — the analogue of the
  reference's DCE/constant-fold/CSE program passes.
* :class:`PatternRewritePass` is the Python-level pattern rewriter: match
  by op name + predicate, mutate through a callback (the
  ``PatternRewritePass``/``drr`` analogue for cases XLA doesn't already
  cover).
* :data:`registry` mirrors the reference's pass registry; the inference
  ``Config.switch_ir_optim`` knob runs the default pipeline on the loaded
  program before execution.

Most of the reference's fusion pass zoo is absorbed by XLA (it fuses
elementwise chains into matmuls at compile time) — these passes exist for
the residue: program surgery, artifact slimming, inspection, and custom
rewrites ahead of XLA.
"""
from __future__ import annotations

import dataclasses

__all__ = ["ProgramIR", "Pass", "MLIRPipelinePass", "PatternRewritePass",
           "PassRegistry", "registry", "optimize_exported"]


def _ir():
    from jax._src.interpreters import mlir as jmlir
    from jaxlib.mlir import ir
    return jmlir, ir


class ProgramIR:
    """A lowered program as a live MLIR module.

    Build from an ``Exported`` (``ProgramIR.from_exported``), a lowered
    jit (``ProgramIR.from_lowered(jax.jit(f).lower(...))``), or StableHLO
    text. ``to_exported()`` re-serializes into the original Exported's
    calling convention (a versioned portable artifact — the edited
    program executes anywhere the original did)."""

    def __init__(self, module, context, exported=None):
        self._module = module
        self._ctx = context
        self._exported = exported

    # -- constructors -------------------------------------------------------
    @classmethod
    def parse(cls, text, exported=None):
        jmlir, ir = _ir()
        ctx = jmlir.make_ir_context()
        return cls(ir.Module.parse(text, context=ctx), ctx, exported)

    @classmethod
    def from_exported(cls, exported):
        return cls.parse(exported.mlir_module(), exported)

    @classmethod
    def from_lowered(cls, lowered):
        return cls.parse(lowered.as_text())

    # -- inspection ---------------------------------------------------------
    @property
    def text(self) -> str:
        return str(self._module)

    def walk(self, fn):
        """Call ``fn(op)`` for every operation, outermost first."""

        def go(op):
            fn(op)
            for region in op.regions:
                for block in region.blocks:
                    for child in block.operations:
                        go(child.operation)

        go(self._module.operation)

    def ops(self, name=None):
        """All operations, or those whose op name matches ``name``."""
        out = []
        self.walk(lambda op: out.append(op)
                  if name is None or op.name == name else None)
        return out

    def op_histogram(self) -> dict:
        """{op name: count} over the whole program — the quick 'what did
        my model lower to' inspection the reference offers via IR print."""
        hist: dict = {}

        def count(op):
            hist[op.name] = hist.get(op.name, 0) + 1

        self.walk(count)
        return hist

    # -- rewrite ------------------------------------------------------------
    def apply(self, passes) -> bool:
        """Run passes (names from the registry, or Pass instances).
        Returns True if any pass reported a change."""
        changed = False
        for p in passes:
            if isinstance(p, str):
                p = registry.get(p)
            changed = bool(p.run(self)) or changed
        return changed

    def to_exported(self):
        """Serialize the (possibly rewritten) module back into an
        executable ``jax.export.Exported``."""
        if self._exported is None:
            raise ValueError("this ProgramIR was not built from an "
                             "Exported; nothing to rebuild")
        from jax._src.export import _export as _exp
        return dataclasses.replace(
            self._exported,
            mlir_module_serialized=_exp._module_to_bytecode(self._module))


class Pass:
    """Base pass: subclass and implement ``run(program_ir) -> changed``."""

    name = "pass"

    def run(self, pir: ProgramIR) -> bool:
        raise NotImplementedError


class MLIRPipelinePass(Pass):
    """Run a real MLIR pass pipeline on the module (``canonicalize``,
    ``cse``, ...) — the reference's DCE/CSE/constant-fold program passes,
    executed by MLIR itself."""

    def __init__(self, name, pipeline):
        self.name = name
        self.pipeline = pipeline

    def run(self, pir: ProgramIR) -> bool:
        from jaxlib.mlir.passmanager import PassManager
        jmlir, _ = _ir()
        before = jmlir.module_to_bytecode(pir._module)   # cheaper than text
        with pir._ctx:
            PassManager.parse(f"builtin.module({self.pipeline})").run(
                pir._module.operation)
        return jmlir.module_to_bytecode(pir._module) != before


class PatternRewritePass(Pass):
    """Python-level pattern rewriter (reference ``PatternRewritePass`` /
    drr): visit every op with ``matcher(op)``; when it returns True call
    ``rewriter(op)`` (mutate attributes, move/erase the op through the
    MLIR python API)."""

    def __init__(self, name, matcher, rewriter):
        self.name = name
        self.matcher = matcher
        self.rewriter = rewriter

    def run(self, pir: ProgramIR) -> bool:
        hits = [op for op in pir.ops() if self.matcher(op)]
        for op in hits:
            self.rewriter(op)
        return bool(hits)


class PassRegistry:
    def __init__(self):
        self._passes: dict = {}

    def register(self, p: Pass):
        self._passes[p.name] = p
        return p

    def get(self, name: str) -> Pass:
        if name not in self._passes:
            raise KeyError(f"unknown pass {name!r}; registered: "
                           f"{sorted(self._passes)}")
        return self._passes[name]

    def names(self):
        return sorted(self._passes)


registry = PassRegistry()
registry.register(MLIRPipelinePass("canonicalize", "canonicalize"))
registry.register(MLIRPipelinePass("cse", "cse"))
registry.register(MLIRPipelinePass("ir_optim", "canonicalize,cse"))


def optimize_exported(exported, passes=("ir_optim",)):
    """One-call helper: parse → run passes → rebuilt Exported. Used by the
    inference Predictor when ``Config.switch_ir_optim(True)`` is set."""
    pir = ProgramIR.from_exported(exported)
    pir.apply(list(passes))
    return pir.to_exported()
