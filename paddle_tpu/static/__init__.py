"""paddle.static facade (reference: ``python/paddle/static/`` — SURVEY.md §2.2).

TPU-native design (SURVEY.md §7.0): the static graph Program is a facade over
a traced+lowered jax function — no ProgramDesc protobuf. ``Executor.run`` is
feed/fetch over compiled calls. The dygraph ``to_static`` path (paddle_tpu/jit)
is the primary compile path; this module exists for API-surface compatibility
with static-mode scripts and grows as static-mode features are ported.
"""
from __future__ import annotations

import contextlib

from ..jit.api import InputSpec  # noqa: F401
from ..framework.core import Tensor, current_place, CPUPlace, TPUPlace, CUDAPlace  # noqa: F401


class Program:
    """Facade: records data() placeholders and a traced fn when compiled."""

    def __init__(self):
        self._inputs = []
        self._fetch = []
        self.random_seed = None

    def global_block(self):
        return self

    def clone(self, for_test=False):
        import copy
        return copy.copy(self)


_default_main = [Program()]
_default_startup = [Program()]


def default_main_program():
    return _default_main[0]


def default_startup_program():
    return _default_startup[0]


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    prev_m, prev_s = _default_main[0], _default_startup[0]
    _default_main[0] = main_program
    if startup_program is not None:
        _default_startup[0] = startup_program
    try:
        yield
    finally:
        _default_main[0], _default_startup[0] = prev_m, prev_s


def data(name, shape, dtype="float32", lod_level=0):
    spec = InputSpec(shape, dtype, name)
    default_main_program()._inputs.append(spec)
    return spec


class Executor:
    """Static executor facade: run(feed, fetch_list) executes the fetches'
    traced computation. In this build, static programs are built by running
    eager code under ``paddle.enable_static()`` compatibility shims; prefer
    ``@to_static``. run() accepts callables or Tensors as fetch targets."""

    def __init__(self, place=None):
        self.place = place or current_place()

    def run(self, program=None, feed=None, fetch_list=None, return_numpy=True):
        outs = []
        for f in (fetch_list or []):
            if callable(f):
                out = f(**(feed or {}))
            else:
                out = f
            if isinstance(out, Tensor):
                outs.append(out.numpy() if return_numpy else out)
            else:
                outs.append(out)
        return outs


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy


class BuildStrategy:
    def __init__(self):
        self.build_cinn_pass = False
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = False


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor, **kwargs):
    raise NotImplementedError(
        "static save_inference_model: use paddle.jit.save (StableHLO export)")


def load_inference_model(path_prefix, executor, **kwargs):
    raise NotImplementedError(
        "static load_inference_model: use paddle.jit.load")


def name_scope(prefix=None):
    return contextlib.nullcontext()


from . import nn  # noqa: E402,F401  (control flow: cond/while_loop/switch_case)
