"""paddle.static facade (reference: ``python/paddle/static/`` — SURVEY.md §2.2).

TPU-native design (SURVEY.md §7.0): the static graph Program is a facade over
a traced+lowered jax function — no ProgramDesc protobuf. ``Executor.run`` is
feed/fetch over compiled calls. The dygraph ``to_static`` path (paddle_tpu/jit)
is the primary compile path; this module exists for API-surface compatibility
with static-mode scripts and grows as static-mode features are ported.
"""
from __future__ import annotations

import contextlib

from ..jit.api import InputSpec  # noqa: F401
from ..framework.core import Tensor, current_place, CPUPlace, TPUPlace, CUDAPlace  # noqa: F401


class Program:
    """Facade: records data() placeholders and a traced fn when compiled."""

    def __init__(self):
        self._inputs = []
        self._fetch = []
        self.random_seed = None

    def global_block(self):
        return self

    def clone(self, for_test=False):
        import copy
        return copy.copy(self)


_default_main = [Program()]
_default_startup = [Program()]


def default_main_program():
    return _default_main[0]


def default_startup_program():
    return _default_startup[0]


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    prev_m, prev_s = _default_main[0], _default_startup[0]
    _default_main[0] = main_program
    if startup_program is not None:
        _default_startup[0] = startup_program
    try:
        yield
    finally:
        _default_main[0], _default_startup[0] = prev_m, prev_s


def data(name, shape, dtype="float32", lod_level=0):
    spec = InputSpec(shape, dtype, name)
    default_main_program()._inputs.append(spec)
    return spec


class Executor:
    """Static executor facade: run(feed, fetch_list) executes the fetches'
    traced computation. In this build, static programs are built by running
    eager code under ``paddle.enable_static()`` compatibility shims; prefer
    ``@to_static``. run() accepts callables or Tensors as fetch targets."""

    def __init__(self, place=None):
        self.place = place or current_place()

    def run(self, program=None, feed=None, fetch_list=None, return_numpy=True):
        outs = []
        # a loaded inference Program executes its compiled StableHLO
        compiled = getattr(program, "_compiled", None)
        if compiled is not None:
            feed = feed or {}
            names = getattr(program, "_feed_names", list(feed))
            missing = [n for n in names if n not in feed]
            extra = [k for k in feed if k not in names]
            if missing or extra:
                raise KeyError(
                    f"Executor.run feed mismatch: program expects "
                    f"{names}, missing={missing}, unknown={extra} — "
                    f"positional fallback would silently reorder inputs")
            args = [feed[n] for n in names]
            out = compiled(*args)
            flat = out if isinstance(out, (list, tuple)) else [out]
            return [o.numpy() if return_numpy and isinstance(o, Tensor)
                    else o for o in flat]
        for f in (fetch_list or []):
            if callable(f):
                out = f(**(feed or {}))
            else:
                out = f
            if isinstance(out, Tensor):
                outs.append(out.numpy() if return_numpy else out)
            else:
                outs.append(out)
        return outs


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy


class BuildStrategy:
    def __init__(self):
        self.build_cinn_pass = False
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = False


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         **kwargs):
    """Static-mode export bridged to the StableHLO path (reference:
    ``static.save_inference_model`` → Program serialization; here the
    IR IS StableHLO, so this wraps :func:`paddle.jit.save`).

    ``feed_vars``: InputSpecs (from :func:`static.data`) or Tensors;
    ``fetch_vars``: a Layer or callable producing the fetch outputs."""
    from .. import jit as pjit

    target = fetch_vars[0] if isinstance(fetch_vars, (list, tuple)) \
        and len(fetch_vars) == 1 else fetch_vars
    if not callable(target):
        raise TypeError(
            "save_inference_model needs fetch_vars to be (or contain) the "
            "Layer/callable that computes the fetches; a bare fetched "
            "Tensor has no captured graph in this build — pass the model")
    specs = _to_input_specs(feed_vars)
    pjit.save(target, path_prefix, input_spec=specs)
    return path_prefix


def load_inference_model(path_prefix, executor, **kwargs):
    """Load a static export: returns ``(program, feed_names,
    fetch_names)`` where ``program`` is runnable via ``Executor.run``
    (it is also directly callable)."""
    from .. import jit as pjit

    layer = pjit.load(path_prefix)
    meta = getattr(layer, "_meta", {}) or {}
    specs = meta.get("input_specs") or []
    # meta entries are (shape, dtype[, name]); older exports lack names
    feed_names = [(s[2] if len(s) > 2 and s[2] else f"feed_{i}")
                  for i, s in enumerate(specs)]
    prog = Program()
    prog._compiled = layer
    prog._feed_names = feed_names
    return prog, feed_names, ["fetch_0"]


def name_scope(prefix=None):
    return contextlib.nullcontext()


def save(program, model_path, protocol=4, **configs):
    """reference: ``paddle.static.save(program, path)`` persists the
    program's persistable variables. Program facades in this build hold
    no parameters (SURVEY.md §7.0 — jit traces close over nn.Layer
    state), so training state saves through ``paddle.save(
    layer.state_dict(), path)`` and deployable graphs through
    ``static.save_inference_model`` / ``paddle.jit.save``."""
    raise NotImplementedError(save.__doc__)


def load(program, model_path, executor=None, var_list=None):
    """reference: ``paddle.static.load``; see :func:`save` — use
    ``paddle.load`` + ``set_state_dict`` or ``load_inference_model``."""
    raise NotImplementedError(load.__doc__)


def cpu_places(device_count=None):
    from ..framework.core import CPUPlace
    import os
    n = device_count or int(os.environ.get("CPU_NUM", "1"))
    return [CPUPlace() for _ in range(n)]


@contextlib.contextmanager
def device_guard(device=None):
    """reference: pins ops to a device inside a program. Single-backend
    build: a no-op context (XLA owns placement)."""
    yield


class Scope:
    """Minimal variable scope (reference ``paddle.static.global_scope()``
    — name → variable holder used by inference IO helpers)."""

    def __init__(self):
        self._vars = {}

    def var(self, name):
        self._vars.setdefault(name, _ScopeVar())
        return self._vars[name]

    def find_var(self, name):
        return self._vars.get(name)


class _ScopeVar:
    def __init__(self):
        self._value = None

    def get_tensor(self):
        return self._value

    def set(self, value, place=None):
        self._value = value


_global_scope = Scope()


def global_scope():
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope):
    global _global_scope
    prev, _global_scope = _global_scope, scope
    try:
        yield
    finally:
        _global_scope = prev


def _to_input_specs(feed_vars):
    return [v if isinstance(v, InputSpec) else InputSpec.from_tensor(v)
            for v in (feed_vars if isinstance(feed_vars, (list, tuple))
                      else [feed_vars])]


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """reference: prunes/standardizes a Program for export. The facade
    records feeds; pruning is the jit tracer's job — returns the program
    with feed specs attached."""
    program._inputs = _to_input_specs(feed_vars)
    return program


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """reference: ``paddle.static.py_func`` — run arbitrary Python inside
    a program. TPU-native: ``jax.pure_callback`` hosts the Python call
    inside the compiled graph; ``out`` supplies the result
    shape/dtype template (InputSpec or Tensor)."""
    import jax
    import numpy as np
    from ..framework.core import Tensor
    from ..autograd.tape import apply

    outs = out if isinstance(out, (list, tuple)) else [out]
    import jax.numpy as jnp
    shapes = [jax.ShapeDtypeStruct(tuple(o.shape),
                                   jnp.dtype(o.dtype)) for o in outs]
    xs = x if isinstance(x, (list, tuple)) else [x]

    def _host(py_fn):
        def host(*np_arrs):
            res = py_fn(*[Tensor(np.asarray(a)) for a in np_arrs])
            res = res if isinstance(res, (list, tuple)) else [res]
            return tuple(np.asarray(r.numpy() if isinstance(r, Tensor)
                                    else r) for r in res)
        return host

    # reference contract: backward_func is called with (forward inputs,
    # forward OUTPUTS, output grads), with any var listed in
    # skip_vars_in_backward_input dropped from the first two groups
    # (matched by identity against ``x``/``out``); it returns the grads
    # of the (unfiltered) forward inputs.
    skip = (list(skip_vars_in_backward_input)
            if skip_vars_in_backward_input is not None else [])
    keep_x = [i for i, v in enumerate(xs)
              if not any(v is s for s in skip)]
    keep_out = [i for i, v in enumerate(outs)
                if not any(v is s for s in skip)]

    def fn(*arrs):
        if backward_func is None:
            # gradient-opaque host call: stop_gradient-ing the callback
            # inputs keeps jax.vjp from needing a (nonexistent) JVP rule
            # for pure_callback; grads through it are zero, matching
            # "no backward_func provided"
            arrs = tuple(jax.lax.stop_gradient(a) for a in arrs)
            res = jax.pure_callback(_host(func), tuple(shapes), *arrs)
            return res if len(res) > 1 else res[0]

        @jax.custom_vjp
        def call(*a):
            res = jax.pure_callback(_host(func), tuple(shapes), *a)
            return res if len(res) > 1 else res[0]

        def fwd(*a):
            y = call(*a)
            ys = y if isinstance(y, tuple) else (y,)
            return y, (a, ys)

        def bwd(resids, g):
            a, ys = resids
            gs = tuple(g) if isinstance(g, tuple) else (g,)
            args = ([a[i] for i in keep_x] + [ys[i] for i in keep_out]
                    + list(gs))
            in_shapes = tuple(jax.ShapeDtypeStruct(v.shape, v.dtype)
                              for v in a)
            res = jax.pure_callback(_host(backward_func), in_shapes, *args)
            return tuple(res)

        call.defvjp(fwd, bwd)
        return call(*arrs)

    return apply(fn, *xs, op_name="py_func")


from . import nn  # noqa: E402,F401  (control flow: cond/while_loop/switch_case)
