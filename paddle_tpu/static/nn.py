"""paddle.static.nn — static-graph control flow (reference:
``python/paddle/static/nn/control_flow.py`` — ``cond``/``while_loop``/
``switch_case``/``case`` build ConditionalBlock/While ops into the Program;
SURVEY.md §7.1 M1 maps them onto XLA control-flow primitives).

TPU-native: under a trace these lower to ``lax.cond`` / ``lax.while_loop`` /
``lax.switch`` — compiler-friendly control flow with NO graph break, so a
tensor-dependent branch inside ``@to_static`` stays compiled instead of
permanently degrading to eager. Eagerly (concrete predicate) they are plain
Python control flow, matching reference dygraph semantics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..autograd.tape import apply


def _arr(x):
    return x._data if isinstance(x, Tensor) else x


def _unwrap_tree(x):
    return jax.tree.map(lambda t: t._data if isinstance(t, Tensor) else t, x,
                        is_leaf=lambda t: isinstance(t, Tensor))


def _is_traced(*vals):
    return any(isinstance(v, jax.core.Tracer)
               for v in jax.tree.leaves([_unwrap_tree(v) for v in vals]))


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """Run ``true_fn()`` or ``false_fn()`` on a scalar boolean ``pred``.

    Both branches must return the same structure/shapes/dtypes (the
    reference ConditionalBlock contract == the ``lax.cond`` contract).
    """
    p = _arr(pred)
    if not isinstance(p, jax.core.Tracer):
        if bool(p):
            return true_fn() if true_fn is not None else None
        return false_fn() if false_fn is not None else None

    def fn(pa):
        def t_(_):
            return _unwrap_tree(true_fn())

        def f_(_):
            return _unwrap_tree(false_fn())

        return jax.lax.cond(jnp.asarray(pa).astype(bool).reshape(()),
                            t_, f_, None)

    return apply(fn, pred, op_name="cond")


def case(pred_fn_pairs, default=None, name=None):
    """First pair whose pred is True wins (reference ``static.nn.case``);
    lowers to a chain of ``cond``."""
    pairs = list(pred_fn_pairs)
    if not pairs:
        return default() if default is not None else None
    (pred, fn), rest = pairs[0], pairs[1:]
    return cond(pred, fn, lambda: case(rest, default=default))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Dispatch on an integer index (reference ``static.nn.switch_case``;
    ``lax.switch`` under trace). ``branch_fns``: list of callables or
    {index: callable} with dense 0..N-1 keys after filling ``default``."""
    idx = _arr(branch_index)
    if isinstance(branch_fns, dict):
        if not isinstance(idx, jax.core.Tracer):
            i = int(idx)     # eager: direct dict dispatch, sparse is fine
            fn = branch_fns.get(i, default)
            if fn is None:
                raise ValueError(f"switch_case: no branch for index {i} "
                                 "and no default")
            return fn()
        hi = max(branch_fns) + 1
        fns = [branch_fns.get(i, default) for i in range(hi)]
        if any(f is None for f in fns):
            raise ValueError("switch_case: under a trace a sparse branch "
                             "dict needs a default (lax.switch is dense)")
    else:
        fns = list(branch_fns)
    if not isinstance(idx, jax.core.Tracer):
        i = int(idx)
        if 0 <= i < len(fns):
            return fns[i]()
        if default is not None:
            return default()
        i = max(0, min(i, len(fns) - 1))    # lax.switch clamp semantics
        return fns[i]()
    all_fns = fns + ([default] if default is not None else [])

    def fn(ia):
        i = jnp.asarray(ia).astype(jnp.int32).reshape(())
        if default is not None:
            i = jnp.where((i < 0) | (i >= len(fns)), len(fns), i)
        return jax.lax.switch(i, [lambda _, f=f: _unwrap_tree(f())
                                  for f in all_fns], None)

    return apply(fn, branch_index, op_name="switch_case")


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """``while cond(*vars): vars = body(*vars)`` (reference
    ``static.nn.while_loop``). Under a trace this is ``lax.while_loop``:
    shapes/dtypes of ``loop_vars`` are invariant, and reverse-mode
    gradients through the traced loop are not defined (same as jax; use
    a scan-style bounded loop for differentiable iteration)."""
    if not isinstance(loop_vars, (list, tuple)):
        raise TypeError("loop_vars must be a list/tuple")
    if not _is_traced(*loop_vars):
        out = list(loop_vars)
        while bool(_arr(cond(*out))):
            out = list(body(*out))
            if len(out) != len(loop_vars):
                raise ValueError("body must return as many vars as it takes")
        return out

    def fn(*arrs):
        def c(vs):
            return jnp.asarray(_unwrap_tree(cond(*_wrap_like(vs)))) \
                      .astype(bool).reshape(())

        def b(vs):
            res = body(*_wrap_like(vs))
            return tuple(_unwrap_tree(r) for r in res)

        return jax.lax.while_loop(c, b, tuple(arrs))

    def _wrap_like(vs):
        return [Tensor(v) if not isinstance(v, Tensor) else v for v in vs]

    out = apply(fn, *loop_vars, op_name="while_loop")
    return list(out)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    raise NotImplementedError(
        "static.nn.fc: build models with paddle.nn.Linear; static-graph "
        "parameter creation is out of the TPU build's scope (SURVEY.md §7.0)")


def conv2d(*args, **kwargs):
    raise NotImplementedError(
        "static.nn.conv2d: build models with paddle.nn.Conv2D; "
        "static-graph parameter creation is out of the TPU build's scope "
        "(SURVEY.md §7.0)")


def batch_norm(*args, **kwargs):
    raise NotImplementedError(
        "static.nn.batch_norm: build models with paddle.nn.BatchNorm2D; "
        "static-graph parameter creation is out of the TPU build's scope "
        "(SURVEY.md §7.0)")


def embedding(*args, **kwargs):
    raise NotImplementedError(
        "static.nn.embedding: build models with paddle.nn.Embedding; "
        "static-graph parameter creation is out of the TPU build's scope "
        "(SURVEY.md §7.0)")


def sequence_expand(*args, **kwargs):
    raise NotImplementedError(
        "static.nn.sequence_expand: LoD sequence ops are legacy-fluid; "
        "use dense padded batches + masks in this build (SURVEY.md §7.4)")
