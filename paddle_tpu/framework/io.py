"""paddle.save / paddle.load (reference: ``python/paddle/framework/io.py`` —
pickle-based state_dict serialization, SURVEY.md §5.4). Tensors are stored as
numpy arrays; nested dicts/lists preserved. A sharded/async Orbax-backed path
for distributed checkpoints lives in ``paddle_tpu/distributed/checkpoint.py``.
"""
from __future__ import annotations

import os
import pickle
import threading

import numpy as np

from .core import Tensor, Parameter


class _TensorPayload:
    """Pickle-stable wrapper marking arrays that were Tensors."""

    def __init__(self, array, is_param, name, stop_gradient):
        self.array = array
        self.is_param = is_param
        self.name = name
        self.stop_gradient = stop_gradient


def _pack(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj.numpy()), isinstance(obj, Parameter),
                              obj.name, obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        cls = Parameter if obj.is_param else Tensor
        t = cls(obj.array, name=obj.name)
        if not obj.is_param:
            t.stop_gradient = obj.stop_gradient
        return t
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    # write-temp-then-replace: a writer killed mid-save (rank preemption,
    # crash) must never leave a half-written file a later load() could
    # deserialize — the target path only ever points at a complete pickle
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "wb") as f:
            pickle.dump(_pack(obj), f, protocol=protocol)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def load(path, **configs):
    return_numpy = configs.get("return_numpy", False)
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy)
