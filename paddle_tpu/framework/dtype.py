"""Dtype system.

Paddle exposes dtypes both as ``paddle.float32``-style singletons and as strings
('float32'). We map every spelling onto numpy/jax dtypes (reference:
``paddle/phi/common/data_type.h`` — see SURVEY.md provenance banner; paths are
canonical-upstream, unverified).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtype singletons (numpy dtype objects; jax arrays report these).
bfloat16 = jnp.bfloat16
float16 = np.float16
float32 = np.float32
float64 = np.float64
int8 = np.int8
int16 = np.int16
int32 = np.int32
int64 = np.int64
uint8 = np.uint8
bool_ = np.bool_
complex64 = np.complex64
complex128 = np.complex128

_STR2DTYPE = {
    "bfloat16": bfloat16, "bf16": bfloat16,
    "float16": float16, "fp16": float16, "half": float16,
    "float32": float32, "fp32": float32, "float": float32,
    "float64": float64, "fp64": float64, "double": float64,
    "int8": int8, "int16": int16, "int32": int32, "int64": int64,
    "uint8": uint8, "bool": bool_,
    "complex64": complex64, "complex128": complex128,
}

_default_dtype = "float32"

# Index dtype actually used at runtime: int64 narrows to int32 without jax
# x64 (documented deviation; paddle reports int64 indices).
INT_DTYPE = int32


def set_default_dtype(d):
    global _default_dtype
    d = np.dtype(convert_dtype(d)).name if d is not None else "float32"
    if np.dtype(convert_dtype(d)) not in (np.dtype(float32), np.dtype(float64), np.dtype(float16)) \
            and convert_dtype(d) != bfloat16:
        raise TypeError(f"set_default_dtype only supports floating dtypes, got {d}")
    _default_dtype = d


def get_default_dtype():
    return _default_dtype


def _narrow_64bit(t):
    """Without jax x64, 64-bit types silently truncate; map them up front so
    dtype queries stay consistent (documented deviation: int64→int32,
    float64→float32 on TPU — the TPU has no fp64 ALU anyway)."""
    import jax
    if jax.config.jax_enable_x64:
        return t
    return {np.int64: int32, np.uint64: np.uint32, np.float64: float32,
            np.complex128: complex64}.get(t, t)


def convert_dtype(d):
    """Normalize any dtype spelling to a numpy-compatible dtype object."""
    if d is None:
        return None
    if isinstance(d, str):
        key = d.lower()
        if key not in _STR2DTYPE:
            raise TypeError(f"unknown dtype {d!r}")
        return _narrow_64bit(_STR2DTYPE[key])
    if d is jnp.bfloat16:
        return bfloat16
    try:
        t = np.dtype(d).type if np.dtype(d) != np.dtype(jnp.bfloat16) else bfloat16
        return _narrow_64bit(t)
    except TypeError:
        raise TypeError(f"unknown dtype {d!r}")


def dtype_name(d) -> str:
    """'float32'-style name for a dtype (paddle convention)."""
    return np.dtype(d).name


def is_floating(d) -> bool:
    return jnp.issubdtype(np.dtype(d), jnp.floating)


def is_integer(d) -> bool:
    return jnp.issubdtype(np.dtype(d), jnp.integer) or np.dtype(d) == np.dtype(np.bool_)
