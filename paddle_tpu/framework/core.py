"""Tensor facade over jax.Array + device/place management.

Design (SURVEY.md §7.0): Paddle's eager ``Tensor`` is mutable, carries
``stop_gradient`` (default True — only Parameters default to False, reference
``python/paddle/autograd`` notes in SURVEY.md §2.2), an accumulated ``.grad``,
and supports in-place ops. We wrap an immutable ``jax.Array`` and swap it on
in-place mutation; autograd is an imperative tape recorded per-op (see
``paddle_tpu/autograd/tape.py``).

Most tensor *methods* (``reshape``, ``sum``, …) are monkey-patched onto this
class from the ops layer by ``paddle_tpu/framework/tensor_patch.py`` — the same
scheme upstream uses (``python/paddle/tensor/__init__.py`` monkey_patch).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtypes

# ---------------------------------------------------------------------------
# Place / device
# ---------------------------------------------------------------------------


class Place:
    """Device place: 'cpu', 'tpu' (the accelerator), 'gpu' aliases to 'tpu'."""

    def __init__(self, kind: str, index: int = 0):
        self.kind = kind
        self.index = index

    def __repr__(self):
        return f"Place({self.kind}:{self.index})"

    def __eq__(self, other):
        return isinstance(other, Place) and (self.kind, self.index) == (other.kind, other.index)

    def jax_device(self):
        plat = {"cpu": "cpu", "tpu": None, "gpu": None}[self.kind]
        devs = jax.devices(plat) if plat else jax.devices()
        return devs[self.index % len(devs)]


class CPUPlace(Place):
    def __init__(self):
        super().__init__("cpu")


class TPUPlace(Place):
    def __init__(self, index=0):
        super().__init__("tpu", index)


CUDAPlace = TPUPlace  # API-compat alias: 'gpu' means 'the accelerator' here.
XPUPlace = TPUPlace   # same alias: any accelerator place maps to the TPU.

_current_place: Place | None = None


def set_device(device: str) -> Place:
    """paddle.set_device('tpu'|'cpu'|'gpu:0'). 'gpu' aliases the accelerator."""
    global _current_place
    kind, _, idx = device.partition(":")
    kind = {"gpu": "tpu", "xpu": "tpu"}.get(kind, kind)
    place = Place(kind, int(idx) if idx else 0)
    _current_place = place
    try:
        jax.config.update("jax_default_device", place.jax_device())
    except RuntimeError:
        pass
    return place


def get_device() -> str:
    p = current_place()
    return f"{p.kind}:{p.index}"


def current_place() -> Place:
    global _current_place
    if _current_place is None:
        # default: accelerator if present else cpu
        kind = "cpu" if jax.default_backend() == "cpu" else "tpu"
        _current_place = Place(kind, 0)
    return _current_place


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def device_count():
    return jax.local_device_count()


# ---------------------------------------------------------------------------
# Tensor
# ---------------------------------------------------------------------------

_name_counter = [0]


def _auto_name(prefix="tensor"):
    _name_counter[0] += 1
    return f"{prefix}_{_name_counter[0]}"


class Tensor:
    """Eager tensor over a jax.Array.

    Attributes mirror Paddle: ``stop_gradient`` (True by default), ``grad``
    (a Tensor or None), ``name``, ``persistable``.
    """

    __array_priority__ = 100.0

    __slots__ = (
        "_data", "stop_gradient", "grad", "name", "persistable",
        "_grad_node", "_out_idx", "_retain_grads", "_grad_hooks", "_weak_pp",
        "process_mesh", "placements",   # auto-parallel dist-tensor attrs
        "__weakref__",
    )

    def __init__(self, data, dtype=None, stop_gradient=True, name=None, place=None):
        if isinstance(data, Tensor):
            data = data._data
        if not isinstance(data, (jax.Array, jax.core.Tracer)):
            dt = dtypes.convert_dtype(dtype) if dtype is not None else None
            arr = np.asarray(data)
            if dt is None and arr.dtype == np.float64:
                dt = dtypes.convert_dtype(dtypes.get_default_dtype())
            data = jnp.asarray(arr, dtype=dt)
        elif dtype is not None and data.dtype != np.dtype(dtypes.convert_dtype(dtype)):
            data = data.astype(dtypes.convert_dtype(dtype))
        self._data = data
        self.stop_gradient = stop_gradient
        self.grad = None
        self.name = name or _auto_name()
        self.persistable = False
        self._grad_node = None
        self._out_idx = 0
        self._retain_grads = False
        self._grad_hooks = None
        self._weak_pp = None
        self.process_mesh = None
        self.placements = None

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def ndim(self):
        return self._data.ndim

    dim = ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self):
        return current_place()

    @property
    def is_leaf(self):
        return self._grad_node is None

    @property
    def T(self):
        from ..ops import manipulation
        return manipulation.transpose(self, list(range(self.ndim))[::-1])

    def numel(self):
        return self.size

    # -- conversion ---------------------------------------------------------
    def numpy(self):
        try:
            return np.asarray(jax.device_get(self._data))
        except RuntimeError as e:
            if type(e).__name__ == "DonatedTensorError":
                raise          # already the clear guard diagnostic
            if "deleted" in str(e).lower() or "donated" in str(e).lower():
                # donation/aliasing misuse guard (SURVEY.md §5.2 TPU
                # equivalent of StreamSafeCUDAAllocator's reuse guard)
                raise RuntimeError(
                    "Tensor used after its device buffer was donated to a "
                    "jitted call (donate_argnums) — keep the returned "
                    "tensor instead of the donated input") from e
            raise

    def item(self, *args):
        return self.numpy().item(*args)

    def tolist(self):
        return self.numpy().tolist()

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        if self.size != 1:
            raise ValueError("The truth value of a Tensor with more than one element is ambiguous")
        return bool(self.item())

    def __index__(self):
        return int(self.item())

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self._data.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        from ..autograd import tape
        from ..profiler import step_phase as _step_phase
        _t0 = _step_phase.clock()
        tape.run_backward([self], [grad_tensor], retain_graph=retain_graph)
        if _t0 is not None:
            import time as _time
            _step_phase.record_phase("backward", _time.perf_counter() - _t0)

    def retain_grads(self):
        self._retain_grads = True

    def register_hook(self, hook):
        if self._grad_hooks is None:
            self._grad_hooks = []
        self._grad_hooks.append(hook)

        class _Removable:
            def __init__(self, hooks, h):
                self._hooks, self._h = hooks, h

            def remove(self):
                if self._h in self._hooks:
                    self._hooks.remove(self._h)

        return _Removable(self._grad_hooks, hook)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def detach(self):
        t = Tensor(self._data, stop_gradient=True, name=self.name + "_detached")
        return t

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self):
        from ..autograd.tape import apply
        return apply(lambda x: x + 0, self, op_name="clone")

    # -- mutation -----------------------------------------------------------
    def _replace_(self, new_data, node=None, out_idx=0):
        """In-place: swap underlying array (and autograd provenance)."""
        self._data = new_data
        self._grad_node = node
        self._out_idx = out_idx
        return self

    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._data
        value = jnp.asarray(value, dtype=self.dtype)
        if tuple(value.shape) != tuple(self._data.shape):
            raise ValueError(f"set_value shape mismatch {value.shape} vs {self._data.shape}")
        self._data = value
        return self

    def copy_(self, other, *a):
        return self.set_value(other)

    # -- device / dtype movement -------------------------------------------
    def astype(self, dtype):
        from ..autograd.tape import apply
        dt = dtypes.convert_dtype(dtype)
        return apply(lambda x: x.astype(dt), self, op_name="cast")

    def cast(self, dtype):
        return self.astype(dtype)

    def cpu(self):
        return Tensor(jax.device_put(self._data, jax.devices("cpu")[0]),
                      stop_gradient=self.stop_gradient)

    def cuda(self, device_id=None, blocking=True):
        """API-compat: 'cuda' means 'the accelerator' in this build."""
        devs = jax.devices()
        return Tensor(jax.device_put(self._data,
                                     devs[(device_id or 0) % len(devs)]),
                      stop_gradient=self.stop_gradient)

    def element_size(self):
        return self._data.dtype.itemsize

    @property
    def nbytes(self):
        return self._data.dtype.itemsize * int(self.size)

    def data_ptr(self):
        """Opaque buffer identity (reference returns the device pointer).
        Uses the device buffer's real address when the backend exposes it,
        so two Tensor wrappers over ONE jax buffer compare equal and ids
        recycled by GC can't alias; falls back to id() where the runtime
        hides the pointer (meaningful only for same-object comparison
        within a live scope there)."""
        try:
            return self._data.unsafe_buffer_pointer()
        except (AttributeError, NotImplementedError, RuntimeError,
                ValueError):   # ValueError: sharded/multi-device arrays
            return id(self._data)

    def is_sparse(self):
        return False

    def coalesce(self):
        """Dense tensors are their own coalesced form; sparse COO
        tensors override this in paddle_tpu.sparse."""
        return self

    def apply_(self, func):
        """In-place elementwise python function (reference
        ``Tensor.apply_`` — host-side, eager only)."""
        import numpy as np
        arr = np.vectorize(func)(self.numpy()).astype(
            np.asarray(self.numpy()).dtype)
        self._replace_(jnp.asarray(arr))
        return self

    def apply(self, func):
        return Tensor(jnp.asarray(self.clone().apply_(func)._data),
                      stop_gradient=self.stop_gradient)

    def exponential_(self, lam=1.0):
        """In-place exponential sampling (reference
        ``Tensor.exponential_``)."""
        from . import random as prandom
        u = jax.random.uniform(prandom.next_key(), self._data.shape,
                               minval=1e-7, maxval=1.0)
        self._replace_((-jnp.log(u) / lam).astype(self._data.dtype))
        return self

    def floor_divide_(self, y):
        y = y._data if isinstance(y, Tensor) else y
        self._replace_(jnp.floor_divide(self._data, y))
        return self

    def to(self, *args, **kwargs):
        t = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and a.lower() in dtypes._STR2DTYPE:
                t = t.astype(a)
            elif isinstance(a, str):  # device string
                kind, _, idx = a.partition(":")
                place = Place({"gpu": "tpu", "xpu": "tpu"}.get(kind, kind),
                              int(idx) if idx else 0)
                t = Tensor(jax.device_put(t._data, place.jax_device()),
                           stop_gradient=t.stop_gradient)
            elif a is not None and not isinstance(a, bool):
                t = t.astype(a)
        return t

    def pin_memory(self):
        return self

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True

    # -- repr ---------------------------------------------------------------
    def __repr__(self):
        grad_info = f", stop_gradient={self.stop_gradient}"
        return (f"Tensor(shape={self.shape}, dtype={dtypes.dtype_name(self.dtype)}"
                f"{grad_info},\n       {np.array2string(self.numpy(), prefix='       ')})")

    __str__ = __repr__

    # -- numpy interop ------------------------------------------------------
    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a


class EagerParamBase(Tensor):
    """A trainable parameter: stop_gradient defaults to False."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "is_distributed",
                 "need_clip", "initializer", "_sharding_spec")

    def __init__(self, data, dtype=None, name=None, trainable=True, **kw):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable,
                         name=name or _auto_name("param"))
        self.trainable = trainable
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False
        self.need_clip = True
        self.initializer = None
        # PartitionSpec-like tuple for distributed placement (parallel/ layer code sets it)
        self._sharding_spec = None

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


Parameter = EagerParamBase


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor"""
    if isinstance(data, Tensor):
        if dtype is not None and np.dtype(dtypes.convert_dtype(dtype)) != data.dtype:
            data = data.astype(dtype)
        t = Tensor(data._data)
        t.stop_gradient = stop_gradient
        return t
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient, place=place)
