"""Functionalize an eager ``nn.Layer`` into a pure JAX function.

This is the single bridge between the imperative Paddle-style world (mutable
Tensors, ``Layer`` objects, hidden RNG state) and the functional JAX world
(pure pytree-in/pytree-out functions that ``jax.jit`` / ``jax.grad`` /
``pjit`` can transform). Everything that compiles a whole model — ``@to_static``
(jit/api.py), the distributed train-step engine (distributed/engine.py), the
pipeline-parallel scheduler, and ``__graft_entry__`` — goes through here.

Reference analogue: the dygraph→static Program capture of
``python/paddle/jit/dy2static/program_translator.py`` (SURVEY.md §3.2) — but
instead of building a Program IR we temporarily swap each Parameter/buffer's
backing ``jax.Array`` for a tracer and let JAX trace the eager op layer
directly (SURVEY.md §7.0: "jax.jit IS the tracer").
"""
from __future__ import annotations

import contextlib

import jax

from .core import Tensor
from . import random as prandom


def _is_tensor(x):
    return isinstance(x, Tensor)


@contextlib.contextmanager
def swap_state(params, buffers, p_arrs, b_arrs, rng_key, layer=None,
               training=None, enable_grad=False):
    """Swap parameter/buffer backing arrays for (possibly traced) ``p_arrs``/
    ``b_arrs``, seed the hidden RNG from ``rng_key``, raise the tracing flag,
    optionally force ``training`` on every sublayer — and restore everything
    on exit. The single primitive under FunctionalModule and @to_static.

    ``enable_grad=True`` keeps the tape RECORDING during the trace (nodes
    over tracers) so in-trace ``paddle.grad(create_graph=...)`` works —
    used by @to_static on retry when the traced function needs autograd;
    XLA dead-code-eliminates the unused vjps otherwise."""
    from ..autograd.tape import no_grad, enable_grad as _enable_grad
    from ..jit import api as jit_api

    saved_p = [t._data for t in params]
    saved_b = [t._data for t in buffers]
    sublayers = (list(layer.sublayers(include_self=True))
                 if layer is not None and hasattr(layer, "sublayers") else [])
    saved_train = [l.training for l in sublayers]
    gen = prandom.default_generator()
    saved_rng = (gen._root, gen._counter)
    saved_tracing = jit_api._TRACING[0]
    jit_api._TRACING[0] = True
    try:
        for t, a in zip(params, p_arrs):
            t._data = a
        for t, a in zip(buffers, b_arrs):
            t._data = a
        if training is not None:
            for l in sublayers:
                l.training = training
        gen._root = rng_key
        gen._counter = 0
        with (_enable_grad() if enable_grad else no_grad()):
            yield
    finally:
        for t, a in zip(params, saved_p):
            t._data = a
        for t, a in zip(buffers, saved_b):
            t._data = a
        if training is not None:
            for l, tr in zip(sublayers, saved_train):
                l.training = tr
        gen._root, gen._counter = saved_rng
        jit_api._TRACING[0] = saved_tracing


class FunctionalModule:
    """Pure-function view of a Layer.

    ``fm = FunctionalModule(layer)`` then
    ``out, new_bufs = fm(p_arrs, b_arrs, rng_key, *args, **kwargs)``

    - ``p_arrs`` / ``b_arrs``: lists of raw arrays matching ``fm.params`` /
      ``fm.buffers`` order (swap-in happens under the hood).
    - ``rng_key``: a jax PRNG key seeding this call's op-level randomness
      (dropout etc.); pass ``fm.next_key()`` eagerly, or thread a key in jit.
    - Tensor leaves in ``args``/``kwargs`` are passed through as arrays;
      raw jax arrays are also accepted.
    - Returns the forward output with Tensors unwrapped to arrays, plus the
      post-call buffer arrays (BN running stats etc.) so state updates thread
      through jit functionally.

    The call is pure in the JAX sense: no tape recording (autograd comes from
    ``jax.grad`` over this function), layer state restored afterwards.
    """

    def __init__(self, layer, method=None, training=None):
        self.layer = layer
        self._method = method or (layer.forward if hasattr(layer, "forward") else layer)
        self.params = [p for p in layer.parameters() if p is not None]
        self.buffers = [b for b in layer.buffers() if b is not None]
        self._training = training

    # -- state accessors -----------------------------------------------------
    def param_arrays(self):
        return [p._data for p in self.params]

    def buffer_arrays(self):
        return [b._data for b in self.buffers]

    def next_key(self):
        return prandom.next_key()

    # -- the pure call -------------------------------------------------------
    def __call__(self, p_arrs, b_arrs, rng_key, *args, **kwargs):
        with swap_state(self.params, self.buffers, p_arrs, b_arrs, rng_key,
                        layer=self.layer if hasattr(self.layer, "sublayers") else None,
                        training=self._training):
            def wrap(x):
                if isinstance(x, Tensor):
                    return x
                if isinstance(x, (jax.Array, jax.core.Tracer)):
                    return Tensor(x)
                return x

            w_args, w_kwargs = jax.tree.map(wrap, (args, kwargs),
                                            is_leaf=_is_tensor)
            out = self._method(*w_args, **w_kwargs)
            out_arrays = jax.tree.map(
                lambda t: t._data if isinstance(t, Tensor) else t, out,
                is_leaf=_is_tensor)
            new_b = [t._data for t in self.buffers]
            return out_arrays, new_b

    # -- sharding ------------------------------------------------------------
    def param_specs(self, rules=None, fsdp_axis=None, fsdp_size=1):
        """PartitionSpec per param (in ``self.params`` order) from an ordered
        ``(name-regex, spec-tuple)`` rule list (first match wins; see
        ``paddle_tpu.models.*.sharding_rules``). With ``fsdp_axis`` set
        (ZeRO-3 / sharding stage-3), each >=2-D param's first dimension that
        is not already sharded and is divisible by ``fsdp_size`` is
        additionally sharded on that axis. 1-D params (norm scales, biases)
        stay replicated: sharding them saves nothing and GSPMD propagates
        their split into every activation that consumes them, forcing an
        "Involuntary full rematerialization" replicate-repartition (observed
        round 1 in the dryrun)."""
        import re
        from jax.sharding import PartitionSpec as P

        named = [(n, p) for n, p in self.layer.named_parameters()
                 if p is not None]
        assert [id(p) for _, p in named] == [id(p) for p in self.params]
        from ..distributed import mesh as mesh_mod
        live = mesh_mod.has_mesh()
        specs = []
        for name, p in named:
            spec = ()
            for pat, s in (rules or []):
                if re.search(pat, name):
                    spec = tuple(s)
                    break
            spec = list(spec) + [None] * (len(p.shape) - len(spec))
            if live:
                # a rule axis that does not divide the dim would fail at
                # device_put (e.g. 4 experts over a dp=8 ep axis): such a
                # param replicates on that axis instead. (spec may be
                # LONGER than the rank when a rule over-matches — those
                # trailing axes fail at P() construction with the clear
                # rank error, not an IndexError here.)
                for d, ax in enumerate(spec[:len(p.shape)]):
                    if ax is not None:
                        n_ax = mesh_mod.axis_size(ax)
                        if n_ax > 1 and p.shape[d] % n_ax != 0:
                            spec[d] = None
            if fsdp_axis is not None and fsdp_size > 1 and len(p.shape) >= 2:
                for d, (sz, ax) in enumerate(zip(p.shape, spec)):
                    if ax is None and sz % fsdp_size == 0 and sz >= fsdp_size:
                        spec[d] = fsdp_axis
                        break
            specs.append(P(*spec))
        return specs

    # -- write-back ----------------------------------------------------------
    def update_params(self, p_arrs):
        for t, a in zip(self.params, p_arrs):
            t._data = a

    def update_buffers(self, b_arrs):
        for t, a in zip(self.buffers, b_arrs):
            t._data = a
