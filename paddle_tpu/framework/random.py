"""Global RNG.

Paddle has a global seed + per-device ``Generator`` with a stateful Philox
counter (reference: ``paddle/phi/core/generator.h``, SURVEY.md §2.1 — canonical
paths, unverified). JAX wants explicit keys; we hide a counter-based key tree
behind Paddle's ``seed()/get_rng_state()`` API (SURVEY.md §7.3 item 5): every
consumer calls :func:`next_key` which folds an incrementing counter into the
root key, so eager randomness is deterministic given ``seed()`` and call order.
"""
from __future__ import annotations

import threading

import jax
import numpy as np


class Generator:
    """Stateful generator: (root_key, counter). fold_in per draw.

    The root key is created LAZILY — a jax dispatch at import time would
    initialize the backend before user code can pick one (and makes even
    ``python -m paddle_tpu.distributed.launch`` touch the accelerator).
    """

    def __init__(self, seed_: int = 0):
        self.manual_seed(seed_)

    def manual_seed(self, seed_: int):
        self._seed = int(seed_)
        self._root = None          # built on first draw
        self._counter = 0
        return self

    def _root_key(self):
        if self._root is None:
            self._root = jax.random.key(self._seed)
        return self._root

    def next_key(self):
        with _lock:
            k = jax.random.fold_in(self._root_key(), self._counter)
            self._counter += 1
        return k

    def get_state(self):
        return {"seed": self._seed, "counter": self._counter}

    def set_state(self, state):
        self._seed = int(state["seed"])
        self._root = None
        self._counter = int(state["counter"])

    @property
    def initial_seed(self):
        return self._seed


_lock = threading.Lock()
_default_generator = Generator(np.random.randint(0, 2**31 - 1))


def seed(s: int) -> Generator:
    """paddle.seed — reset the global generator."""
    return _default_generator.manual_seed(s)


def default_generator() -> Generator:
    return _default_generator


def next_key():
    return _default_generator.next_key()


def get_rng_state():
    return [_default_generator.get_state()]


def set_rng_state(state):
    if isinstance(state, (list, tuple)):
        state = state[0]
    _default_generator.set_state(state)


def get_cuda_rng_state():  # API-compat alias (no CUDA on TPU build)
    return get_rng_state()


def set_cuda_rng_state(state):
    set_rng_state(state)
