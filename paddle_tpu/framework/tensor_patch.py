"""Attach ops as Tensor methods + operator dunders.

Mirrors upstream's monkey-patch scheme (``python/paddle/tensor/__init__.py``
``monkey_patch_tensor`` — SURVEY.md §2.2): tensor methods are the same
functions as the ``paddle.*`` free functions, with the tensor as first arg.
"""
from __future__ import annotations

import jax.numpy as jnp

from .core import Tensor
from .. import ops
from ..autograd.tape import apply


def _conv_idx(idx):
    if isinstance(idx, Tensor):
        return idx._data
    if isinstance(idx, tuple):
        return tuple(_conv_idx(i) for i in idx)
    if isinstance(idx, list):
        return jnp.asarray([i._data if isinstance(i, Tensor) else i for i in idx]) \
            if any(isinstance(i, Tensor) for i in idx) else jnp.asarray(idx)
    return idx


def _getitem(self, idx):
    idxc = _conv_idx(idx)
    return apply(lambda a: a[idxc], self, op_name="getitem")


def _setitem(self, idx, value):
    idxc = _conv_idx(idx)
    if isinstance(value, Tensor):
        out = apply(lambda a, v: a.at[idxc].set(v.astype(a.dtype)), self, value,
                    op_name="setitem")
    else:
        out = apply(lambda a: a.at[idxc].set(value), self, op_name="setitem")
    self._replace_(out._data, out._grad_node, out._out_idx)


def _swap(method):
    """out-of-place op -> in-place variant mutating self."""

    def inplace(self, *args, **kwargs):
        out = method(self, *args, **kwargs)
        return self._replace_(out._data, out._grad_node, out._out_idx)

    return inplace


def monkey_patch_tensor():
    T = Tensor
    # arithmetic dunders
    T.__add__ = lambda s, o: ops.add(s, o)
    T.__radd__ = lambda s, o: ops.add(s, o)
    T.__sub__ = lambda s, o: ops.subtract(s, o)
    T.__rsub__ = lambda s, o: ops.subtract(o, s) if isinstance(o, Tensor) \
        else apply(lambda a: o - a, s, op_name="rsub")
    T.__mul__ = lambda s, o: ops.multiply(s, o)
    T.__rmul__ = lambda s, o: ops.multiply(s, o)
    T.__truediv__ = lambda s, o: ops.divide(s, o)
    T.__rtruediv__ = lambda s, o: ops.divide(o, s) if isinstance(o, Tensor) \
        else apply(lambda a: o / a, s, op_name="rdiv")
    T.__floordiv__ = lambda s, o: ops.floor_divide(s, o)
    T.__rfloordiv__ = lambda s, o: ops.floor_divide(o, s) \
        if isinstance(o, Tensor) \
        else apply(lambda a: jnp.floor_divide(o, a), s,
                   op_name="rfloordiv")
    T.__dlpack__ = lambda s, **kw: s._data.__dlpack__(**kw)
    T.__dlpack_device__ = lambda s: s._data.__dlpack_device__()
    T.__mod__ = lambda s, o: ops.mod(s, o)
    T.__pow__ = lambda s, o: ops.pow(s, o)
    T.__rpow__ = lambda s, o: apply(lambda a: jnp.power(o, a), s, op_name="rpow")
    T.__matmul__ = lambda s, o: ops.matmul(s, o)
    T.__rmatmul__ = lambda s, o: ops.matmul(o, s)
    T.__neg__ = lambda s: ops.neg(s)
    T.__abs__ = lambda s: ops.abs(s)
    T.__invert__ = lambda s: ops.logical_not(s) if s.dtype == jnp.bool_ \
        else ops.bitwise_not(s)
    T.__and__ = lambda s, o: ops.logical_and(s, o) if s.dtype == jnp.bool_ \
        else ops.bitwise_and(s, o)
    T.__or__ = lambda s, o: ops.logical_or(s, o) if s.dtype == jnp.bool_ \
        else ops.bitwise_or(s, o)
    T.__xor__ = lambda s, o: ops.logical_xor(s, o) if s.dtype == jnp.bool_ \
        else ops.bitwise_xor(s, o)
    # comparisons (return Tensors, like paddle)
    T.__eq__ = lambda s, o: ops.equal(s, o)
    T.__ne__ = lambda s, o: ops.not_equal(s, o)
    T.__lt__ = lambda s, o: ops.less_than(s, o)
    T.__le__ = lambda s, o: ops.less_equal(s, o)
    T.__gt__ = lambda s, o: ops.greater_than(s, o)
    T.__ge__ = lambda s, o: ops.greater_equal(s, o)
    T.__getitem__ = _getitem
    T.__setitem__ = _setitem

    methods = """
        add subtract multiply divide floor_divide mod remainder pow maximum minimum
        fmax fmin atan2 lerp logaddexp equal not_equal greater_than greater_equal
        less_than less_equal logical_and logical_or logical_xor logical_not
        bitwise_and bitwise_or bitwise_xor bitwise_not
        exp expm1 log log2 log10 log1p sqrt rsqrt square abs sign neg reciprocal
        floor ceil round trunc frac sin cos tan asin acos atan sinh cosh tanh
        asinh acosh atanh erf erfinv sigmoid digamma lgamma clip scale stanh
        isnan isinf isfinite isclose allclose equal_all
        sum mean prod max min amax amin logsumexp std var median nanmedian
        quantile nansum nanmean count_nonzero cumsum cumprod cummax cummin
        logcumsumexp matmul mm bmm dot inner outer addmm kron cross trace t
        argmax argmin argsort sort topk kthvalue mode searchsorted bucketize
        reshape flatten squeeze unsqueeze transpose moveaxis swapaxes
        concat stack split chunk unbind unstack tile expand expand_as
        broadcast_to flip rot90 roll repeat_interleave pad cast
        take_along_axis put_along_axis index_select index_sample gather gather_nd
        scatter scatter_nd_add index_add index_put masked_select masked_fill
        tril triu
        masked_scatter where nonzero unique unique_consecutive
        norm dist histogram bincount increment lcm gcd heaviside hypot
        nan_to_num multiplex divide_no_nan tensordot
        all any take permute diff mv
        reshape_ squeeze_ unsqueeze_
        ldexp frexp sinc signbit isneginf isposinf isreal i0 i0e i1 i1e
        polygamma gammainc gammaincc multigammaln nanquantile renorm
        bitwise_left_shift bitwise_right_shift combinations clip_by_norm
        unflatten diagonal_scatter select_scatter slice_scatter index_fill
        tensor_split hsplit vsplit dsplit vander atleast_1d atleast_2d
        atleast_3d
        sgn cdist unfold trapezoid cumulative_trapezoid rank
        float_power vdot nanargmax nanargmin positive isin fliplr
        flipud index_copy view view_as
    """.split()
    for name in methods:
        fn = getattr(ops, name, None) or getattr(ops.linalg, name, None)
        if fn is not None and not hasattr(T, name):
            setattr(T, name, fn)

    # in-place variants derived from out-of-place ops
    for name in """add subtract multiply divide scale clip exp sqrt rsqrt
                   reciprocal floor ceil round abs sin cos tanh sigmoid neg
                   erfinv pow mod remainder lerp masked_fill index_put
                   put_along_axis index_add scatter tril triu""".split():
        fn = getattr(ops, name, None)
        if fn is not None and not hasattr(T, name + "_"):
            setattr(T, name + "_", _swap(fn))

    T.zero_ = _swap(lambda s: apply(lambda a: jnp.zeros_like(a), s, op_name="zero_"))
    T.fill_ = _swap(lambda s, v: apply(lambda a: jnp.full_like(a, v), s, op_name="fill_"))
    T.fill_diagonal_ = _swap(lambda s, v, offset=0, wrap=False: apply(
        lambda a: a.at[jnp.arange(min(a.shape[-2:])), jnp.arange(min(a.shape[-2:]))].set(v),
        s, op_name="fill_diagonal_"))
    T.uniform_ = lambda s, min=-1.0, max=1.0, seed=0: s._replace_(
        ops.uniform(s.shape, dtype=s.dtype, min=min, max=max)._data)
    # in-place distribution fills (reference Tensor.cauchy_/geometric_/
    # log_normal_) — framework-PRNG seeded
    def _fill_from(dist_builder):
        def fill(s, *a, **kw):
            d = dist_builder(*a, **kw)
            return s._replace_(
                d.sample(tuple(s.shape))._data.astype(s.dtype))
        return fill
    from ..distribution import Cauchy as _Cauchy, Geometric as _Geometric, \
        LogNormal as _LogNormal
    T.cauchy_ = _fill_from(lambda loc=0.0, scale=1.0, **k:
                           _Cauchy(loc, scale))
    T.geometric_ = _fill_from(lambda probs=0.5, **k: _Geometric(probs))
    T.log_normal_ = _fill_from(lambda mean=1.0, std=2.0, **k:
                               _LogNormal(mean, std))
    T.normal_ = lambda s, mean=0.0, std=1.0: s._replace_(
        (ops.randn(s.shape, dtype=s.dtype) * std + mean)._data)
    from ..framework import random as _prandom
    import jax as _jax

    def _bernoulli_(s, p=0.5):
        keep = _jax.random.bernoulli(_prandom.next_key(), p,
                                     tuple(s.shape))
        return s._replace_(keep.astype(s.dtype))

    T.bernoulli_ = _bernoulli_


monkey_patch_tensor()
