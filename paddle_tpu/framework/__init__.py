from .core import (  # noqa: F401
    Tensor, Parameter, EagerParamBase, to_tensor, Place, CPUPlace, TPUPlace,
    CUDAPlace, XPUPlace, set_device, get_device, current_place, device_count,
    is_compiled_with_cuda, is_compiled_with_xpu,
)
from .dtype import (  # noqa: F401
    bfloat16, float16, float32, float64, int8, int16, int32, int64, uint8,
    bool_, complex64, complex128, set_default_dtype, get_default_dtype,
    convert_dtype, dtype_name,
)
from .random import (  # noqa: F401
    seed, get_rng_state, set_rng_state, get_cuda_rng_state, set_cuda_rng_state,
    Generator, default_generator, next_key,
)
