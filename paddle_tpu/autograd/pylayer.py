"""PyLayer: user-defined forward/backward (reference: Paddle's
``python/paddle/autograd/py_layer.py`` — SURVEY.md §2.2).

The custom backward is spliced into the tape as a GradNode whose "vjp" calls
the user's ``backward`` staticmethod on Tensors.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from .tape import GradNode, is_grad_enabled, no_grad


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved

    def mark_not_inplace(self, *a):
        pass

    def mark_non_differentiable(self, *a):
        pass

    def set_materialize_grads(self, v):
        self.materialize_grads = v


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outputs, (list, tuple))
        out_list = [outputs] if single else list(outputs)

        diff_inputs = [a for a in args
                       if isinstance(a, Tensor) and not a.stop_gradient
                       and jnp.issubdtype(a.dtype, jnp.inexact)]
        if not is_grad_enabled() or not diff_inputs:
            return outputs

        out_meta = [(tuple(t._data.shape), t.dtype) for t in out_list]
        _, out_tree = jax.tree.flatten(out_list)

        def vjp_like(cotangents):
            cts = [Tensor(c) for c in cotangents]
            with no_grad():
                grads = cls.backward(ctx, *cts) if len(cts) > 1 \
                    else cls.backward(ctx, cts[0])
            grads = grads if isinstance(grads, (list, tuple)) else [grads]
            out = []
            for a, g in zip([a for a in args if isinstance(a, Tensor)], grads):
                if any(a is d for d in diff_inputs):
                    out.append(None if g is None else
                               (g._data if isinstance(g, Tensor) else jnp.asarray(g)))
            return tuple(out)

        edges = [(t, t._grad_node, t._out_idx) for t in diff_inputs]
        node = GradNode(vjp_like, edges, out_meta, out_tree, cls.__name__)
        for k, t in enumerate(out_list):
            if jnp.issubdtype(t.dtype, jnp.inexact):
                t.stop_gradient = False
                t._grad_node = node
                t._out_idx = k
        return outputs
