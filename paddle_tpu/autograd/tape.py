"""Imperative autograd tape over jax.vjp.

This is the TPU-native replacement for Paddle's eager autograd engine
(reference: ``paddle/fluid/eager/backward.cc`` — topological traversal with
dependency counting and grad accumulation; ``grad_node_info.h`` GradNode graph.
SURVEY.md §2.1/§3.1; canonical paths, unverified).

Every differentiable eager op goes through :func:`apply`: we run the op's pure
jax function under ``jax.vjp`` w.r.t. the inputs that require grad, and record
a :class:`GradNode` holding the vjp closure. ``Tensor.backward()`` replays the
node graph in reverse topological order with dependency counting, accumulating
leaf ``.grad`` exactly like the reference engine.
"""
from __future__ import annotations

import contextlib
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..framework import dtype as dtypes
from ..flags import flag as _flag

_FLOAT0 = jax.dtypes.float0

# ---------------------------------------------------------------------------
# grad mode
# ---------------------------------------------------------------------------

# THREAD-LOCAL, not process-global: the thread-rank simulator runs N
# ranks as threads, and each rank enters/leaves no_grad independently
# (every Optimizer.step is @no_grad). With a shared flag, two ranks'
# interleaved enter/exit could restore the OTHER rank's saved state and
# leave gradients disabled for the whole process (A on→off, B off→off,
# A →on, B →off: poisoned). Thread-local save/restore is race-free.
import threading as _grad_threading

_grad_mode = _grad_threading.local()


def is_grad_enabled() -> bool:
    return getattr(_grad_mode, "enabled", True)


def _set_grad_mode(mode: bool):
    _grad_mode.enabled = bool(mode)


def _push_grad_mode(mode: bool):
    # saved states live on a PER-THREAD stack, never on the context
    # instance: one @no_grad decorator instance is shared by every caller
    # of the function it wraps, so instance state would race across
    # threads the same way the old global flag did
    stack = getattr(_grad_mode, "stack", None)
    if stack is None:
        stack = _grad_mode.stack = []
    stack.append(is_grad_enabled())
    _set_grad_mode(mode)


def _pop_grad_mode():
    stack = getattr(_grad_mode, "stack", None)
    _set_grad_mode(stack.pop() if stack else True)


def set_grad_enabled(mode: bool):
    class _Ctx(contextlib.AbstractContextManager):
        def __init__(self, mode):
            _push_grad_mode(mode)

        def __exit__(self, *exc):
            _pop_grad_mode()
            return False

    return _Ctx(mode)


class no_grad(contextlib.ContextDecorator):
    """paddle.no_grad — context manager AND decorator."""

    def __enter__(self):
        _push_grad_mode(False)
        return self

    def __exit__(self, *exc):
        _pop_grad_mode()
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        _push_grad_mode(True)
        return self

    def __exit__(self, *exc):
        _pop_grad_mode()
        return False


# ---------------------------------------------------------------------------
# GradNode
# ---------------------------------------------------------------------------


class GradNode:
    """One recorded op. ``inputs`` are edges to the diff inputs captured at
    record time (tensor ref, producer node at record time, producer out idx) —
    captured eagerly so later in-place mutation of a tensor can't create a
    self-cycle."""

    __slots__ = ("vjp_fn", "edges", "out_meta", "out_tree", "name", "pure_fn",
                 "__weakref__")

    def __init__(self, vjp_fn, edges, out_meta, out_tree, name, pure_fn=None):
        self.vjp_fn = vjp_fn
        self.edges = edges          # list[(Tensor, GradNode|None, int)]
        self.out_meta = out_meta    # list[(shape, dtype)] flat output leaves
        self.out_tree = out_tree
        self.name = name
        self.pure_fn = pure_fn      # primal replay fn (higher-order grad)

    def __repr__(self):
        return f"<GradNode {self.name}>"


def _is_diff_tensor(t) -> bool:
    return (isinstance(t, Tensor) and not t.stop_gradient
            and jnp.issubdtype(t.dtype, jnp.inexact))


# hooks installed by other subsystems (amp, debugging, profiler)
_amp_cast_inputs = None
_nan_check = False
_profiler = None     # paddle_tpu.profiler.Profiler when recording

# telemetry observers: fn(op_name, seconds) called after every dispatch
# while installed (profiler.telemetry.enable_op_telemetry). Kept separate
# from _profiler so the metrics registry can watch ops without a Profiler
# window being open; the empty-list check keeps the off path free.
_op_observers: list = []

# activation observers: fn(op_name, out) called with every dispatch's
# OUTPUT while installed (profiler.tensor_stats activation abs-max).
# Separate from _op_observers (which only see timing) and from the
# single-slot _op_inspect (owned by auto_parallel completion); the
# empty-list check keeps the off path to one truthiness test.
_act_observers: list = []


def register_activation_observer(fn):
    if fn not in _act_observers:
        _act_observers.append(fn)
    return fn


def unregister_activation_observer(fn):
    if fn in _act_observers:
        _act_observers.remove(fn)

# callbacks fired once after a top-level backward() finishes (DataParallel
# grad sync uses this — the analogue of the reference reducer's
# post-backward allreduce flush, ``paddle/fluid/imperative/reducer.cc``).
# Thread-local: each simulated rank (distributed/simulator.py) registers and
# fires only its own callbacks.
import threading as _threading

_post_backward_tls = _threading.local()


def register_post_backward_callback(cb):
    lst = getattr(_post_backward_tls, "callbacks", None)
    if lst is None:
        lst = _post_backward_tls.callbacks = []
    lst.append(cb)
    return cb


def unregister_post_backward_callback(cb):
    lst = getattr(_post_backward_tls, "callbacks", None)
    if lst and cb in lst:
        lst.remove(cb)


# grad-ready callbacks: fn(tensor) fired DURING run_backward the moment a
# leaf tensor's gradient is final for the current backward (every reachable
# consumer of that leaf has been processed). This is the signal the
# ready-bucket comm scheduler (distributed/comm/bucketer.py) keys on to
# dispatch a bucket's collective while the rest of backward still runs —
# the analogue of the reference reducer's per-variable Hook
# (``reducer.cc::AddDistHook``), where post-backward callbacks above are
# the analogue of its finalize flush. Thread-local for the same reason:
# each simulated rank observes only its own backward.


def register_grad_ready_callback(cb):
    lst = getattr(_post_backward_tls, "ready_callbacks", None)
    if lst is None:
        lst = _post_backward_tls.ready_callbacks = []
    lst.append(cb)
    return cb


def unregister_grad_ready_callback(cb):
    lst = getattr(_post_backward_tls, "ready_callbacks", None)
    if lst and cb in lst:
        lst.remove(cb)


_op_inspect = [None]   # auto_parallel completion hook: (op_name, out) -> None


def apply(fn, *args, op_name: str | None = None, **kwargs):
    """Run pure-array function ``fn`` on (possibly) Tensor args; record a tape
    node if grad is enabled and any input requires grad. Returns Tensor(s)
    mirroring fn's output structure."""
    name = op_name or getattr(fn, "__name__", "op")
    _prof = _profiler if (_profiler is not None
                          and _profiler._recording) else None
    if _prof is not None or _op_observers:
        import time as _time
        _t0 = _time.perf_counter()
        try:
            out = _apply_inner(fn, name, args, kwargs)
        finally:
            _dt = _time.perf_counter() - _t0
            if _prof is not None:
                _prof._record_op(name, _dt)
            for _ob in _op_observers:
                _ob(name, _dt)
    else:
        out = _apply_inner(fn, name, args, kwargs)
    if _op_inspect[0] is not None:
        _op_inspect[0](name, out)
    if _act_observers:
        for _ob in _act_observers:
            _ob(name, out)
    return out


_FLAT_TYPES = (int, float, bool, str, bytes, type(None))
_FAST_ARG_TYPES = (Tensor,) + _FLAT_TYPES
_ARRAY_IMPL = []      # concrete jax array type, resolved on first dispatch


_AMP_STATE = [None]


def _amp_active():
    """Cheap AMP-enabled probe: the amp module installs its cast hook at
    import time, so hook-present != policy-active."""
    st = _AMP_STATE[0]
    if st is None:
        try:
            from ..amp import amp_state
        except Exception:
            return True    # unknown — take the general (safe) path
        st = _AMP_STATE[0] = amp_state()
    return st.enabled


def _apply_inner(fn, name, args, kwargs):
    # Fast path for the dominant dispatch shape (SURVEY §7.3 item 1:
    # dygraph per-op overhead): flat positional Tensor/scalar args, no
    # kwargs, no AMP recast, grads off or no diff inputs — skip the
    # pytree flatten/unflatten/map machinery entirely (~40% of the
    # no-grad dispatch cost measured round 4).
    if (not kwargs and not _nan_check
            and (_amp_cast_inputs is None or not _amp_active())
            and all(isinstance(a, _FAST_ARG_TYPES)
                    or isinstance(a, jax.Array) for a in args)):
        if not (is_grad_enabled()
                and any(_is_diff_tensor(a) for a in args)):
            out = fn(*(a._data if isinstance(a, Tensor) else a
                       for a in args))
            if not _ARRAY_IMPL:
                import jax.numpy as _jnp
                _ARRAY_IMPL.append(type(_jnp.zeros(())))
            if out.__class__ is _ARRAY_IMPL[0]:
                return Tensor(out)
            return jax.tree.map(lambda v: Tensor(v), out)
    # flatten args AND kwargs: Tensors passed by keyword unwrap (and
    # differentiate) exactly like positional ones — the reference API
    # accepts either form for every op
    leaves, treedef = jax.tree.flatten((list(args), dict(kwargs)),
                                       is_leaf=lambda x: isinstance(x, Tensor))
    if _amp_cast_inputs is not None:
        # cast policy applies to the flattened leaves so keyword Tensors
        # follow the same AMP dtype as positional ones
        leaves = _amp_cast_inputs(name, leaves)
    consts = [l._data if isinstance(l, Tensor) else l for l in leaves]
    diff_idx = [i for i, l in enumerate(leaves)
                if _is_diff_tensor(l)] if is_grad_enabled() else []

    if not diff_idx:
        c_args, c_kwargs = jax.tree.unflatten(treedef, consts)
        out = fn(*c_args, **c_kwargs)
        if _nan_check:
            _check_finite(out, name)
        return jax.tree.map(lambda v: Tensor(v), out)

    def pure(*arrs):
        cl = list(consts)
        for i, a in zip(diff_idx, arrs):
            cl[i] = a
        p_args, p_kwargs = jax.tree.unflatten(treedef, cl)
        return fn(*p_args, **p_kwargs)

    primals = [consts[i] for i in diff_idx]
    out_val, vjp_fn = jax.vjp(pure, *primals)
    if _nan_check:
        _check_finite(out_val, name)

    out_leaves, out_tree = jax.tree.flatten(out_val)
    out_meta = [(v.shape, v.dtype) for v in out_leaves]
    edges = [(leaves[i], leaves[i]._grad_node, leaves[i]._out_idx) for i in diff_idx]
    node = GradNode(vjp_fn, edges, out_meta, out_tree, name,
                    pure_fn=pure if _flag("FLAGS_enable_double_grad", True)
                    else None)

    wrapped = []
    for k, v in enumerate(out_leaves):
        t = Tensor(v)
        if jnp.issubdtype(v.dtype, jnp.inexact):
            t.stop_gradient = False
            t._grad_node = node
            t._out_idx = k
        wrapped.append(t)
    return jax.tree.unflatten(out_tree, wrapped)


def _check_finite(out, name):
    """FLAGS_check_nan_inf: per-op output scan, abort with op identity
    (reference: ``nan_inf_utils`` — SURVEY.md §5.2). Skipped under tracing."""
    for v in jax.tree.leaves(out):
        if isinstance(v, jax.core.Tracer):
            return
        if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.inexact):
            if not bool(jnp.all(jnp.isfinite(v))):
                raise FloatingPointError(f"NaN/Inf found in output of op '{name}'")


def defop(fn):
    """Decorator: pure-array fn -> eager Tensor op."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return apply(fn, *args, op_name=fn.__name__, **kwargs)

    wrapper.raw = fn
    return wrapper


# ---------------------------------------------------------------------------
# backward engine
# ---------------------------------------------------------------------------


def _zeros_cotangent(shape, dt):
    if jnp.issubdtype(dt, jnp.inexact):
        return jnp.zeros(shape, dt)
    return np.zeros(shape, _FLOAT0)


def _accum(a, b):
    return b if a is None else a + b


def poison_next_leaf_grad():
    """Fault-injection hook (``distributed.fault`` ``nan:`` directives):
    arm a one-shot NaN poison on THIS thread — the first leaf gradient
    finalized by the next accumulate-mode backward gets a NaN written
    into its first element, before grad hooks, ``.grad`` accumulation
    and the grad-ready callbacks observe it (so the comm bucketer and
    the numerics sentinel both see the poisoned value, exactly like a
    real numerics blow-up). Thread-local: in the thread-rank simulator
    only the targeted rank's backward is affected."""
    _post_backward_tls.nan_poison = getattr(
        _post_backward_tls, "nan_poison", 0) + 1


def _poison_nan(g):
    arr = jnp.asarray(g)
    flat = arr.reshape(-1)
    flat = flat.at[0].set(jnp.nan)
    return flat.reshape(arr.shape)


def flip_bit_next_leaf_grad():
    """Fault-injection hook (``distributed.fault`` ``bitflip:``
    directives): arm a one-shot single-bit flip on THIS thread — the
    first leaf gradient FINALIZED by the next accumulate-mode backward
    gets its element 0's lowest mantissa bit flipped. Unlike the NaN
    poison (applied pre-hooks so the comm bucketer spreads it), the
    flip lands at the very END of backward, AFTER the post-backward
    callbacks — i.e. after the overlap scheduler's synced-grad
    write-back — so in data-parallel training the corruption stays
    rank-LOCAL: exactly the silent 1-ulp hardware fault the determinism
    ledger's cross-rank digest comparison exists to catch (a NaN would
    trip the numerics sentinel; a low-bit flip trips nothing else).
    Thread-local, consumed once."""
    _post_backward_tls.bit_poison = getattr(
        _post_backward_tls, "bit_poison", 0) + 1


def _flip_low_bit(g):
    """XOR the lowest bit of element 0's bit pattern (f16/bf16/f32/f64)."""
    from jax import lax
    arr = jnp.asarray(g)
    flat = arr.reshape(-1)
    uint = {2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}[arr.dtype.itemsize]
    bits = lax.bitcast_convert_type(flat[:1], uint)
    flipped = lax.bitcast_convert_type(bits ^ jnp.ones((1,), uint), arr.dtype)
    return flat.at[0].set(flipped[0]).reshape(arr.shape)


def _run_hooks(t: Tensor, g):
    if t._grad_hooks:
        for h in list(t._grad_hooks):
            r = h(Tensor(g) if not isinstance(g, Tensor) else g)
            if r is not None:
                g = r._data if isinstance(r, Tensor) else r
    return g


def run_backward(tensors, grads=None, retain_graph=False, accumulate=True,
                 capture: dict | None = None):
    """Reverse-topological replay with dependency counting (mirrors
    ``egr::Backward``). ``capture``: id(tensor) -> slot, used by paddle.grad;
    when given + accumulate=False, grads are written there instead of ``.grad``."""
    grads = grads or [None] * len(tensors)
    # grad-ready firing is an accumulate-mode feature (paddle.grad capture
    # never owns .grad finality); snapshot the list so callbacks that
    # unregister themselves mid-backward don't skew iteration
    ready_cbs = (list(getattr(_post_backward_tls, "ready_callbacks", ()))
                 if accumulate else [])
    # armed fault-injection poison (poison_next_leaf_grad) — one getattr
    # on the off path, consumed by the first finalized leaf grad below
    nan_poison = (getattr(_post_backward_tls, "nan_poison", 0)
                  if accumulate else 0)
    # armed bit flip (flip_bit_next_leaf_grad): applied to the FIRST
    # finalized leaf at the very end of backward (post write-back), so
    # leaf-finality tracking runs even with no ready callbacks
    bit_poison = (getattr(_post_backward_tls, "bit_poison", 0)
                  if accumulate else 0)
    track_final = bool(ready_cbs) or bool(bit_poison)
    first_final: list = []   # [leaf Tensor] — finalize order, first only
    seed_leaves = []   # root tensors that got their grad in the seed loop
    # ---- seed
    seeds = []  # (node, out_idx, grad) or leaf accumulation
    for t, g in zip(tensors, grads):
        if not isinstance(t, Tensor):
            raise TypeError("backward inputs must be Tensors")
        if g is None:
            g = jnp.ones(t._data.shape, t.dtype)
        elif isinstance(g, Tensor):
            g = g._data
        else:
            g = jnp.asarray(g, t.dtype)
        if t._grad_node is None:
            if capture is not None and id(t) in capture:
                capture[id(t)] = _accum(capture[id(t)], g)
            elif accumulate and not t.stop_gradient:
                t.grad = Tensor(_accum(t.grad._data if t.grad is not None else None, g))
                if track_final:
                    seed_leaves.append(t)
        else:
            if accumulate and t._retain_grads and not t.stop_gradient:
                # a non-leaf backward root with retain_grads gets the seed grad
                t.grad = Tensor(_accum(t.grad._data if t.grad is not None else None, g))
            seeds.append((t._grad_node, t._out_idx, g))

    if not seeds:
        for t in seed_leaves:
            for cb in ready_cbs:
                cb(t)
        return

    # ---- collect reachable graph
    nodes = set()
    node_objs = {}
    stack = [s[0] for s in seeds]
    while stack:
        n = stack.pop()
        if id(n) in nodes:
            continue
        nodes.add(id(n))
        node_objs[id(n)] = n
        for (_, prod, _) in n.edges:
            if prod is not None and not isinstance(prod, _SeveredEdge) \
                    and id(prod) not in nodes:
                stack.append(prod)

    # ---- dependency (consumer) counts among reachable nodes
    consumers = {nid: 0 for nid in nodes}
    for nid in nodes:
        for (_, prod, _) in node_objs[nid].edges:
            if prod is not None and id(prod) in nodes:
                consumers[id(prod)] += 1

    # ---- leaf finality counts (grad-ready hooks): a leaf's gradient is
    # final once every reachable edge pointing at it has been processed —
    # only then may the ready callbacks (comm overlap) read t.grad
    leaf_pending: dict[int, int] = {}
    if track_final:
        for nid in nodes:
            for (t, prod, _) in node_objs[nid].edges:
                if prod is None and not t.stop_gradient:
                    leaf_pending[id(t)] = leaf_pending.get(id(t), 0) + 1
        for t in seed_leaves:
            if id(t) not in leaf_pending:
                if not first_final:
                    first_final.append(t)
                for cb in ready_cbs:
                    cb(t)

    out_grads: dict[int, dict[int, Any]] = {nid: {} for nid in nodes}
    for node, idx, g in seeds:
        d = out_grads[id(node)]
        d[idx] = _accum(d.get(idx), g)

    ready = [node_objs[nid] for nid, c in consumers.items() if c == 0]
    processed = 0
    while ready:
        n = ready.pop()
        processed += 1
        if n.vjp_fn is None:
            raise RuntimeError(
                f"Trying to backward through node {n.name} a second time; "
                "set retain_graph=True if you need to.")
        got = out_grads[id(n)]
        cot_leaves = [got.get(i, _zeros_cotangent(sh, dt))
                      for i, (sh, dt) in enumerate(n.out_meta)]
        cotangent = jax.tree.unflatten(n.out_tree, cot_leaves)
        in_grads = n.vjp_fn(cotangent)
        if not retain_graph:
            n.vjp_fn = None
            n.pure_fn = None    # free the replay closure's pinned inputs too
        out_grads[id(n)] = None  # free
        for (t, prod, pidx), g in zip(n.edges, in_grads):
            # finality bookkeeping counts the edge even when its cotangent
            # is symbolically zero (None/float0) — the leaf is "done" with
            # this consumer either way
            final = False
            if track_final and prod is None and not t.stop_gradient:
                c = leaf_pending[id(t)] - 1
                leaf_pending[id(t)] = c
                final = c == 0
                if final and not first_final:
                    first_final.append(t)
            if g is None or (hasattr(g, "dtype") and g.dtype == _FLOAT0):
                if final:
                    for cb in ready_cbs:
                        cb(t)
                continue
            if nan_poison and prod is None and not t.stop_gradient:
                g = _poison_nan(g)
                nan_poison = 0
                _post_backward_tls.nan_poison = max(
                    getattr(_post_backward_tls, "nan_poison", 1) - 1, 0)
            g = _run_hooks(t, g)
            is_capture = capture is not None and id(t) in capture
            if is_capture:
                capture[id(t)] = _accum(capture[id(t)], g)
            if prod is None or t._retain_grads:
                if accumulate and not t.stop_gradient and not is_capture:
                    t.grad = Tensor(_accum(t.grad._data if t.grad is not None else None, g))
            if final:
                for cb in ready_cbs:
                    cb(t)
            if prod is not None and id(prod) in nodes:
                d = out_grads[id(prod)]
                d[pidx] = _accum(d.get(pidx), g)
                consumers[id(prod)] -= 1
                if consumers[id(prod)] == 0:
                    ready.append(prod)

    if accumulate:
        for cb in list(getattr(_post_backward_tls, "callbacks", ())):
            cb()
    if bit_poison and first_final:
        # bit flip lands AFTER the post-backward flush (overlap
        # scheduler's synced-grad write-back): rank-local corruption of
        # the grad the optimizer is about to consume
        t = first_final[0]
        if t.grad is not None:
            t.grad = Tensor(_flip_low_bit(t.grad._data))
            _post_backward_tls.bit_poison = max(
                getattr(_post_backward_tls, "bit_poison", 1) - 1, 0)


def _graph_grad(outputs, inputs, grad_outputs, allow_unused):
    """``paddle.grad(create_graph=True)`` — differentiable gradients
    (reference: the eager double-grad node tier,
    ``paddle/fluid/eager/api/generated`` higher-order paths).

    TPU-native design: instead of building grad-of-grad node classes per
    op, the recorded subgraph between ``inputs`` and ``outputs`` is
    REPLAYED as one pure jax function (each GradNode stored its primal
    ``pure_fn`` at record time), and the gradient is ``jax.vjp`` of that
    replay — recorded on the tape as a single op via ``apply``, so the
    result connects to ``inputs`` AND to every requires-grad leaf the
    subgraph touches (weights under a gradient penalty), and third-order
    grads fall out for free (jax differentiates the replay's vjp)."""
    # duplicates in ``inputs`` share one replay variable; every occurrence
    # gets the same grad in the result (reference behavior)
    uniq_inputs, input_pos = [], {}
    for t in inputs:
        if id(t) not in input_pos:
            input_pos[id(t)] = len(uniq_inputs)
            uniq_inputs.append(t)
    orig_inputs, inputs = inputs, uniq_inputs

    # ---- collect the full ancestor graph of outputs (no cut at inputs:
    # an input may sit in another input's ancestry — reference semantics
    # give it the full chain-rule grad through that path; a truly detached
    # injection point has no recorded ancestry in the first place)
    node_set, node_objs = set(), {}
    stack = [t._grad_node for t in outputs if t._grad_node is not None]
    while stack:
        n = stack.pop()
        if id(n) in node_set:
            continue
        node_set.add(id(n))
        node_objs[id(n)] = n
        if n.pure_fn is None:
            if n.vjp_fn is None:
                raise RuntimeError(
                    f"Trying to backward through node {n.name} a second "
                    "time; set retain_graph=True if you need to.")
            if not _flag("FLAGS_enable_double_grad", True):
                raise RuntimeError(
                    "create_graph=True needs FLAGS_enable_double_grad=True "
                    "(it was disabled, so primal replay fns were not "
                    "recorded on this graph)")
            raise NotImplementedError(
                f"create_graph=True through op '{n.name}' (a PyLayer or "
                "custom node without a primal replay fn) is not supported; "
                "detach() the subgraph above it if its grads are not needed")
        for (_, prod, _) in n.edges:
            if prod is not None and not isinstance(prod, _SeveredEdge) \
                    and id(prod) not in node_set:
                stack.append(prod)

    # forward topological order: producers before consumers
    indeg = {nid: 0 for nid in node_set}
    dependents = {nid: [] for nid in node_set}
    for nid in node_set:
        for (_, prod, _) in node_objs[nid].edges:
            if prod is not None and id(prod) in node_set:
                indeg[nid] += 1
                dependents[id(prod)].append(nid)
    ready = [nid for nid, d in indeg.items() if d == 0]
    order = []
    while ready:
        nid = ready.pop()
        order.append(node_objs[nid])
        for dep in dependents[nid]:
            indeg[dep] -= 1
            if indeg[dep] == 0:
                ready.append(dep)

    # other differentiable tensors feeding the kept subgraph (weights
    # etc.): grads must flow to them through the replay too
    extra, seen = [], set(input_pos)
    for n in order:
        for (t, prod, _) in n.edges:
            if id(t) in seen:
                continue
            seen.add(id(t))
            if _is_diff_tensor(t) and (prod is None or id(prod) not in node_set):
                extra.append(t)
    n_in, n_extra = len(inputs), len(extra)
    extra_pos = {id(t): i for i, t in enumerate(extra)}

    def replay(in_arrs, extra_arrs):
        env = {}

        def chained(t, p_val):
            """Input with a live producer: value = replayed p, gradient
            flows BOTH to the injected variable and through the chain
            (torch/paddle grad semantics for an input that is an
            ancestor of another input's consumer path)."""
            v = in_arrs[input_pos[id(t)]]
            return p_val + (v - jax.lax.stop_gradient(v))

        def val_of(t, prod, pidx):
            if id(t) in input_pos:
                if prod is not None and id(prod) in node_set:
                    return chained(t, env[(id(prod), pidx)])
                return in_arrs[input_pos[id(t)]]
            if id(t) in extra_pos:
                return extra_arrs[extra_pos[id(t)]]
            if prod is not None and id(prod) in node_set:
                return env[(id(prod), pidx)]
            return t._data

        for n in order:
            args = [val_of(*e) for e in n.edges]
            outs = n.pure_fn(*args)
            for k, leaf in enumerate(jax.tree.leaves(outs)):
                env[(id(n), k)] = leaf
        res = []
        for t in outputs:
            if t._grad_node is not None and id(t._grad_node) in node_set:
                p_val = env[(id(t._grad_node), t._out_idx)]
                res.append(chained(t, p_val) if id(t) in input_pos else p_val)
            elif id(t) in input_pos:
                res.append(in_arrs[input_pos[id(t)]])
            else:
                res.append(t._data)           # constant w.r.t. inputs
        return tuple(res)

    seed_from = []      # grad_outputs that are themselves differentiable
    seeds = []
    for i, t in enumerate(outputs):
        g = grad_outputs[i] if grad_outputs is not None else None
        if g is None:
            seeds.append(jnp.ones(t._data.shape, t.dtype))
        elif isinstance(g, Tensor):
            seeds.append(g)
            if _is_diff_tensor(g):
                seed_from.append(i)
        else:
            # same coercion run_backward applies to raw seeds
            seeds.append(jnp.asarray(g, t.dtype))

    def G(*arrs):
        in_arrs = list(arrs[:n_in])
        extra_arrs = list(arrs[n_in:n_in + n_extra])
        seed_arrs = list(arrs[n_in + n_extra:])
        cur = {i: a for i, a in zip(seed_from, seed_arrs)}
        cots = tuple(cur.get(i, s._data if isinstance(s, Tensor) else s)
                     for i, s in enumerate(seeds))
        _, vjp = jax.vjp(lambda ia: replay(ia, extra_arrs), in_arrs)
        (gs,) = vjp(cots)
        return tuple(gs)

    # Inputs with a replayed producer must enter the outer tape as LEAF
    # edges (producer severed): the replay already internalized their
    # upstream chain (``chained``) — keeping the original edge would
    # double-count the path when the returned grads are differentiated
    # again, while a detached copy would orphan d(grad)/d(input). We
    # temporarily clear ``_grad_node`` around the recording so the edge
    # captures the ORIGINAL tensor, leaf-like.
    sever = [t for t in inputs
             if t._grad_node is not None and id(t._grad_node) in node_set]
    saved_nodes = [(t, t._grad_node, t._out_idx) for t in sever]
    try:
        for t in sever:
            t._grad_node = _SEVERED
        args = (list(inputs) + extra + [seeds[i] for i in seed_from])
        out = apply(G, *args, op_name="grad_replay")
    finally:
        for t, n, k in saved_nodes:
            t._grad_node = n
            t._out_idx = k
    # jax.vjp returns a cotangent for every input; true "unused" shows as a
    # symbolically-zero None only pre-materialization. Match the reference's
    # allow_unused contract via graph reachability instead.
    used_ids = ({id(t) for n in order for (t, _, _) in n.edges}
                | {id(t) for t in outputs})
    result = []
    for t in orig_inputs:
        g = out[input_pos[id(t)]]
        if id(t) not in used_ids:
            if not allow_unused:
                raise ValueError(
                    "One of the differentiated Tensors appears unused in the "
                    "graph; set allow_unused=True to return None for it.")
            result.append(None)
        else:
            result.append(g)
    return result


class _SeveredEdge:
    """Marker producer for grad_replay edges whose upstream chain was
    internalized by the replay: run_backward neither traverses past it
    nor treats the tensor as a leaf (no spurious ``.grad`` writes on
    non-leaf inputs)."""
    __slots__ = ()


_SEVERED = _SeveredEdge()


class InTraceAutogradNeeded(RuntimeError):
    """Raised when paddle.grad runs inside a @to_static trace that was
    captured without tape recording; StaticFunction catches this and
    re-traces with ``swap_state(enable_grad=True)``."""


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """paddle.grad — return grads of outputs w.r.t. inputs without touching
    ``.grad``. ``create_graph=True`` returns differentiable grads (see
    ``_graph_grad``)."""
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if (not is_grad_enabled()
            and all(t._grad_node is None for t in outputs
                    if isinstance(t, Tensor))):
        from ..jit import api as jit_api
        if jit_api._TRACING[0]:
            if jit_api._STATIC_ACTIVE[0]:
                raise InTraceAutogradNeeded(
                    "paddle.grad inside @to_static needs tape-in-trace "
                    "recording")
            raise RuntimeError(
                "paddle.grad called under a functional trace with no "
                "recorded graph (grad is disabled inside FunctionalModule/"
                "swap_state); compute gradients with jax.grad over the "
                "functional view, or call paddle.grad eagerly")
    if grad_outputs is not None and not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]
    if create_graph:
        return _graph_grad(outputs, inputs, grad_outputs, allow_unused)
    capture = {id(t): None for t in inputs}
    retain = True if retain_graph is None else retain_graph
    run_backward(list(outputs), grad_outputs, retain_graph=retain,
                 accumulate=False, capture=capture)
    result = []
    for t in inputs:
        g = capture[id(t)]
        if g is None:
            if not allow_unused:
                raise ValueError(
                    "One of the differentiated Tensors appears unused in the "
                    "graph; set allow_unused=True to return None for it.")
            result.append(None)
        else:
            result.append(Tensor(g))
    return result
