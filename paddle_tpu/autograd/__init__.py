"""paddle.autograd equivalent (reference: ``python/paddle/autograd/`` —
SURVEY.md §2.2)."""
from .tape import (  # noqa: F401
    no_grad, enable_grad, is_grad_enabled, set_grad_enabled, grad,
    run_backward, apply, defop, GradNode,
)
from .pylayer import PyLayer, PyLayerContext  # noqa: F401


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward"""
    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is not None and not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    run_backward(list(tensors), grad_tensors, retain_graph=retain_graph)


def jacobian(ys, xs, batch_axis=None):
    """reference: ``paddle.autograd.jacobian`` (3.0 dygraph flavor takes
    computed ys; common usage passes (func, xs) via incubate). This
    facade accepts the (func, xs) form and delegates to the dense
    incubate implementation."""
    if callable(ys):
        from ..incubate.autograd import Jacobian
        return Jacobian(ys, xs if isinstance(xs, (list, tuple)) else [xs],
                        is_batched=batch_axis is not None)
    raise NotImplementedError(
        "paddle.autograd.jacobian over already-computed outputs needs the "
        "functional form: pass the function as the first argument "
        "(jacobian(func, xs)), or use paddle.incubate.autograd.Jacobian")


def hessian(ys, xs, batch_axis=None):
    """See :func:`jacobian` — functional (func, xs) form."""
    if callable(ys):
        from ..incubate.autograd import Hessian
        return Hessian(ys, xs if isinstance(xs, (list, tuple)) else [xs],
                       is_batched=batch_axis is not None)
    raise NotImplementedError(
        "paddle.autograd.hessian needs the functional form "
        "(hessian(func, xs)); see paddle.incubate.autograd.Hessian")
