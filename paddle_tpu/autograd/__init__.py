"""paddle.autograd equivalent (reference: ``python/paddle/autograd/`` —
SURVEY.md §2.2)."""
from .tape import (  # noqa: F401
    no_grad, enable_grad, is_grad_enabled, set_grad_enabled, grad,
    run_backward, apply, defop, GradNode,
)
from .pylayer import PyLayer, PyLayerContext  # noqa: F401


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward"""
    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is not None and not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    run_backward(list(tensors), grad_tensors, retain_graph=retain_graph)
