"""paddle.callbacks (reference: ``python/paddle/hapi/callbacks.py`` —
Callback base + ModelCheckpoint / EarlyStopping / LRScheduler /
ProgBarLogger / ReduceLROnPlateau wired into ``Model.fit``; SURVEY.md §2.2
"hapi"). VisualDLCallback is out of the TPU build (VisualDL is an external
package) — ``LogWriterCallback`` writes plain JSONL instead.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRScheduler", "LogWriterCallback", "ReduceLROnPlateau", "VisualDL",
           "TelemetryCallback"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = dict(params or {})

    # hook surface (reference names)
    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks, model=None, params=None):
        self.callbacks = list(callbacks or [])
        for c in self.callbacks:
            c.set_model(model)
            c.set_params(params)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *a: self._call(name, *a)
        raise AttributeError(name)

    @property
    def stop_training(self):
        return any(getattr(c, "stop_training", False)
                   for c in self.callbacks)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=10, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            logs = logs or {}
            msg = " ".join(f"{k}: {v:.4f}" if isinstance(v, float) else
                           f"{k}: {v}" for k, v in logs.items())
            rate = (time.time() - self._t0) / (step + 1)
            print(f"Epoch {self._epoch} step {step} {msg} ({rate:.3f}s/step)")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, f"epoch_{epoch}"))

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        # reference semantics: baseline seeds `best` — the metric must beat
        # it within `patience` evals or training stops
        self.best = baseline
        self.wait = 0
        self.stop_training = False
        self.save_dir = None

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(np.asarray(cur).reshape(-1)[0])
        if self._better(cur):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.save_dir and self.model:
                self.model.save(os.path.join(self.save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (by_step or by_epoch)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        assert by_step != by_epoch
        self.by_step = by_step

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if not self.by_step and s is not None:
            s.step()


class LogWriterCallback(Callback):
    """JSONL metrics writer (VisualDL stand-in). File opens lazily on
    train begin so one instance survives multiple fit() calls."""

    def __init__(self, log_dir="./vdl_log"):
        super().__init__()
        self.log_dir = log_dir
        self._f = None

    def on_train_begin(self, logs=None):
        if self._f is None or self._f.closed:
            os.makedirs(self.log_dir, exist_ok=True)
            self._f = open(os.path.join(self.log_dir, "metrics.jsonl"), "a")

    def on_train_batch_end(self, step, logs=None):
        if self._f is None or self._f.closed:
            return
        rec = {"step": step}
        for k, v in (logs or {}).items():
            try:
                rec[k] = float(v)
            except (TypeError, ValueError):
                pass
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()

    def on_train_end(self, logs=None):
        if self._f is not None and not self._f.closed:
            self._f.close()


class ReduceLROnPlateau(Callback):
    """Reduce optimizer LR when a monitored metric plateaus (reference:
    ``paddle.callbacks.ReduceLROnPlateau``)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0.0):
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.mode = mode
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self._best = None
        self._wait = 0
        self._cooldown_ctr = 0

    def _better(self, cur, best):
        if self.mode == "max" or (self.mode == "auto"
                                  and "acc" in self.monitor):
            return cur > best + self.min_delta
        return cur < best - self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple)) else cur)
        if self._cooldown_ctr > 0:
            # hold period after a reduction: track the best but never
            # count toward patience
            self._cooldown_ctr -= 1
            self._wait = 0
            if self._best is None or self._better(cur, self._best):
                self._best = cur
            return
        if self._best is None or self._better(cur, self._best):
            self._best = cur
            self._wait = 0
            return
        self._wait += 1
        if self._wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is not None:
                lr = opt.get_lr() if hasattr(opt, "get_lr") else opt._learning_rate
                new_lr = max(lr * self.factor, self.min_lr)
                if hasattr(opt, "set_lr"):
                    opt.set_lr(new_lr)
                if self.verbose:
                    print(f"ReduceLROnPlateau: lr -> {new_lr:.3e}")
            self._wait = 0
            self._cooldown_ctr = self.cooldown


class TelemetryCallback(Callback):
    """Training-side bridge into the unified metrics registry
    (``paddle.profiler.metrics()``): per-step wall time, throughput,
    MFU and device memory high-water — the step-breakdown substrate
    every perf PR measures against.

    Records per train batch:

    * ``paddle_train_step_seconds`` (histogram) + ``paddle_train_steps_total``
    * ``paddle_train_tokens_per_sec`` / ``paddle_train_samples_per_sec``
      gauges, when ``tokens_per_batch`` / ``samples_per_batch`` are given
    * ``paddle_train_mfu_ratio`` gauge, when ``step_flops`` is given
      (:class:`profiler.mfu.MFUMonitor` accounting — achieved / peak)
    * ``paddle_device_live_bytes_high_water`` gauge (PJRT allocator peak)

    While training runs, per-op dispatch telemetry is enabled on the
    autograd tape (``paddle_op_dispatch_total{op=...}``), so one fit()
    populates the tape, io, and train layers of the registry together.

    ``track_phases=True`` (default) additionally enables the
    step-phase layer (``profiler.step_phase``) for the duration of the
    fit — forward/backward/comm-wait/optimizer spans land in
    ``paddle_step_phase_seconds{phase}`` and every phase boundary
    becomes a memory-timeline sample point (the memory timeline itself
    stays gated on ``PADDLE_MEMORY`` / ``profiler.memory.enable()``).
    """

    def __init__(self, step_flops=None, tokens_per_batch=None,
                 samples_per_batch=None, chip=None, n_chips=1,
                 track_memory=True, track_ops=True, track_phases=True):
        super().__init__()
        self.step_flops = step_flops
        self.tokens_per_batch = tokens_per_batch
        self.samples_per_batch = samples_per_batch
        self.chip = chip
        self.n_chips = n_chips
        self.track_memory = track_memory
        self.track_ops = track_ops
        self.track_phases = track_phases
        self._m = None
        self._monitor = None
        self._t_batch = None
        self._flight = None
        self._phases_enabled_here = False

    def _metrics(self):
        if self._m is None:
            from .profiler.telemetry import get_registry
            r = get_registry()
            self._m = {
                "step": r.histogram("paddle_train_step_seconds",
                                    "train-loop wall time per step"),
                "steps": r.counter("paddle_train_steps_total",
                                   "train steps completed"),
                "tok_s": r.gauge("paddle_train_tokens_per_sec",
                                 "rolling training token throughput"),
                "smp_s": r.gauge("paddle_train_samples_per_sec",
                                 "rolling training sample throughput"),
                "mfu": r.gauge("paddle_train_mfu_ratio",
                               "achieved FLOP/s / peak FLOP/s"),
                "mem": r.gauge("paddle_device_live_bytes_high_water",
                               "peak device bytes in use seen during "
                               "training"),
            }
        return self._m

    def on_train_begin(self, logs=None):
        self._metrics()
        from .profiler import flight_recorder
        self._flight = flight_recorder
        if self.track_ops:
            from .profiler.telemetry import enable_op_telemetry
            enable_op_telemetry()
        if self.track_phases:
            from .profiler import step_phase
            # enable only for this fit (mirror track_ops); remember
            # whether WE turned it on so a knob-enabled layer survives
            self._phases_enabled_here = not step_phase.is_enabled()
            step_phase.enable()
        if self.step_flops:
            from .profiler.mfu import MFUMonitor, chip_kind
            chip = self.chip
            if chip is None:
                try:
                    chip = chip_kind()
                except Exception:
                    chip = "cpu"
            self._monitor = MFUMonitor(self.step_flops, chip=chip,
                                       n_chips=self.n_chips)

    def on_train_end(self, logs=None):
        if self.track_ops:
            from .profiler.telemetry import disable_op_telemetry
            disable_op_telemetry()
        if self._phases_enabled_here:
            from .profiler import step_phase
            step_phase.disable()
            self._phases_enabled_here = False

    def on_train_batch_begin(self, step, logs=None):
        self._t_batch = time.perf_counter()
        from .profiler import step_phase
        step_phase.step_begin(step)

    def on_train_batch_end(self, step, logs=None):
        if self._t_batch is None:
            return
        if self._flight is not None:
            # flight-recorder liveness: the watchdog's "is training still
            # stepping" signal (no-op bool check when the recorder is off)
            self._flight.heartbeat()
        dt = max(time.perf_counter() - self._t_batch, 1e-9)
        m = self._metrics()
        m["step"].observe(dt)
        m["steps"].inc()
        if self.tokens_per_batch:
            m["tok_s"].set(self.tokens_per_batch / dt)
        if self.samples_per_batch:
            m["smp_s"].set(self.samples_per_batch / dt)
        if self._monitor is not None:
            self._monitor.step(tokens=self.tokens_per_batch or 0)
            m["mfu"].set(self._monitor.mfu())
        if self.track_memory:
            try:
                from .device.memory import max_memory_allocated
                m["mem"].set_max(max_memory_allocated())
            except Exception:
                pass      # backend without allocator stats
        from .profiler import step_phase
        step_phase.step_end()


class VisualDL(Callback):
    """reference: ``paddle.callbacks.VisualDL`` — VisualDL is explicitly
    not rebuilt (SURVEY.md §7.4); this stub raises with guidance."""

    def __init__(self, log_dir="vdl_log"):
        raise NotImplementedError(
            "VisualDL is not in the TPU build (SURVEY.md §7.4); use the "
            "profiler's chrome-trace export or metric callbacks instead")
