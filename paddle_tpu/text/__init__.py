"""paddle.text (reference: ``python/paddle/text/`` — dataset loaders +
``ViterbiDecoder``; SURVEY.md §2.2 "Metrics/text/audio").

Datasets that require downloads are out of the zero-egress build (they raise
with the cache path, like paddle.utils.download); the compute pieces —
Viterbi decoding for CRF-style sequence labeling — are implemented TPU-style
with a ``lax.scan`` over time steps (static shapes, vectorized over batch).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..autograd.tape import apply

__all__ = ["ViterbiDecoder", "viterbi_decode"]


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True):
    """Viterbi decoding: potentials [B, T, N] emission scores, transition
    [N(+2), N(+2)] (+2 = BOS/EOS rows when include_bos_eos_tag). Returns
    (scores [B], paths [B, T]) — reference ``viterbi_decode`` contract.
    """

    def fn(emis, trans, *rest):
        b, t, n = emis.shape
        lens = rest[0] if rest else jnp.full((b,), t, jnp.int32)
        if include_bos_eos_tag:
            # rows/cols n..n+1 are BOS/EOS; strip to the N real tags with
            # start scores = trans[BOS, :N], stop scores = trans[:N, EOS]
            start = trans[n, :n]
            stop = trans[:n, n + 1]
            trans_core = trans[:n, :n]
        else:
            start = jnp.zeros((n,), emis.dtype)
            stop = jnp.zeros((n,), emis.dtype)
            trans_core = trans

        alpha0 = emis[:, 0] + start[None, :]                  # [B, N]

        def step(carry, et):
            alpha, tstep = carry
            e, = et
            # scores[b, i, j] = alpha[b, i] + trans[i, j]
            scores = alpha[:, :, None] + trans_core[None]
            best_prev = jnp.argmax(scores, axis=1)            # [B, N]
            new_alpha = jnp.max(scores, axis=1) + e           # [B, N]
            # positions past each sequence's length keep old alpha
            active = (tstep < lens)[:, None]
            new_alpha = jnp.where(active, new_alpha, alpha)
            return (new_alpha, tstep + 1), best_prev

        (alpha, _), backptrs = jax.lax.scan(
            step, (alpha0, jnp.ones((b,), jnp.int32)),
            (jnp.moveaxis(emis[:, 1:], 1, 0),))
        alpha = alpha + stop[None, :]
        last_tag = jnp.argmax(alpha, axis=-1)                 # [B]
        score = jnp.max(alpha, axis=-1)

        # backtrack (scan in reverse over backptrs)
        def back(carry, bp_t):
            tag, tstep = carry
            prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
            # only move while within the sequence
            active = tstep < lens
            tag = jnp.where(active, prev, tag)
            return (tag, tstep - 1), tag

        (first_tag, _), path_rev = jax.lax.scan(
            back, (last_tag, jnp.full((b,), t - 1, jnp.int32)),
            backptrs, reverse=True)
        path = jnp.concatenate([path_rev, last_tag[None]], axis=0)
        return score, jnp.moveaxis(path, 0, 1).astype(jnp.int64)

    args = (potentials, transition_params) + \
        ((lengths,) if lengths is not None else ())
    return apply(fn, *args, op_name="viterbi_decode")


class ViterbiDecoder:
    """paddle.text.ViterbiDecoder layer form."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


# ---------------------------------------------------------------------------
# datasets (reference: ``python/paddle/text/datasets/`` — UCIHousing, Imdb,
# Imikolov, Movielens, Conll05, WMT14/16). Zero-egress build: each dataset
# resolves from the shared local cache (~/.cache/paddle/dataset/<name>,
# utils.dataset_cache_path) and raises with the expected path
# on a miss; UCIHousing additionally offers a deterministic synthetic mode
# for tests/examples.
# ---------------------------------------------------------------------------

class _CachedDataset:
    """Base for reference text datasets in the zero-egress build."""

    _filename = None      # expected file under the cache dir

    def __init__(self, data_file=None, mode="train", **kw):
        import os
        self.mode = mode
        if data_file is None:
            from ..utils import dataset_cache_path
            data_file = dataset_cache_path(self._filename)
        if not os.path.exists(data_file):
            raise IOError(
                f"{type(self).__name__}: no network egress in the TPU "
                f"build — place the reference archive at {data_file}")
        self.data_file = data_file
        self._load()

    def _load(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        return self.samples[i]


class UCIHousing(_CachedDataset):
    """Boston-housing regression rows (13 features, 1 target). Pass
    ``synthetic=N`` to generate a deterministic stand-in dataset."""

    _filename = "housing.data"

    def __init__(self, data_file=None, mode="train", synthetic=None, **kw):
        import numpy as np
        if synthetic:
            rng = np.random.RandomState(0)
            feats = rng.rand(int(synthetic), 13).astype("float32")
            w = rng.rand(13, 1).astype("float32")
            tgt = feats @ w + 0.1 * rng.rand(int(synthetic), 1)
            self.mode = mode
            self.samples = [(feats[i], tgt[i].astype("float32"))
                            for i in range(int(synthetic))]
            return
        super().__init__(data_file, mode, **kw)

    def _load(self):
        import numpy as np
        raw = np.loadtxt(self.data_file).astype("float32")
        split = int(0.8 * len(raw))
        rows = raw[:split] if self.mode == "train" else raw[split:]
        mu, sigma = raw[:, :13].mean(0), raw[:, :13].std(0) + 1e-8
        self.samples = [(((r[:13] - mu) / sigma).astype("float32"),
                         r[13:14].astype("float32")) for r in rows]


class Imdb(_CachedDataset):
    """IMDB sentiment archive (aclImdb_v1.tar.gz)."""

    _filename = "aclImdb_v1.tar.gz"

    _vocab_cache = {}     # data_file -> word_idx (one archive pass)

    def _load(self):
        import re
        from collections import Counter
        import tarfile
        any_pat = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        tok_pat = re.compile(r"[a-z']+")
        # frequency-sorted vocab over the WHOLE archive so train and test
        # instances share word ids (reference build_dict); cached per
        # archive so the second split skips the full decode pass
        cached = Imdb._vocab_cache.get(self.data_file)
        freq = Counter() if cached is None else None
        mode_docs = []
        with tarfile.open(self.data_file) as tf:
            for m in tf.getmembers():
                match = any_pat.match(m.name)
                if not match:
                    continue
                in_mode = match.group(1) == self.mode
                if freq is None and not in_mode:
                    continue            # vocab cached: only read our split
                text = tf.extractfile(m).read().decode(
                    "utf-8", "ignore").lower()
                toks = tok_pat.findall(text)
                if freq is not None:
                    freq.update(toks)
                if in_mode:
                    mode_docs.append(
                        (toks, 0 if match.group(2) == "pos" else 1))
        if cached is None:
            cached = {w: i for i, (w, _) in enumerate(
                sorted(freq.items(), key=lambda kv: (-kv[1], kv[0])))}
            Imdb._vocab_cache[self.data_file] = cached
        self.word_idx = cached
        self.samples = [([self.word_idx[t] for t in toks], lab)
                        for toks, lab in mode_docs]


class Imikolov(_CachedDataset):
    """PTB language-model n-grams (simple-examples.tgz)."""

    _filename = "simple-examples.tgz"

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", **kw):
        self.data_type = data_type
        self.window_size = window_size
        super().__init__(data_file, mode, **kw)

    def _load(self):
        import tarfile
        with tarfile.open(self.data_file) as tf:
            # vocab ALWAYS from the train file (first-occurrence order) so
            # train/test instances share word ids (reference build_dict)
            train_text = tf.extractfile(
                "./simple-examples/data/ptb.train.txt").read().decode(
                "utf-8")
            self.word_idx = {"<eos>": 0, "<unk>": 1}
            for line in train_text.splitlines():
                for t in line.split():
                    self.word_idx.setdefault(t, len(self.word_idx))
            if self.mode == "train":
                text = train_text
            else:
                text = tf.extractfile(
                    f"./simple-examples/data/ptb.{self.mode}.txt"
                ).read().decode("utf-8")
        unk = self.word_idx["<unk>"]
        sents = []
        for line in text.splitlines():
            toks = line.split() + ["<eos>"]
            sents.append([self.word_idx.get(t, unk) for t in toks])
        if str(self.data_type).upper() == "SEQ":
            # reference SEQ mode: (src, trg) = (l[:-1], l[1:]) per sentence
            self.samples = [(s[:-1], s[1:]) for s in sents if len(s) > 1]
        else:
            out = []
            n = self.window_size
            for s in sents:
                for i in range(len(s) - n + 1):
                    out.append(tuple(s[i:i + n]))
            self.samples = out
