"""paddle.text (reference: ``python/paddle/text/`` — dataset loaders +
``ViterbiDecoder``; SURVEY.md §2.2 "Metrics/text/audio").

Datasets that require downloads are out of the zero-egress build (they raise
with the cache path, like paddle.utils.download); the compute pieces —
Viterbi decoding for CRF-style sequence labeling — are implemented TPU-style
with a ``lax.scan`` over time steps (static shapes, vectorized over batch).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..autograd.tape import apply

__all__ = ["ViterbiDecoder", "viterbi_decode"]


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True):
    """Viterbi decoding: potentials [B, T, N] emission scores, transition
    [N(+2), N(+2)] (+2 = BOS/EOS rows when include_bos_eos_tag). Returns
    (scores [B], paths [B, T]) — reference ``viterbi_decode`` contract.
    """

    def fn(emis, trans, *rest):
        b, t, n = emis.shape
        lens = rest[0] if rest else jnp.full((b,), t, jnp.int32)
        if include_bos_eos_tag:
            # rows/cols n..n+1 are BOS/EOS; strip to the N real tags with
            # start scores = trans[BOS, :N], stop scores = trans[:N, EOS]
            start = trans[n, :n]
            stop = trans[:n, n + 1]
            trans_core = trans[:n, :n]
        else:
            start = jnp.zeros((n,), emis.dtype)
            stop = jnp.zeros((n,), emis.dtype)
            trans_core = trans

        alpha0 = emis[:, 0] + start[None, :]                  # [B, N]

        def step(carry, et):
            alpha, tstep = carry
            e, = et
            # scores[b, i, j] = alpha[b, i] + trans[i, j]
            scores = alpha[:, :, None] + trans_core[None]
            best_prev = jnp.argmax(scores, axis=1)            # [B, N]
            new_alpha = jnp.max(scores, axis=1) + e           # [B, N]
            # positions past each sequence's length keep old alpha
            active = (tstep < lens)[:, None]
            new_alpha = jnp.where(active, new_alpha, alpha)
            return (new_alpha, tstep + 1), best_prev

        (alpha, _), backptrs = jax.lax.scan(
            step, (alpha0, jnp.ones((b,), jnp.int32)),
            (jnp.moveaxis(emis[:, 1:], 1, 0),))
        alpha = alpha + stop[None, :]
        last_tag = jnp.argmax(alpha, axis=-1)                 # [B]
        score = jnp.max(alpha, axis=-1)

        # backtrack (scan in reverse over backptrs)
        def back(carry, bp_t):
            tag, tstep = carry
            prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
            # only move while within the sequence
            active = tstep < lens
            tag = jnp.where(active, prev, tag)
            return (tag, tstep - 1), tag

        (first_tag, _), path_rev = jax.lax.scan(
            back, (last_tag, jnp.full((b,), t - 1, jnp.int32)),
            backptrs, reverse=True)
        path = jnp.concatenate([path_rev, last_tag[None]], axis=0)
        return score, jnp.moveaxis(path, 0, 1).astype(jnp.int64)

    args = (potentials, transition_params) + \
        ((lengths,) if lengths is not None else ())
    return apply(fn, *args, op_name="viterbi_decode")


class ViterbiDecoder:
    """paddle.text.ViterbiDecoder layer form."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


# ---------------------------------------------------------------------------
# datasets (reference: ``python/paddle/text/datasets/`` — UCIHousing, Imdb,
# Imikolov, Movielens, Conll05, WMT14/16). Zero-egress build: each dataset
# resolves from the shared local cache (~/.cache/paddle/dataset/<name>,
# utils.dataset_cache_path) and raises with the expected path
# on a miss; UCIHousing additionally offers a deterministic synthetic mode
# for tests/examples.
# ---------------------------------------------------------------------------

class _CachedDataset:
    """Base for reference text datasets in the zero-egress build."""

    _filename = None      # expected file under the cache dir

    def __init__(self, data_file=None, mode="train", **kw):
        import os
        self.mode = mode
        if data_file is None:
            from ..utils import dataset_cache_path
            data_file = dataset_cache_path(self._filename)
        if not os.path.exists(data_file):
            raise IOError(
                f"{type(self).__name__}: no network egress in the TPU "
                f"build — place the reference archive at {data_file}")
        self.data_file = data_file
        self._load()

    def _load(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        return self.samples[i]


class UCIHousing(_CachedDataset):
    """Boston-housing regression rows (13 features, 1 target). Pass
    ``synthetic=N`` to generate a deterministic stand-in dataset."""

    _filename = "housing.data"

    def __init__(self, data_file=None, mode="train", synthetic=None, **kw):
        import numpy as np
        if synthetic:
            rng = np.random.RandomState(0)
            feats = rng.rand(int(synthetic), 13).astype("float32")
            w = rng.rand(13, 1).astype("float32")
            tgt = feats @ w + 0.1 * rng.rand(int(synthetic), 1)
            self.mode = mode
            self.samples = [(feats[i], tgt[i].astype("float32"))
                            for i in range(int(synthetic))]
            return
        super().__init__(data_file, mode, **kw)

    def _load(self):
        import numpy as np
        raw = np.loadtxt(self.data_file).astype("float32")
        split = int(0.8 * len(raw))
        rows = raw[:split] if self.mode == "train" else raw[split:]
        mu, sigma = raw[:, :13].mean(0), raw[:, :13].std(0) + 1e-8
        self.samples = [(((r[:13] - mu) / sigma).astype("float32"),
                         r[13:14].astype("float32")) for r in rows]


class Imdb(_CachedDataset):
    """IMDB sentiment archive (aclImdb_v1.tar.gz)."""

    _filename = "aclImdb_v1.tar.gz"

    _vocab_cache = {}     # data_file -> word_idx (one archive pass)

    def _load(self):
        import re
        from collections import Counter
        import tarfile
        any_pat = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        tok_pat = re.compile(r"[a-z']+")
        # frequency-sorted vocab over the WHOLE archive so train and test
        # instances share word ids (reference build_dict); cached per
        # archive so the second split skips the full decode pass
        cached = Imdb._vocab_cache.get(self.data_file)
        freq = Counter() if cached is None else None
        mode_docs = []
        with tarfile.open(self.data_file) as tf:
            for m in tf.getmembers():
                match = any_pat.match(m.name)
                if not match:
                    continue
                in_mode = match.group(1) == self.mode
                if freq is None and not in_mode:
                    continue            # vocab cached: only read our split
                text = tf.extractfile(m).read().decode(
                    "utf-8", "ignore").lower()
                toks = tok_pat.findall(text)
                if freq is not None:
                    freq.update(toks)
                if in_mode:
                    mode_docs.append(
                        (toks, 0 if match.group(2) == "pos" else 1))
        if cached is None:
            cached = {w: i for i, (w, _) in enumerate(
                sorted(freq.items(), key=lambda kv: (-kv[1], kv[0])))}
            Imdb._vocab_cache[self.data_file] = cached
        self.word_idx = cached
        self.samples = [([self.word_idx[t] for t in toks], lab)
                        for toks, lab in mode_docs]


class Imikolov(_CachedDataset):
    """PTB language-model n-grams (simple-examples.tgz)."""

    _filename = "simple-examples.tgz"

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", **kw):
        self.data_type = data_type
        self.window_size = window_size
        super().__init__(data_file, mode, **kw)

    def _load(self):
        import tarfile
        with tarfile.open(self.data_file) as tf:
            # vocab ALWAYS from the train file (first-occurrence order) so
            # train/test instances share word ids (reference build_dict)
            train_text = tf.extractfile(
                "./simple-examples/data/ptb.train.txt").read().decode(
                "utf-8")
            self.word_idx = {"<eos>": 0, "<unk>": 1}
            for line in train_text.splitlines():
                for t in line.split():
                    self.word_idx.setdefault(t, len(self.word_idx))
            if self.mode == "train":
                text = train_text
            else:
                text = tf.extractfile(
                    f"./simple-examples/data/ptb.{self.mode}.txt"
                ).read().decode("utf-8")
        unk = self.word_idx["<unk>"]
        sents = []
        for line in text.splitlines():
            toks = line.split() + ["<eos>"]
            sents.append([self.word_idx.get(t, unk) for t in toks])
        if str(self.data_type).upper() == "SEQ":
            # reference SEQ mode: (src, trg) = (l[:-1], l[1:]) per sentence
            self.samples = [(s[:-1], s[1:]) for s in sents if len(s) > 1]
        else:
            out = []
            n = self.window_size
            for s in sents:
                for i in range(len(s) - n + 1):
                    out.append(tuple(s[i:i + n]))
            self.samples = out


class Movielens(_CachedDataset):
    """MovieLens-1M ratings (reference ``paddle.text.Movielens`` —
    ``ml-1m.zip`` with ``ratings.dat``/``users.dat``/``movies.dat``,
    ``::``-separated). Samples: (user_id, gender_id, age_id,
    occupation_id, movie_id, category_ids, title_ids, rating)."""

    _filename = "ml-1m.zip"

    AGES = [1, 18, 25, 35, 45, 50, 56]

    def _load(self):
        import zipfile
        with zipfile.ZipFile(self.data_file) as z:
            root = "ml-1m/"
            names = z.namelist()
            if root + "ratings.dat" not in names:
                root = next((n[:-len("ratings.dat")] for n in names
                             if n.endswith("ratings.dat")), "")

            def lines(name):
                return z.read(root + name).decode(
                    "latin-1").strip().splitlines()

            users = {}
            for ln in lines("users.dat"):
                uid, gender, age, occ, _zip = ln.split("::")
                users[int(uid)] = (0 if gender == "M" else 1,
                                   self.AGES.index(int(age)), int(occ))
            cats, words = {}, {}
            movies = {}
            for ln in lines("movies.dat"):
                mid, title, genres = ln.split("::")
                cat_ids = [cats.setdefault(c, len(cats))
                           for c in genres.split("|")]
                tw = [words.setdefault(w, len(words))
                      for w in title.lower().split()]
                movies[int(mid)] = (cat_ids, tw)
            n = 0
            self.samples = []
            for ln in lines("ratings.dat"):
                uid, mid, rating, _ts = ln.split("::")
                uid, mid = int(uid), int(mid)
                if uid not in users or mid not in movies:
                    continue
                # reference split: 9:1 train/test round-robin
                is_test = n % 10 == 9
                n += 1
                if (self.mode == "test") != is_test:
                    continue
                g, a, o = users[uid]
                c, tw = movies[mid]
                self.samples.append((uid, g, a, o, mid, c, tw,
                                     float(rating)))
        self.categories_dict = cats
        self.movie_title_dict = words


class _WMTBase(_CachedDataset):
    """Shared WMT en↔de/fr pair loader: archives hold parallel line files;
    samples are (src_ids, trg_ids_with_bos, trg_ids_with_eos) like the
    reference's trainer feed. Vocab is frequency-sorted per language with
    <s>, <e>, <unk> reserved."""

    _src_suffix = None
    _trg_suffix = None

    BOS, EOS, UNK = 0, 1, 2

    def _build_vocab(self, lines, size):
        from collections import Counter
        freq = Counter()
        for ln in lines:
            freq.update(ln.split())
        keep = [w for w, _ in sorted(freq.items(),
                                     key=lambda kv: (-kv[1], kv[0]))]
        vocab = {"<s>": self.BOS, "<e>": self.EOS, "<unk>": self.UNK}
        for w in keep[:max(size - 3, 0)]:
            vocab[w] = len(vocab)
        return vocab

    def _pairs_from_tar(self):
        import tarfile
        src_lines, trg_lines = [], []
        want = self.mode  # train/test/dev naming inside the archives
        with tarfile.open(self.data_file) as tf:
            members = {m.name: m for m in tf.getmembers() if m.isfile()}
            src_name = next((n for n in sorted(members)
                             if want in n and n.endswith(self._src_suffix)),
                            None)
            trg_name = next((n for n in sorted(members)
                             if want in n and n.endswith(self._trg_suffix)),
                            None)
            if src_name is None or trg_name is None:
                raise IOError(
                    f"{type(self).__name__}: no '{want}' *{self._src_suffix}"
                    f"/*{self._trg_suffix} pair inside {self.data_file}")
            src_lines = tf.extractfile(members[src_name]).read().decode(
                "utf-8", "ignore").strip().splitlines()
            trg_lines = tf.extractfile(members[trg_name]).read().decode(
                "utf-8", "ignore").strip().splitlines()
        return src_lines, trg_lines

    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang=None, **kw):
        self._src_size = src_dict_size
        self._trg_size = trg_dict_size
        super().__init__(data_file, mode, **kw)

    def _load(self):
        src_lines, trg_lines = self._pairs_from_tar()
        if self.mode == "train":
            vs, vt = src_lines, trg_lines
        else:
            # vocab ALWAYS from the train pair so train/test share word
            # ids (same contract as Imdb/Imikolov above)
            saved = self.mode
            self.mode = "train"
            try:
                vs, vt = self._pairs_from_tar()
            finally:
                self.mode = saved
        self.src_dict = self._build_vocab(vs, self._src_size)
        self.trg_dict = self._build_vocab(vt, self._trg_size)

        def ids(ln, vocab):
            return [vocab.get(w, self.UNK) for w in ln.split()]

        self.samples = []
        for s, t in zip(src_lines, trg_lines):
            ti = ids(t, self.trg_dict)
            self.samples.append((ids(s, self.src_dict),
                                 [self.BOS] + ti, ti + [self.EOS]))


class WMT14(_WMTBase):
    """reference ``paddle.text.WMT14`` (en→fr)."""

    _filename = "wmt14.tgz"
    _src_suffix = ".en"
    _trg_suffix = ".fr"


class WMT16(_WMTBase):
    """reference ``paddle.text.WMT16`` (en↔de multi-lingual archive)."""

    _filename = "wmt16.tar.gz"
    _src_suffix = ".en"
    _trg_suffix = ".de"

    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en", **kw):
        if lang == "de":
            self._src_suffix, self._trg_suffix = ".de", ".en"
        super().__init__(data_file, mode, src_dict_size, trg_dict_size, **kw)


class Conll05st(_CachedDataset):
    """reference ``paddle.text.Conll05st`` — semantic role labeling rows.
    Expects the test split's column files (words / props) inside the
    archive; samples are (words, predicate, labels) id lists."""

    _filename = "conll05st-tests.tar.gz"

    def _load(self):
        import tarfile
        with tarfile.open(self.data_file) as tf:
            members = {m.name: m for m in tf.getmembers() if m.isfile()}
            w_name = next((n for n in sorted(members) if "words" in n), None)
            p_name = next((n for n in sorted(members) if "props" in n), None)
            if w_name is None or p_name is None:
                raise IOError(f"Conll05st: words/props files not found in "
                              f"{self.data_file}")
            import gzip
            def read(name):
                raw = tf.extractfile(members[name]).read()
                if name.endswith(".gz"):
                    raw = gzip.decompress(raw)
                return raw.decode("utf-8", "ignore")
            sents, cur_w, cur_p = [], [], []
            for wln, pln in zip(read(w_name).splitlines(),
                                read(p_name).splitlines()):
                if not wln.strip():
                    if cur_w:
                        sents.append((cur_w, cur_p))
                    cur_w, cur_p = [], []
                    continue
                cur_w.append(wln.strip().lower())
                cur_p.append(pln.split())
            if cur_w:
                sents.append((cur_w, cur_p))
        # props format: col 0 = verb lemma or '-', cols 1..P = one label
        # column per predicate — ONE sample per predicate, tagged with
        # the predicate's token index
        raw = []
        for words, prows in sents:
            pred_rows = [i for i, pr in enumerate(prows) if pr[0] != "-"]
            n_pred = max(len(pr) for pr in prows) - 1
            for k in range(n_pred):
                labels = [pr[1 + k] if len(pr) > 1 + k else "*"
                          for pr in prows]
                pred_idx = pred_rows[k] if k < len(pred_rows) else 0
                raw.append((words, pred_idx, labels))
        self.word_dict = {w: i for i, w in enumerate(
            sorted({w for s, _, _ in raw for w in s}))}
        self.label_dict = {l: i for i, l in enumerate(
            sorted({l for _, _, ls in raw for l in ls}))}
        self.samples = [([self.word_dict[w] for w in s], p,
                         [self.label_dict[l] for l in ls])
                        for s, p, ls in raw]


Conll05 = Conll05st
