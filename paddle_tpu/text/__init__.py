"""paddle.text (reference: ``python/paddle/text/`` — dataset loaders +
``ViterbiDecoder``; SURVEY.md §2.2 "Metrics/text/audio").

Datasets that require downloads are out of the zero-egress build (they raise
with the cache path, like paddle.utils.download); the compute pieces —
Viterbi decoding for CRF-style sequence labeling — are implemented TPU-style
with a ``lax.scan`` over time steps (static shapes, vectorized over batch).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..autograd.tape import apply

__all__ = ["ViterbiDecoder", "viterbi_decode"]


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True):
    """Viterbi decoding: potentials [B, T, N] emission scores, transition
    [N(+2), N(+2)] (+2 = BOS/EOS rows when include_bos_eos_tag). Returns
    (scores [B], paths [B, T]) — reference ``viterbi_decode`` contract.
    """

    def fn(emis, trans, *rest):
        b, t, n = emis.shape
        lens = rest[0] if rest else jnp.full((b,), t, jnp.int32)
        if include_bos_eos_tag:
            # rows/cols n..n+1 are BOS/EOS; strip to the N real tags with
            # start scores = trans[BOS, :N], stop scores = trans[:N, EOS]
            start = trans[n, :n]
            stop = trans[:n, n + 1]
            trans_core = trans[:n, :n]
        else:
            start = jnp.zeros((n,), emis.dtype)
            stop = jnp.zeros((n,), emis.dtype)
            trans_core = trans

        alpha0 = emis[:, 0] + start[None, :]                  # [B, N]

        def step(carry, et):
            alpha, tstep = carry
            e, = et
            # scores[b, i, j] = alpha[b, i] + trans[i, j]
            scores = alpha[:, :, None] + trans_core[None]
            best_prev = jnp.argmax(scores, axis=1)            # [B, N]
            new_alpha = jnp.max(scores, axis=1) + e           # [B, N]
            # positions past each sequence's length keep old alpha
            active = (tstep < lens)[:, None]
            new_alpha = jnp.where(active, new_alpha, alpha)
            return (new_alpha, tstep + 1), best_prev

        (alpha, _), backptrs = jax.lax.scan(
            step, (alpha0, jnp.ones((b,), jnp.int32)),
            (jnp.moveaxis(emis[:, 1:], 1, 0),))
        alpha = alpha + stop[None, :]
        last_tag = jnp.argmax(alpha, axis=-1)                 # [B]
        score = jnp.max(alpha, axis=-1)

        # backtrack (scan in reverse over backptrs)
        def back(carry, bp_t):
            tag, tstep = carry
            prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
            # only move while within the sequence
            active = tstep < lens
            tag = jnp.where(active, prev, tag)
            return (tag, tstep - 1), tag

        (first_tag, _), path_rev = jax.lax.scan(
            back, (last_tag, jnp.full((b,), t - 1, jnp.int32)),
            backptrs, reverse=True)
        path = jnp.concatenate([path_rev, last_tag[None]], axis=0)
        return score, jnp.moveaxis(path, 0, 1).astype(jnp.int64)

    args = (potentials, transition_params) + \
        ((lengths,) if lengths is not None else ())
    return apply(fn, *args, op_name="viterbi_decode")


class ViterbiDecoder:
    """paddle.text.ViterbiDecoder layer form."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
