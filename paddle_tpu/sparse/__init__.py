"""paddle.sparse (reference: ``python/paddle/sparse/`` — COO/CSR tensors
over ``paddle/phi/kernels/sparse/``; SURVEY.md §2.2).

TPU-native: backed by ``jax.experimental.sparse`` BCOO/BCSR — XLA lowers the
sparse contractions to gather/scatter + dense tiles (TPUs have no native
sparse MXU path, same as the reference's cuSPARSE fallback tier). Dense
operands stay differentiable through the tape; sparse values are
differentiable through ``values()``-preserving elementwise ops.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..framework.core import Tensor
from ..framework import dtype as dtypes
from ..autograd.tape import apply

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
    "SparseCsrTensor", "add", "multiply", "matmul", "masked_matmul", "relu",
    "is_sparse", "nn",
    # elementwise value ops (pattern-preserving)
    "sin", "tan", "asin", "atan", "sinh", "tanh", "asinh", "atanh",
    "sqrt", "square", "abs", "pow", "neg", "expm1", "log1p", "cast",
    "rad2deg", "deg2rad", "isnan",
    # binary / reduction / structure
    "subtract", "divide", "sum", "transpose", "reshape", "coalesce",
    "is_same_shape", "mask_as", "slice", "mv", "addmm",
]


class SparseCooTensor:
    """COO sparse tensor (wraps BCOO). ``indices`` [ndim, nnz], ``values``
    [nnz] — reference layout."""

    def __init__(self, bcoo):
        self._m = bcoo

    # -- construction -------------------------------------------------------
    @property
    def shape(self):
        return list(self._m.shape)

    @property
    def dtype(self):
        return self._m.dtype

    @property
    def nnz(self):
        return int(self._m.nse)

    def indices(self):
        return Tensor(jnp.swapaxes(self._m.indices, 0, 1))

    def values(self):
        return Tensor(self._m.data)

    def to_dense(self):
        return Tensor(self._m.todense())

    def to_sparse_csr(self):
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(self._m))

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def coalesce(self):
        return SparseCooTensor(self._m.sum_duplicates())

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={dtypes.dtype_name(self.dtype)})")


class SparseCsrTensor:
    def __init__(self, bcsr):
        self._m = bcsr

    @property
    def shape(self):
        return list(self._m.shape)

    @property
    def dtype(self):
        return self._m.dtype

    @property
    def nnz(self):
        return int(self._m.nse)

    def crows(self):
        return Tensor(self._m.indptr)

    def cols(self):
        return Tensor(self._m.indices)

    def values(self):
        return Tensor(self._m.data)

    def to_dense(self):
        return Tensor(self._m.todense())

    def to_sparse_coo(self, sparse_dim=None):
        return SparseCooTensor(self._m.to_bcoo())

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={dtypes.dtype_name(self.dtype)})")


def _as_array(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x)


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    idx = np.asarray(indices if not isinstance(indices, Tensor)
                     else indices.numpy())
    vals = _as_array(values)
    if dtype is not None:
        vals = vals.astype(dtypes.convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(i.max()) + 1 for i in idx)
    bcoo = jsparse.BCOO((vals, jnp.asarray(idx.T)), shape=tuple(shape))
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, **kw):
    vals = _as_array(values)
    if dtype is not None:
        vals = vals.astype(dtypes.convert_dtype(dtype))
    bcsr = jsparse.BCSR((vals, _as_array(cols).astype(jnp.int32),
                         _as_array(crows).astype(jnp.int32)),
                        shape=tuple(shape))
    return SparseCsrTensor(bcsr)


def is_sparse(x):
    return isinstance(x, (SparseCooTensor, SparseCsrTensor))


def _coo(x):
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    return x


# -- ops --------------------------------------------------------------------

def add(x, y):
    if is_sparse(x) and is_sparse(y):
        xm, ym = _coo(x)._m, _coo(y)._m
        # sum via dense-free concat of coordinates
        data = jnp.concatenate([xm.data, ym.data])
        idx = jnp.concatenate([xm.indices, ym.indices], axis=0)
        m = jsparse.BCOO((data, idx), shape=xm.shape).sum_duplicates(
            nse=xm.nse + ym.nse)
        return SparseCooTensor(m)
    if is_sparse(x):
        return Tensor(x.to_dense()._data + _as_array(y))
    return Tensor(_as_array(x) + y.to_dense()._data)


def multiply(x, y):
    if is_sparse(x) and not is_sparse(y):
        xm = _coo(x)._m
        dense_vals = xm.todense() * _as_array(y)
        m = jsparse.bcoo_fromdense(dense_vals, nse=xm.nse)
        return SparseCooTensor(m)
    if is_sparse(x) and is_sparse(y):
        return SparseCooTensor(jsparse.bcoo_multiply_sparse(
            _coo(x)._m, _coo(y)._m))
    return multiply(y, x)


def matmul(x, y):
    """sparse @ dense → dense (differentiable w.r.t. the dense operand)."""
    if is_sparse(x):
        xm = _coo(x)._m

        def fn(d):
            return xm @ d

        return apply(fn, y if isinstance(y, Tensor) else Tensor(y),
                     op_name="sparse_matmul")
    if is_sparse(y):
        ym = _coo(y)._m

        def fn(d):
            return jsparse.bcoo_dot_general(
                ym, d, dimension_numbers=(((0,), (d.ndim - 2,)), ((), ())))

        # x @ sparse == (sparse^T @ x^T)^T for 2-D; keep simple via dense
        return apply(lambda d: d @ ym.todense(),
                     x if isinstance(x, Tensor) else Tensor(x),
                     op_name="sparse_matmul")
    from ..ops import math as pmath
    return pmath.matmul(x, y)


def masked_matmul(x, y, mask):
    """(x @ y) sampled at mask's sparsity pattern (reference sddmm)."""
    xm = _as_array(x)
    ym = _as_array(y)
    mm = _coo(mask)._m
    rows = mm.indices[:, 0]
    cols = mm.indices[:, 1]
    vals = jnp.einsum("nd,nd->n", xm[rows], ym[:, cols].T)
    return SparseCooTensor(jsparse.BCOO((vals, mm.indices), shape=mm.shape))


def relu(x):
    m = _coo(x)._m
    return SparseCooTensor(jsparse.BCOO((jnp.maximum(m.data, 0), m.indices),
                                        shape=m.shape))


# -- elementwise value ops (reference: paddle/phi/kernels/sparse/unary_*):
# pattern-preserving maps over the stored values only -------------------------

def _unary(x, vfn):
    m = _coo(x)._m
    out = SparseCooTensor(jsparse.BCOO((vfn(m.data), m.indices),
                                       shape=m.shape))
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) else out


def sin(x, name=None):
    return _unary(x, jnp.sin)


def tan(x, name=None):
    return _unary(x, jnp.tan)


def asin(x, name=None):
    return _unary(x, jnp.arcsin)


def atan(x, name=None):
    return _unary(x, jnp.arctan)


def sinh(x, name=None):
    return _unary(x, jnp.sinh)


def tanh(x, name=None):
    return _unary(x, jnp.tanh)


def asinh(x, name=None):
    return _unary(x, jnp.arcsinh)


def atanh(x, name=None):
    return _unary(x, jnp.arctanh)


def sqrt(x, name=None):
    return _unary(x, jnp.sqrt)


def square(x, name=None):
    return _unary(x, jnp.square)


def abs(x, name=None):
    return _unary(x, jnp.abs)


def pow(x, factor, name=None):
    return _unary(x, lambda v: v ** factor)


def neg(x, name=None):
    return _unary(x, jnp.negative)


def expm1(x, name=None):
    return _unary(x, jnp.expm1)


def log1p(x, name=None):
    return _unary(x, jnp.log1p)


def rad2deg(x, name=None):
    return _unary(x, jnp.rad2deg)


def deg2rad(x, name=None):
    return _unary(x, jnp.deg2rad)


def isnan(x, name=None):
    return _unary(x, jnp.isnan)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    m = _coo(x)._m
    data = m.data if value_dtype is None else \
        m.data.astype(dtypes.convert_dtype(value_dtype))
    idx = m.indices if index_dtype is None else \
        m.indices.astype(dtypes.convert_dtype(index_dtype))
    out = SparseCooTensor(jsparse.BCOO((data, idx), shape=m.shape))
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) else out


# -- binary / reductions / structure -----------------------------------------

def subtract(x, y, name=None):
    if is_sparse(y):
        return add(x, neg(y))
    return Tensor(x.to_dense()._data - _as_array(y))


def divide(x, y, name=None):
    """Elementwise divide. Sparse ÷ dense divides the stored values by
    the dense entries at their coordinates (pattern preserved); sparse ÷
    sparse requires matching (coalesced) patterns — the reference's
    same-pattern contract."""
    m = _coo(x)._m.sum_duplicates()
    if is_sparse(y):
        ym = _coo(y)._m.sum_duplicates()
        if m.indices.shape != ym.indices.shape or \
                bool((m.indices != ym.indices).any()):
            raise ValueError("sparse.divide needs identical sparsity "
                             "patterns (coalesce first)")
        vals = m.data / ym.data
    else:
        d = _as_array(y)
        vals = m.data / d[tuple(m.indices[:, i]
                                for i in range(m.indices.shape[1]))]
    out = SparseCooTensor(jsparse.BCOO((vals, m.indices), shape=m.shape))
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) else out


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    """paddle.sparse.sum — dense scalar for axis=None, else a sparse
    tensor with the axis reduced."""
    m = _coo(x)._m
    data = m.data if dtype is None else \
        m.data.astype(dtypes.convert_dtype(dtype))
    if axis is None:
        out = data.sum()
        return Tensor(out[None] if keepdim else out)
    dense = jsparse.BCOO((data, m.indices), shape=m.shape).todense()
    red = dense.sum(axis=axis, keepdims=keepdim)
    nse = int((red != 0).sum())
    out = SparseCooTensor(jsparse.bcoo_fromdense(red, nse=max(nse, 1)))
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) \
        and out._m.ndim == 2 else out


def transpose(x, perm, name=None):
    m = _coo(x)._m
    out = SparseCooTensor(jsparse.bcoo_transpose(
        m, permutation=tuple(int(p) for p in perm)))
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) else out


def reshape(x, shape, name=None):
    m = _coo(x)._m
    shape = tuple(int(s) for s in shape)
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        total = int(np.prod(m.shape))
        shape = tuple(total // known if s == -1 else s for s in shape)
    out = SparseCooTensor(jsparse.bcoo_reshape(m, new_sizes=shape))
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) else out


def coalesce(x, name=None):
    return _coo(x).coalesce()


def is_same_shape(x, y, name=None):
    sx = x.shape if is_sparse(x) else list(_as_array(x).shape)
    sy = y.shape if is_sparse(y) else list(_as_array(y).shape)
    return list(sx) == list(sy)


def mask_as(x, mask, name=None):
    """Sample dense ``x`` at ``mask``'s sparsity pattern (reference
    ``paddle.sparse.mask_as``)."""
    xa = _as_array(x)
    m = _coo(mask)._m
    vals = xa[tuple(m.indices[:, i] for i in range(m.indices.shape[1]))]
    out = SparseCooTensor(jsparse.BCOO((vals, m.indices), shape=m.shape))
    return out.to_sparse_csr() if isinstance(mask, SparseCsrTensor) else out


def slice(x, axes, starts, ends, name=None):
    m = _coo(x)._m
    dense = m.todense()
    # build python slices explicitly (the name `slice` is shadowed here)
    import builtins
    sl = [builtins.slice(None)] * dense.ndim
    for ax, st, en in zip(axes, starts, ends):
        sl[int(ax)] = builtins.slice(int(st), int(en))
    sub = dense[tuple(sl)]
    nse = int((sub != 0).sum())
    out = SparseCooTensor(jsparse.bcoo_fromdense(sub, nse=max(nse, 1)))
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) else out


def mv(x, vec, name=None):
    """sparse matrix × dense vector → dense vector."""
    return matmul(x, vec)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """input + alpha·(x @ y) with a sparse ``x`` (dense result)."""
    prod = matmul(x, y)
    base = input.to_dense() if is_sparse(input) else \
        (input if isinstance(input, Tensor) else Tensor(_as_array(input)))
    return Tensor(beta * base._data + alpha * prod._data)


def _sparse_attention_impl(query, key, value, sparse_mask):
    """paddle.sparse.nn.functional.attention — attention restricted to
    ``sparse_mask``'s nonzero pattern (reference: the sparse-attention
    phi kernel over CSR masks). TPU tier: dense QK^T with the pattern
    applied as an additive mask — the MXU has no sparse systolic path,
    so this mirrors the reference's cuSPARSE-fallback semantics while
    keeping O(s²) compute on the MXU's fast path."""
    q = _as_array(query)
    k = _as_array(key)
    v = _as_array(value)
    b, h, s, d = q.shape
    m = _coo(sparse_mask)._m if is_sparse(sparse_mask) else None
    lg = jnp.einsum("bhqd,bhkd->bhqk", q, k) / (d ** 0.5)
    if m is not None:
        dense_mask = m.todense()
        dense_mask = dense_mask.reshape(b, h, s, s)
        lg = jnp.where(dense_mask != 0, lg, -1e30)
    w = jax.nn.softmax(lg, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", w, v)
    return Tensor(out)


class _SparseConvBase:
    """Shared machinery for sparse 3-D convs (reference:
    ``phi/kernels/sparse/conv_kernel``): correctness-first dense conv on
    the gathered voxels — XLA runs the conv on the MXU; the sparse win
    on TPU is memory (COO storage), not compute."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, subm=False):
        from ..nn.initializer import XavierUniform
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) \
            else (kernel_size,) * 3
        self.kernel_size = tuple(int(x) for x in ks)
        self.stride = stride if isinstance(stride, (list, tuple)) \
            else (stride,) * 3
        self.padding = padding if isinstance(padding, (list, tuple)) \
            else (padding,) * 3
        self.subm = subm
        from ..framework.core import Parameter
        self.weight = Parameter(XavierUniform()(
            self.kernel_size + (in_channels, out_channels), "float32"))

    def parameters(self):
        return [self.weight]

    def __call__(self, x):
        # x: SparseCooTensor [N, D, H, W, C] (paddle sparse conv layout)
        dense = _coo(x)._m.todense()
        pad = [(0, 0)] + [(p, p) for p in self.padding] + [(0, 0)]
        out = jax.lax.conv_general_dilated(
            dense.astype(jnp.float32), self.weight._data,
            window_strides=tuple(self.stride),
            padding=pad[1:4],
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.subm:
            # submanifold: output pattern == input pattern (per-voxel)
            in_pat = (jnp.abs(dense).sum(-1, keepdims=True) != 0)
            out = jnp.where(in_pat, out, 0.0)
        nse = int((jnp.abs(out).sum(-1) != 0).sum()) * out.shape[-1]
        bc = jsparse.bcoo_fromdense(out, nse=max(nse, 1))
        return SparseCooTensor(bc)


class nn:
    """paddle.sparse.nn — sparse layers/activations (subset)."""

    class ReLU:
        def __call__(self, x):
            return relu(x)

    class Conv3D(_SparseConvBase):
        """paddle.sparse.nn.Conv3D over SparseCooTensor [N,D,H,W,C]."""

        def __init__(self, in_channels, out_channels, kernel_size,
                     stride=1, padding=0, **kw):
            super().__init__(in_channels, out_channels, kernel_size,
                             stride, padding, subm=False)

    class SubmConv3D(_SparseConvBase):
        """Submanifold sparse conv: output sparsity == input sparsity."""

        def __init__(self, in_channels, out_channels, kernel_size,
                     stride=1, padding=0, **kw):
            super().__init__(in_channels, out_channels, kernel_size,
                             stride, padding, subm=True)

    class functional:
        attention = staticmethod(_sparse_attention_impl)
        relu = staticmethod(relu)


def softmax(x, axis=-1, name=None):
    """Pattern-restricted softmax (reference:
    ``paddle.sparse.nn.functional.softmax`` / phi sparse softmax):
    normalizes over the STORED entries of each row; the zero pattern is
    preserved."""
    m = _coo(x)._m
    if axis not in (-1, len(m.shape) - 1):
        raise NotImplementedError("sparse.softmax supports the last axis")
    dense = m.todense()
    # mask non-stored entries with -inf, softmax, then re-gather values
    mask = jnp.zeros(m.shape, bool).at[tuple(m.indices.T)].set(True)
    z = jnp.where(mask, dense, -jnp.inf)
    sm = jax.nn.softmax(z, axis=-1)
    vals = sm[tuple(m.indices.T)]
    out = SparseCooTensor(jsparse.BCOO((vals, m.indices), shape=m.shape))
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) else out
